"""Reflector-based API client: list at a resourceVersion, watch, relist
on 410 Gone, and fan events out to handlers.

client-go's machinery mapped onto this build:

  * ``Reflector``      — ListAndWatch (tools/cache/reflector.go:340): one
    thread per resource, initial list at the server's rv, incremental
    watch from it, full relist when the server compacts past our rv
    (410 Gone) or the connection drops;
  * informer store     — uid→object map; a relist DIFFS against it and
    synthesizes add/update/delete deltas (DeltaFIFO Replace semantics,
    shared_informer.go:459), so crash recovery rebuilds downstream state
    without phantom or lost objects;
  * ``RemoteClusterSource`` — the scheduler-facing facade with the same
    connect() surface as the in-proc FakeCluster: handlers in, binding/
    eviction/status writes out (clientset REST calls).
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
import urllib.error
import urllib.parse
import urllib.request
from typing import Callable, Dict, List, Optional
from urllib.parse import quote

from kubernetes_tpu.api.codec import decode, encode
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.client import wire_codec


class ApiError(RuntimeError):
    def __init__(self, code: int, msg: str, body=None):
        super().__init__(f"HTTP {code}: {msg}")
        self.code = code
        # the parsed error payload when the response carried one (e.g. a
        # binding 409's {"error", "node"}) — what lets bind() distinguish
        # conflict-on-retry from a real double-bind
        self.body = body


class ApiClient:
    """Thin REST client (the generated clientset analogue).  Requests ride
    a THREAD-LOCAL keep-alive connection — per-request TCP setup halves
    full-stack throughput at kubemark scale (client-go pools HTTP/2
    streams for the same reason).

    ``codec`` picks the wire format for requests, responses, and watch
    streams: "binary" (the default — the serving tier's hot path rides
    client/wire_codec.py frames) or "json" (the server's debug default;
    what a codec-less client gets).  Decoded structures are identical
    either way, so everything above ``_req``/``watch_stream`` is
    codec-blind."""

    def __init__(
        self,
        endpoint: str,
        timeout: float = 10.0,
        watch_timeout: Optional[float] = None,
        codec: str = "binary",
    ):
        self.endpoint = endpoint.rstrip("/")
        self.timeout = timeout
        # watch-stream read timeout (None → max(timeout, 30), the historical
        # default).  The reflector treats an expiry as a clean EOF and
        # re-watches at its current rv — the reference's client-side watch
        # timeout behavior (reflector.go timeoutSeconds), so a quiet stream
        # cycles cheaply instead of surfacing as an error + relist.
        self.watch_timeout = watch_timeout
        if codec not in ("json", "binary"):
            raise ValueError(f"codec must be 'json' or 'binary', got {codec!r}")
        self.codec = codec
        parsed = urllib.parse.urlparse(self.endpoint)
        self._host = parsed.hostname or "127.0.0.1"
        self._port = parsed.port or 80
        self._local = threading.local()

    def _conn(self, fresh: bool = False):
        conn = getattr(self._local, "conn", None)
        if conn is None or fresh:
            if conn is not None:
                conn.close()
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self.timeout
            )
            # Nagle + delayed-ACK stalls every header/body write pair by
            # ~40ms — fatal for per-pod request rates (client-go rides
            # HTTP/2 streams where this never applies)
            conn.connect()
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            self._local.conn = conn
        return conn

    @staticmethod
    def _parse(body: bytes, ctype: str):
        """Response body → value, by the Content-Type the server chose
        (robust to a server that negotiated differently than asked)."""
        if not body:
            return {}
        if wire_codec.CT_BINARY in ctype:
            return wire_codec.decode_frame(body)[0]
        return json.loads(body)

    def _req(self, method: str, path: str, payload=None):
        binary = self.codec == "binary"
        ctype = wire_codec.CT_BINARY if binary else "application/json"
        if payload is None:
            data = None
        elif binary:
            data = wire_codec.encode_frame(payload)
        else:
            data = json.dumps(payload).encode()
        headers = {"Content-Type": ctype, "Accept": ctype}
        # Transport-level failures (keep-alive gone stale, backlog
        # overflow RST during bursts) retry on a fresh connection with
        # backoff — client-go's rest client does the same; API-level
        # errors surface immediately.
        last: Exception = RuntimeError("unreachable")
        for attempt in range(4):
            try:
                conn = self._conn(fresh=attempt > 0)
                conn.request(method, path, body=data, headers=headers)
                resp = conn.getresponse()
                body = resp.read() or b""
                resp_ct = resp.getheader("Content-Type") or ""
                if resp.status >= 400:
                    try:
                        parsed = self._parse(body, resp_ct)
                    except Exception:  # noqa: BLE001 — opaque error body
                        parsed = None
                    msg = (
                        json.dumps(parsed)
                        if wire_codec.CT_BINARY in resp_ct and parsed is not None
                        else body.decode(errors="replace")
                    )
                    raise ApiError(
                        resp.status,
                        msg,
                        body=parsed if isinstance(parsed, dict) else None,
                    )
                return self._parse(body, resp_ct)
            except ApiError:
                raise
            except (ConnectionError, OSError, http.client.HTTPException) as e:
                last = e
                import time as _time

                _time.sleep(0.05 * (2**attempt))
        raise last

    # reads
    def list(self, resource: str) -> dict:
        return self._req("GET", f"/api/v1/{resource}")

    # writes
    def create_node(self, node: Node) -> None:
        self._req("POST", "/api/v1/nodes", encode(node))

    def update_node(self, node: Node) -> None:
        self._req("PUT", f"/api/v1/nodes/{quote(node.name, safe='')}", encode(node))

    def delete_node(self, name: str) -> None:
        self._req("DELETE", f"/api/v1/nodes/{quote(name, safe='')}")

    def create_pod(self, pod: Pod) -> None:
        self._req("POST", "/api/v1/pods", encode(pod))

    def delete_pod(self, uid: str) -> None:
        self._req("DELETE", f"/api/v1/pods/{quote(uid, safe='')}")

    def create_nodes(self, nodes) -> None:
        """Bulk node create — one request; raises on any per-item error."""
        out = self._req(
            "POST", "/api/v1/nodes", {"items": [encode(n) for n in nodes]}
        )
        errs = [r for r in out.get("results", []) if r is not None]
        if errs:
            raise ApiError(409, f"{len(errs)} bulk create conflicts: {errs[:3]}")

    def create_pods(self, pods) -> None:
        """Bulk pod create — one request; raises on any per-item error."""
        out = self._req(
            "POST", "/api/v1/pods", {"items": [encode(p) for p in pods]}
        )
        errs = [r for r in out.get("results", []) if r is not None]
        if errs:
            raise ApiError(409, f"{len(errs)} bulk create conflicts: {errs[:3]}")

    def bind(self, pod: Pod, node_name: str) -> None:
        try:
            self._req(
                "POST",
                f"/api/v1/pods/{quote(pod.uid, safe='')}/binding",
                {"node": node_name},
            )
        except ApiError as e:
            # Idempotent retry: ``_req`` re-sends a binding POST whose
            # response was lost after the server applied it.  A 409 whose
            # recorded binding MATCHES the requested node is that retry
            # observing its own first attempt — success, not conflict
            # (assignPod's same-node CAS is a no-op for the same reason).
            if (
                e.code == 409
                and isinstance(e.body, dict)
                and e.body.get("node") == node_name
            ):
                return
            raise

    def bind_many(self, items) -> List[Optional[str]]:
        """Bulk bindings: items is [(pod, node_name), ...]; returns a
        per-item error message or None, aligned with the input.  The
        binding subresource is per-pod in the reference (storage.go:169) —
        the batch-first rebuild extends it so a drain's worth of bindings
        rides one request instead of one per pod."""
        payload = {
            "items": [
                {"uid": pod.uid, "node": node} for pod, node in items
            ]
        }
        out = self._req("POST", "/api/v1/bindings", payload)
        results = out.get("results", [None] * len(items))
        wanted = [node for _, node in items]
        return [
            None
            if r is None
            # conflict-on-retry (see bind()): the recorded binding already
            # matches what this item asked for — success, not an error
            or (r.get("code") == 409 and r.get("node") == want)
            else f"HTTP {r.get('code')}: {r.get('error')}"
            for r, want in zip(results, wanted)
        ]

    def patch_pod_status(self, pod: Pod) -> None:
        self._req(
            "PATCH",
            f"/api/v1/pods/{quote(pod.uid, safe='')}/status",
            {"nominatedNodeName": pod.nominated_node_name},
        )

    def patch_pod_phase(self, uid: str, phase: str) -> None:
        """Pod phase write (the kubelet's status report, e.g. Running)."""
        self._req(
            "PATCH",
            f"/api/v1/pods/{quote(uid, safe='')}/status",
            {"phase": phase},
        )

    def patch_node_taints(
        self, name: str, add=(), remove_keys=(), ready=None
    ) -> None:
        """Atomic server-side taint/readiness patch (the node-lifecycle
        controller's write shape — full-object PUTs would race kubelet
        heartbeats since nodes carry no resourceVersion)."""
        body = {
            "addTaints": [
                {"key": t.key, "value": t.value, "effect": t.effect}
                for t in add
            ],
            "removeTaintKeys": list(remove_keys),
        }
        if ready is not None:
            body["ready"] = ready
        self._req(
            "PATCH", f"/api/v1/nodes/{quote(name, safe='')}", body
        )

    def patch_node_status(self, name: str, ready: bool, heartbeat: float) -> None:
        """The kubelet heartbeat (node status subresource write)."""
        self._req(
            "PATCH",
            f"/api/v1/nodes/{quote(name, safe='')}/status",
            {"ready": ready, "lastHeartbeat": heartbeat},
        )

    def watch_stream(self, resource: str, rv: int):
        """Yields decoded watch events; raises ApiError(410) on
        compaction, StopIteration/return on clean EOF.  The event dicts
        are codec-identical: {"type", "rv", "object"} whether the stream
        carried JSON lines or binary frames — the Reflector (and the
        chaos proxy wrapping this method) never sees the difference."""
        binary = self.codec == "binary"
        req = urllib.request.Request(
            f"{self.endpoint}/api/v1/{resource}?watch=1&resourceVersion={rv}",
            headers={"Accept": wire_codec.CT_BINARY} if binary else {},
        )
        read_timeout = (
            self.watch_timeout
            if self.watch_timeout is not None
            else max(self.timeout, 30)
        )
        with urllib.request.urlopen(req, timeout=read_timeout) as resp:
            if binary:
                while True:
                    evt = wire_codec.read_frame(resp)
                    if evt is None:
                        return  # clean EOF or cut mid-frame: re-watch
                    if evt.get("type") == "ERROR" and evt.get("code") == 410:
                        raise ApiError(410, "resourceVersion compacted")
                    yield evt
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                evt = json.loads(line)
                if evt.get("type") == "ERROR" and evt.get("code") == 410:
                    raise ApiError(410, "resourceVersion compacted")
                yield evt


class RemoteLeaseStore:
    """The LeaseStore get/update surface over the API server's
    /api/v1/leases resource — what lets two real scheduler PROCESSES elect
    through one control plane (resourcelock/leaselock.go's role).  CAS
    conflicts (409) surface as update() → False; transport errors also
    count as failed attempts so the elector just retries next period."""

    def __init__(self, client: ApiClient):
        self.client = client

    def get(self, name: str):
        from kubernetes_tpu.util.leases import lease_from_wire

        try:
            d = self.client._req(
                "GET", f"/api/v1/leases/{quote(name, safe='')}"
            )
        except ApiError as e:
            if e.code == 404:
                return None
            raise
        return lease_from_wire(d)

    def update(self, name: str, rec) -> bool:
        from kubernetes_tpu.util.leases import lease_to_wire

        try:
            self.client._req(
                "PUT",
                f"/api/v1/leases/{quote(name, safe='')}",
                lease_to_wire(rec),
            )
            return True
        except ApiError as e:
            if e.code == 409:
                return False
            raise


def _key_of(obj) -> str:
    return obj.uid if isinstance(obj, Pod) else obj.name


class Reflector:
    """ListAndWatch for one resource with an informer store + diffs."""

    def __init__(
        self,
        client: ApiClient,
        resource: str,
        on_add: Callable,
        on_update: Callable,
        on_delete: Callable,
    ):
        self.client = client
        self.resource = resource
        self.on_add = on_add
        self.on_update = on_update
        self.on_delete = on_delete
        self.store: Dict[str, object] = {}
        self.rv = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.synced = threading.Event()
        self.relists = 0
        self.watch_timeouts = 0  # idle read expiries re-watched without relist
        # optional ControlPlaneMonitor (observability/controlplane.py),
        # set via RemoteClusterSource → monitor.attach_source: stamps the
        # watch_delivery hop + newest-delivered clock per decoded event.
        # One attribute read + branch when unwired.
        self.cp = None

    # ----- list + diff (DeltaFIFO Replace) ---------------------------------

    def _relist(self) -> None:
        payload = self.client.list(self.resource)
        fresh = {}
        for envelope in payload["items"]:
            obj = decode(envelope)
            fresh[_key_of(obj)] = obj
        old = self.store
        for key, obj in fresh.items():
            if key not in old:
                self.on_add(obj)
            elif old[key] != obj:
                self.on_update(old[key], obj)
        for key, obj in old.items():
            if key not in fresh:
                self.on_delete(obj)
        self.store = fresh
        self.rv = payload["resourceVersion"]
        self.relists += 1
        self.synced.set()

    def _apply(self, etype: str, obj) -> None:
        key = _key_of(obj)
        if etype == "ADDED":
            prior = self.store.get(key)
            self.store[key] = obj
            if prior is None:
                self.on_add(obj)
            elif prior != obj:
                self.on_update(prior, obj)
        elif etype == "MODIFIED":
            prior = self.store.get(key)
            self.store[key] = obj
            if prior is None:
                self.on_add(obj)
            elif prior != obj:
                self.on_update(prior, obj)
        elif etype == "DELETED":
            prior = self.store.pop(key, None)
            if prior is not None:
                self.on_delete(prior)

    # ----- the loop ---------------------------------------------------------

    def run_once(self) -> None:
        """One ListAndWatch cycle; returns on stream end or 410.

        An idle READ TIMEOUT is a clean EOF, not an error: the store is
        consistent up to ``self.rv``, so the watch reopens at that rv
        without the full relist a transport error forces (reflector.go's
        client-side timeoutSeconds behavior)."""
        self._relist()
        while not self._stop.is_set():
            try:
                for evt in self.client.watch_stream(self.resource, self.rv):
                    if self._stop.is_set():
                        return
                    if evt.get("type") == "BOOKMARK":
                        continue
                    self.rv = evt["rv"]
                    obj = decode(evt["object"])
                    cp = self.cp
                    if cp is not None and cp.enabled:
                        cp.note_delivery(self.resource, evt["rv"], obj)
                    self._apply(evt["type"], obj)
                return  # server closed the stream: caller relists
            except ApiError as e:
                if e.code != 410:
                    raise
                return  # compaction: fall through — the caller relists
            except (socket.timeout, TimeoutError):
                # quiet stream outlived the read timeout; re-watch at the
                # current rv
                self.watch_timeouts += 1
                continue

    def start(self) -> "Reflector":
        def loop():
            while not self._stop.is_set():
                try:
                    self.run_once()
                except Exception:  # noqa: BLE001 — reconnect with backoff
                    if self._stop.wait(0.2):
                        return

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)


class RemoteClusterSource:
    """The scheduler's ClusterSource over HTTP — same connect() shape as
    the in-proc FakeCluster (testing/fake_cluster.py), so `server.py
    --api-endpoint` swaps the wire tier in without touching the core."""

    def __init__(
        self,
        endpoint: str,
        client: Optional[ApiClient] = None,
        codec: str = "binary",
    ):
        # an injected client (e.g. the chaos subsystem's fault-wrapping
        # ChaosClient) rides the whole tier: reflector streams, bindings,
        # status writes — and carries its own codec
        self.client = client or ApiClient(endpoint, codec=codec)
        # SHARED informers (one list/watch stream per resource, any number
        # of consumers + named indexes — shared_informer.go:459); the
        # scheduler registers as the first consumer, debuggers/metrics
        # join via .informers without a second watch stream
        self.informers: Dict[str, SharedInformer] = {
            "nodes": SharedInformer(self.client, "nodes"),
            "pods": SharedInformer(self.client, "pods"),
        }
        # registered EAGERLY: lazy registration would take the delivery
        # lock on first query, inverting lock order against a caller that
        # holds a handler-side lock (deadlock); per-event upkeep is two
        # dict ops
        self.informers["pods"].add_indexer("node", pods_by_node_indexer)
        self._connected = False

    def pods_by_node(self, node_name: str):
        """Assigned pods on one node via the shared informer's index
        (index reads take only the index lock — safe from any thread)."""
        return self.informers["pods"].by_index("node", node_name)

    def connect(self, scheduler) -> None:
        if self._connected:
            raise RuntimeError(
                "RemoteClusterSource.connect called twice — handler sets "
                "accumulate on the shared informers; build a fresh source "
                "per scheduler instead"
            )
        self._connected = True
        if getattr(scheduler, "event_broadcaster", None) is not None:
            # events currently stay process-local (an events API write
            # sink would slot in here)
            pass
        self.informers["nodes"].add_handlers(
            scheduler.on_node_add,
            scheduler.on_node_update,
            scheduler.on_node_delete,
        )
        self.informers["pods"].add_handlers(
            scheduler.on_pod_add,
            scheduler.on_pod_update,
            scheduler.on_pod_delete,
        )
        scheduler.binding_sink = self.client.bind
        scheduler.binding_sink_many = self.client.bind_many
        scheduler.pod_deleter = lambda pod: self.client.delete_pod(pod.uid)
        scheduler.status_patcher = self.client.patch_pod_status

    def start(self) -> "RemoteClusterSource":
        for inf in self.informers.values():
            inf.start()
        return self

    def wait_for_sync(self, timeout: float = 10.0) -> bool:
        return all(
            inf.synced.wait(timeout) for inf in self.informers.values()
        )

    def stop(self) -> None:
        for inf in self.informers.values():
            inf.stop()


class SharedInformer:
    """SharedIndexInformer's fan-out + indexer surface
    (tools/cache/shared_informer.go:459): ONE Reflector list/watch stream
    feeds any number of registered handler sets, and maintains named
    INDEXES over the store (e.g. pods-by-node) that consumers query
    instead of scanning — the reference narrows AssignedPodAdded requeue
    work through exactly such indexers (backend/queue/
    scheduling_queue.go:964-1135).

    Handlers added AFTER start receive synthetic ADDs for the current
    store contents (the informer's replay-on-join contract)."""

    def __init__(self, client: ApiClient, resource: str):
        self.resource = resource
        self._handlers: List[tuple] = []  # (on_add, on_update, on_delete)
        self._indexers: Dict[str, Callable] = {}
        self._indexes: Dict[str, Dict[str, Dict[str, object]]] = {}
        # _mu guards the index tables (by_index readers); _delivery_mu
        # serializes {own-store update + index update + handler delivery}
        # against join replays — the two-lock split keeps by_index safe to
        # call from threads that hold locks the handlers also take
        self._mu = threading.Lock()
        self._delivery_mu = threading.RLock()
        self._store: Dict[str, object] = {}  # delivery-consistent mirror
        self._reflector = Reflector(
            client,
            resource,
            self._on_add,
            self._on_update,
            self._on_delete,
        )

    # ----- indexers ---------------------------------------------------------

    def add_indexer(self, name: str, key_fn: Callable) -> None:
        """key_fn(obj) → index key or None (unindexed)."""
        with self._delivery_mu:
            snapshot = list(self._store.values())
            with self._mu:
                self._indexers[name] = key_fn
                idx: Dict[str, Dict[str, object]] = {}
                for obj in snapshot:
                    k = key_fn(obj)
                    if k is not None:
                        idx.setdefault(k, {})[_key_of(obj)] = obj
                self._indexes[name] = idx

    def by_index(self, name: str, key: str) -> List[object]:
        """Objects whose index key matches — O(bucket), not O(store)."""
        with self._mu:
            return list(self._indexes.get(name, {}).get(key, {}).values())

    def _index_add(self, obj) -> None:
        with self._mu:
            for name, fn in self._indexers.items():
                k = fn(obj)
                if k is not None:
                    self._indexes[name].setdefault(k, {})[_key_of(obj)] = obj

    def _index_remove(self, obj) -> None:
        with self._mu:
            for name, fn in self._indexers.items():
                k = fn(obj)
                if k is not None:
                    bucket = self._indexes[name].get(k)
                    if bucket is not None:
                        bucket.pop(_key_of(obj), None)
                        if not bucket:
                            del self._indexes[name][k]

    # ----- fan-out ----------------------------------------------------------

    def add_handlers(self, on_add, on_update, on_delete) -> None:
        """Join the stream.  The replay happens under the DELIVERY lock
        against the delivery-consistent store mirror, so a late joiner can
        neither miss an object, see one twice, nor resurrect a concurrent
        delete (the delta-queue sequencing client-go gets for free)."""
        with self._delivery_mu:
            for obj in self._store.values():
                on_add(obj)
            self._handlers.append((on_add, on_update, on_delete))

    def _on_add(self, obj) -> None:
        with self._delivery_mu:
            self._store[_key_of(obj)] = obj
            self._index_add(obj)
            for add, _, _ in self._handlers:
                add(obj)

    def _on_update(self, old, new) -> None:
        with self._delivery_mu:
            self._store[_key_of(new)] = new
            self._index_remove(old)
            self._index_add(new)
            for _, update, _ in self._handlers:
                update(old, new)

    def _on_delete(self, obj) -> None:
        with self._delivery_mu:
            self._store.pop(_key_of(obj), None)
            self._index_remove(obj)
            for _, _, delete in self._handlers:
                delete(obj)

    def start(self) -> "SharedInformer":
        self._reflector.start()
        return self

    @property
    def synced(self):
        return self._reflector.synced

    def stop(self) -> None:
        self._reflector.stop()


def pods_by_node_indexer(pod) -> Optional[str]:
    """The pods-by-node index key (assigned pods only)."""
    return pod.node_name or None
