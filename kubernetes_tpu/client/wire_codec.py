"""Binary wire codec for the list/watch/bind hot path.

The reference leans on protobuf precisely because JSON list/watch
dominates at scale (SURVEY L0-L4); this is the same move shrunk to the
repo's JSON-safe value domain.  One self-describing, length-prefixed
FRAME carries any structure the JSON tier carries, and decodes to the
IDENTICAL Python structure ``json.loads`` would have produced — so every
parity, journal-replay, and relist guarantee carries over unchanged and
a decoded object is byte-identical between codecs (same ``json.dumps``).

Frame layout (all integers big-endian):

    frame   := u32 body-length | body
    body    := value
    value   := 0x00                          # None
             | 0x01 | 0x02                   # False | True
             | 0x03 zigzag-varint            # int (unbounded)
             | 0x04 f64                      # float (8-byte IEEE double)
             | 0x05 varint utf8-bytes        # str, inline (registers in the
                                             #   frame's dynamic table)
             | 0x06 varint                   # str, STATIC table ref
             | 0x07 varint                   # str, dynamic table ref
             | 0x08 varint value*            # list  (count, then items)
             | 0x09 varint (value value)*    # dict  (count, then k/v pairs;
                                             #   keys are str values)
             | 0x0A varint body              # NESTED value: byte length +
                                             #   a self-contained body with
                                             #   its OWN dynamic table

STRING INTERNING is two-tier.  The STATIC table is baked into this
module — every dataclass field name reachable from the codec's KINDS
(the wire keys), the envelope/protocol keys, event types, and the common
label/taint vocabulary — so the strings that dominate Node/Pod payloads
cost one tag + one varint.  Anything else goes inline once per frame and
by dynamic back-reference after that (repeated label values, node names
in taint messages).  Both sides derive the static table from the same
``_build_static_table()``, so there is no negotiation of table versions:
the table is part of the content type.

The NESTED value (0x0A) is the ZERO-COPY seam: an object envelope is
encoded ONCE into a nested blob at watch-cache append time, and that
same blob is spliced verbatim into every watch event frame and every
binary list response (cacher.go keeps one encoded object per event for
the same reason).  A nested body carries its own dynamic table, so
splicing can never desynchronize the enclosing frame's table.

Content negotiation: clients send ``Accept``/``Content-Type`` of
``CT_BINARY``; the server answers in kind and keeps JSON the default for
anything that doesn't ask (curl debugging, the chaos journal's decoded
entries, old clients).  When JSON still wins: see WIRE.md.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Any, Dict, List, Optional, Tuple, get_args, get_type_hints

CT_JSON = "application/json"
CT_BINARY = "application/vnd.ktpu.wire+binary"

_TAG_NONE = 0x00
_TAG_FALSE = 0x01
_TAG_TRUE = 0x02
_TAG_INT = 0x03
_TAG_FLOAT = 0x04
_TAG_STR = 0x05
_TAG_SREF = 0x06
_TAG_DREF = 0x07
_TAG_LIST = 0x08
_TAG_DICT = 0x09
_TAG_NESTED = 0x0A

_U32 = struct.Struct("!I")
_F64 = struct.Struct("!d")

# Lock-discipline registry (kubernetes_tpu.analysis): the codec is PURE —
# the static table below is built once at import and never mutated, and
# every encoder/decoder carries its state in locals/instance fields owned
# by one call.  Registered empty so the checker vets any mutable state a
# future change introduces here (encoders ride apiserver handler threads,
# reflector threads, and the watch-cache append path concurrently).
# Plain assignment — analysis.core.module_literal reads ast.Assign only.
_KTPU_GUARDED = {}


# ---------------------------------------------------------------------------
# static intern table
# ---------------------------------------------------------------------------

# envelope / protocol keys and values the server's frames always carry
_PROTOCOL_STRINGS = (
    "kind",
    "object",
    "type",
    "rv",
    "items",
    "resourceVersion",
    "results",
    "error",
    "code",
    "ok",
    "node",
    "uid",
    "idempotent",
    "ADDED",
    "MODIFIED",
    "DELETED",
    "BOOKMARK",
    "ERROR",
)

# common label / taint / value vocabulary (the reference's well-known
# keys) — frames carrying them pay a ref, not the full string
_COMMON_STRINGS = (
    "app",
    "cpu",
    "memory",
    "pods",
    "kubernetes.io/hostname",
    "topology.kubernetes.io/zone",
    "topology.kubernetes.io/region",
    "node.kubernetes.io/not-ready",
    "node.kubernetes.io/unreachable",
    "node.kubernetes.io/unschedulable",
    "NoSchedule",
    "PreferNoSchedule",
    "NoExecute",
    "Exists",
    "Equal",
    "In",
    "NotIn",
    "DoNotSchedule",
    "ScheduleAnyway",
    "Honor",
    "Ignore",
    "TCP",
    "UDP",
    "Pending",
    "Running",
    "Always",
    "Never",
    "PreemptLowerPriority",
    "default",
    "default-scheduler",
)


def _collect_field_names(cls, seen: set, out: List[str]) -> None:
    """Every dataclass field name reachable from ``cls`` (the wire keys
    ``api.codec.to_wire`` emits), depth-first in declaration order —
    deterministic, so server and client derive the same table."""
    if not dataclasses.is_dataclass(cls) or cls in seen:
        return
    seen.add(cls)
    try:
        hints = get_type_hints(cls)
    except Exception:  # noqa: BLE001 — unresolvable forward ref: skip nest
        hints = {}
    for f in dataclasses.fields(cls):
        if f.name not in out:
            out.append(f.name)
        _walk_hint(hints.get(f.name), seen, out)


def _walk_hint(hint, seen: set, out: List[str]) -> None:
    if hint is None:
        return
    if dataclasses.is_dataclass(hint):
        _collect_field_names(hint, seen, out)
        return
    for a in get_args(hint):
        _walk_hint(a, seen, out)


def _build_static_table() -> Tuple[str, ...]:
    from kubernetes_tpu.api.codec import KINDS

    out: List[str] = list(_PROTOCOL_STRINGS)
    seen: set = set()
    for kind in sorted(KINDS):
        if kind not in out:
            out.append(kind)
        _collect_field_names(KINDS[kind], seen, out)
    for s in _COMMON_STRINGS:
        if s not in out:
            out.append(s)
    return tuple(out)


STATIC_STRINGS: Tuple[str, ...] = _build_static_table()
_STATIC_INDEX: Dict[str, int] = {s: i for i, s in enumerate(STATIC_STRINGS)}


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def _write_varint(out: List[bytes], n: int) -> None:
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(bytes((b | 0x80,)))
        else:
            out.append(bytes((b,)))
            return


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    n = 0
    while True:
        b = buf[pos]
        pos += 1
        n |= (b & 0x7F) << shift
        if not b & 0x80:
            return n, pos
        shift += 7


def _zigzag(n: int) -> int:
    return (n << 1) if n >= 0 else (-(n << 1) - 1)


def _unzigzag(z: int) -> int:
    return (z >> 1) if not z & 1 else -((z + 1) >> 1)


# ---------------------------------------------------------------------------
# encoder
# ---------------------------------------------------------------------------


class _Encoder:
    """One frame's encoding context (the dynamic string table is
    per-frame; a nested blob carries its own)."""

    def __init__(self):
        self.out: List[bytes] = []
        self.dynamic: Dict[str, int] = {}

    def value(self, v: Any) -> None:
        out = self.out
        if v is None:
            out.append(b"\x00")
        elif v is True:
            out.append(b"\x02")
        elif v is False:
            out.append(b"\x01")
        elif isinstance(v, int):
            out.append(b"\x03")
            _write_varint(out, _zigzag(v))
        elif isinstance(v, float):
            out.append(b"\x04")
            out.append(_F64.pack(v))
        elif isinstance(v, str):
            self.string(v)
        elif isinstance(v, (list, tuple)):
            out.append(b"\x08")
            _write_varint(out, len(v))
            for x in v:
                self.value(x)
        elif isinstance(v, dict):
            out.append(b"\x09")
            _write_varint(out, len(v))
            for k, x in v.items():
                if not isinstance(k, str):
                    raise TypeError(f"wire_codec: non-str dict key {k!r}")
                self.string(k)
                self.value(x)
        else:
            raise TypeError(f"wire_codec: unsupported {type(v)!r}")

    def string(self, s: str) -> None:
        out = self.out
        idx = _STATIC_INDEX.get(s)
        if idx is not None:
            out.append(b"\x06")
            _write_varint(out, idx)
            return
        idx = self.dynamic.get(s)
        if idx is not None:
            out.append(b"\x07")
            _write_varint(out, idx)
            return
        self.dynamic[s] = len(self.dynamic)
        raw = s.encode()
        out.append(b"\x05")
        _write_varint(out, len(raw))
        out.append(raw)

    def splice(self, nested_blob: bytes) -> None:
        """Append a pre-encoded NESTED blob (from ``encode_nested``) where
        a value is expected — the zero-copy path: the blob's own dynamic
        table means no re-encode and no table interaction."""
        self.out.append(nested_blob)

    def body(self) -> bytes:
        return b"".join(self.out)


def encode_value(v: Any) -> bytes:
    """Value → frame BODY bytes (no length prefix)."""
    enc = _Encoder()
    enc.value(v)
    return enc.body()


def encode_nested(v: Any) -> bytes:
    """Value → a NESTED blob: splice it into any frame via
    ``_Encoder.splice`` / the event and list assemblers below."""
    body = encode_value(v)
    out: List[bytes] = [b"\x0a"]
    _write_varint(out, len(body))
    out.append(body)
    return b"".join(out)


def encode_frame(v: Any) -> bytes:
    """Value → full length-prefixed frame."""
    body = encode_value(v)
    return _U32.pack(len(body)) + body


def encode_event(etype: str, rv: int, nested_obj: Optional[bytes]) -> bytes:
    """One watch event as a full frame:
    ``{"type": etype, "rv": rv, "object": <spliced blob>}`` — the blob is
    the object envelope encoded ONCE at watch-cache append time and
    shared across every watcher's stream and the binary list path."""
    enc = _Encoder()
    enc.out.append(b"\x09")
    _write_varint(enc.out, 3 if nested_obj is not None else 2)
    enc.string("type")
    enc.string(etype)
    enc.string("rv")
    enc.value(rv)
    if nested_obj is not None:
        enc.string("object")
        enc.splice(nested_obj)
    body = enc.body()
    return _U32.pack(len(body)) + body


def encode_list_frame(rv: int, nested_items: List[bytes]) -> bytes:
    """A binary list response as one full frame:
    ``{"resourceVersion": rv, "items": [<spliced blobs>]}`` — items are
    the per-object blobs maintained by the watch cache, NOT re-encoded
    per request (the JSON list path re-serializes the full object set on
    every call; this path just concatenates)."""
    enc = _Encoder()
    enc.out.append(b"\x09")
    _write_varint(enc.out, 2)
    enc.string("resourceVersion")
    enc.value(rv)
    enc.string("items")
    enc.out.append(b"\x08")
    _write_varint(enc.out, len(nested_items))
    for blob in nested_items:
        enc.splice(blob)
    body = enc.body()
    return _U32.pack(len(body)) + body


# ---------------------------------------------------------------------------
# decoder
# ---------------------------------------------------------------------------


def _decode(buf: bytes, pos: int, dynamic: List[str]) -> Tuple[Any, int]:
    tag = buf[pos]
    pos += 1
    if tag == _TAG_NONE:
        return None, pos
    if tag == _TAG_FALSE:
        return False, pos
    if tag == _TAG_TRUE:
        return True, pos
    if tag == _TAG_INT:
        z, pos = _read_varint(buf, pos)
        return _unzigzag(z), pos
    if tag == _TAG_FLOAT:
        return _F64.unpack_from(buf, pos)[0], pos + 8
    if tag == _TAG_STR:
        n, pos = _read_varint(buf, pos)
        s = buf[pos : pos + n].decode()
        dynamic.append(s)
        return s, pos + n
    if tag == _TAG_SREF:
        i, pos = _read_varint(buf, pos)
        return STATIC_STRINGS[i], pos
    if tag == _TAG_DREF:
        i, pos = _read_varint(buf, pos)
        return dynamic[i], pos
    if tag == _TAG_LIST:
        n, pos = _read_varint(buf, pos)
        out = []
        for _ in range(n):
            v, pos = _decode(buf, pos, dynamic)
            out.append(v)
        return out, pos
    if tag == _TAG_DICT:
        n, pos = _read_varint(buf, pos)
        d = {}
        for _ in range(n):
            k, pos = _decode(buf, pos, dynamic)
            v, pos = _decode(buf, pos, dynamic)
            d[k] = v
        return d, pos
    if tag == _TAG_NESTED:
        n, pos = _read_varint(buf, pos)
        v, _ = _decode(buf, pos, [])  # fresh table: self-contained blob
        return v, pos + n
    raise ValueError(f"wire_codec: bad tag 0x{tag:02x} at {pos - 1}")


def decode_value(body: bytes) -> Any:
    """Frame BODY bytes → value (the exact structure ``json.loads`` of
    the JSON encoding would produce)."""
    v, pos = _decode(body, 0, [])
    if pos != len(body):
        raise ValueError(
            f"wire_codec: {len(body) - pos} trailing bytes after value"
        )
    return v


def decode_frame(buf: bytes, offset: int = 0) -> Tuple[Any, int]:
    """One length-prefixed frame at ``offset`` → (value, next offset)."""
    (n,) = _U32.unpack_from(buf, offset)
    start = offset + 4
    return decode_value(buf[start : start + n]), start + n


def read_frame(stream) -> Optional[Any]:
    """Read one frame from a file-like stream (a dechunked HTTP response
    body).  Returns None on clean EOF — and on a connection cut mid-frame
    (truncated read), which the reflector handles exactly like a clean
    stream end: re-watch/relist from its current rv."""
    header = _read_exact(stream, 4)
    if header is None:
        return None
    (n,) = _U32.unpack(header)
    body = _read_exact(stream, n)
    if body is None:
        return None
    return decode_value(body)


def _read_exact(stream, n: int) -> Optional[bytes]:
    chunks: List[bytes] = []
    got = 0
    while got < n:
        chunk = stream.read(n - got)
        if not chunk:
            return None
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)
