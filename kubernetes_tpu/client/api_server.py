"""HTTP API server: list + watch over a FakeCluster store.

The serving half of the reference's storage stack, shrunk to the
scheduler-relevant surface:

  * per-resource WATCH CACHE — a sliding window of (rv, type, object)
    events (apiserver/pkg/storage/cacher: watch_cache.go's rolling window)
    so watchers resume from a resourceVersion without hitting the store;
    a request older than the window gets 410 Gone, triggering the
    client's relist (reflector.go:340);
  * GET  /api/v1/{nodes,pods}                  → {"resourceVersion", "items"}
  * GET  /api/v1/{res}?watch=1&resourceVersion=N → chunked JSON-lines stream
  * POST /api/v1/{nodes,pods}                  → create (bare object, or
    {"items": [...]} for a bulk create in one request)
  * PUT  /api/v1/nodes/{name}                  → update
  * DELETE /api/v1/{res}/{key}                 → delete
  * POST /api/v1/pods/{uid}/binding            → the binding subresource
    (registry/core/pod/storage/storage.go:169 assignPod)
  * POST /api/v1/bindings                      → BULK bindings ({"items":
    [{"uid","node"}]} → per-item results) — the batch-first extension of
    the per-pod subresource
  * PATCH /api/v1/pods/{uid}/status            → nominatedNodeName patches

Writes go through the wrapped FakeCluster so its watch fan-out, PV
controller, and binding semantics stay authoritative; this server records
the fan-out into the watch cache and serves it over the wire.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from kubernetes_tpu.api.codec import decode, encode
from kubernetes_tpu.api.types import Node, Pod

WATCH_WINDOW = 4096  # events kept per resource (watch_cache.go capacity)


class _WatchCache:
    """Sliding window of events with a condition for long-polling.

    Each event carries its WIRE BYTES (the JSON line), serialized once at
    record time — every watcher of every stream writes the same bytes, so
    per-watcher re-serialization would multiply encode cost by the watcher
    count (cacher.go keeps one encoded object per event the same way)."""

    def __init__(self, window: int = WATCH_WINDOW):
        self.events: Deque[Tuple[int, bytes]] = deque(maxlen=window)  # (rv, wire line)
        self.rv = 0
        self.cond = threading.Condition()
        # observability counters (controlplane tier scrapes deltas):
        # compactions that dropped events, and 410s served — always-on
        # plain ints under the cond, like rv
        self.compactions = 0
        self.gone_total = 0
        # active watcher registry: watcher id → last rv delivered to that
        # stream.  Registration/removal under the cond; the per-iteration
        # position update is a plain dict store (GIL-atomic) so the watch
        # loop never takes the lock just to report progress.
        self.watchers: Dict[int, int] = {}
        self._watcher_seq = 0

    def record(self, event_type: str, envelope: dict) -> int:
        with self.cond:
            self.rv += 1
            line = (
                json.dumps(
                    {"type": event_type, "rv": self.rv, "object": envelope}
                )
                + "\n"
            ).encode()
            self.events.append((self.rv, line))
            self.cond.notify_all()
            return self.rv

    def _stale(self, rv: int) -> bool:
        """rv precedes the retained window → the watcher must relist.

        With a NON-EMPTY window the oldest replayable position is
        events[0].rv - 1.  With an EMPTY window (deque wrap at maxlen 0
        during tests, explicit compaction, server restart) NOTHING is
        replayable, so any rv behind the head counter is stale — returning
        [] there would silently strand a watcher that can never catch up.
        """
        if self.events:
            return rv < self.events[0][0] - 1
        return rv < self.rv

    def since(self, rv: int, timeout: float) -> Optional[List[Tuple[int, bytes]]]:
        """Events with rv' > rv; None ⇒ rv fell out of the window (410)."""
        with self.cond:
            if self._stale(rv):
                self.gone_total += 1
                return None  # compacted away → 410 Gone
            out = [e for e in self.events if e[0] > rv]
            if out:
                return out
            self.cond.wait(timeout)
            if self._stale(rv):
                self.gone_total += 1
                return None
            return [e for e in self.events if e[0] > rv]

    def compact(self, keep: int = 0) -> None:
        """Drop all but the last ``keep`` retained events (the etcd
        compaction shape, on demand — the chaos runner's forced-410 lever).
        Wakes blocked watchers so stale ones see the 410 immediately."""
        with self.cond:
            if len(self.events) > keep:
                self.compactions += 1
            while len(self.events) > keep:
                self.events.popleft()
            self.cond.notify_all()


class ApiServer:
    def __init__(self, api, host: str = "127.0.0.1", port: int = 0):
        self.api = api
        self._mu = threading.Lock()
        # optional ControlPlaneMonitor (observability/controlplane.py),
        # set by monitor.attach_api_server: api-write breadcrumbs +
        # per-request accounting.  Every producer site gates on one
        # attribute read, so the unwired server pays a load + branch.
        self.cp = None
        self.caches: Dict[str, _WatchCache] = {
            "nodes": _WatchCache(),
            "pods": _WatchCache(),
        }
        # subscribe to the store's fan-out so every mutation (from any
        # client, or in-proc drivers) lands in the watch caches
        api.watch_nodes(
            lambda n: self._record("nodes", "ADDED", n),
            lambda old, new: self._record("nodes", "MODIFIED", new),
            lambda n: self._record("nodes", "DELETED", n),
        )
        api.watch_pods(
            lambda p: self._record("pods", "ADDED", p),
            lambda old, new: self._record("pods", "MODIFIED", new),
            lambda p: self._record("pods", "DELETED", p),
        )
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + the peer's delayed ACK turns every multi-write
            # response into a ~40ms stall on keep-alive connections —
            # fatal for per-pod request rates (kube-apiserver serves
            # HTTP/2 where this never applies).  StreamRequestHandler
            # applies this to the connection socket.
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # noqa: D401 — quiet
                pass

            # per-request accounting context, set by _begin at the top of
            # each verb handler and consumed by _json at response time
            _acct = None

            def _begin(self, verb: str) -> None:
                cp = server.cp
                if cp is None or not cp.enabled:
                    self._acct = None
                    return
                parts = [
                    p for p in urlparse(self.path).path.split("/") if p
                ]
                res = parts[2] if len(parts) >= 3 and parts[0] == "api" else (
                    parts[0] if parts else "other"
                )
                self._acct = (cp, verb, res, time.monotonic())

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                acct = self._acct
                if acct is not None:
                    self._acct = None
                    cp, verb, res, t0 = acct
                    cp.note_request(verb, res, code, time.monotonic() - t0)

            def do_GET(self):  # noqa: N802
                self._begin("GET")
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                if len(parts) == 4 and parts[:3] == ["api", "v1", "leases"]:
                    from kubernetes_tpu.util.leases import lease_to_wire

                    rec = server.api.lease_store.get(unquote(parts[3]))
                    if rec is None:
                        return self._json(404, {"error": "lease not found"})
                    return self._json(200, lease_to_wire(rec))
                if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                    res = parts[2]
                    if res not in server.caches:
                        return self._json(404, {"error": "unknown resource"})
                    if q.get("watch", ["0"])[0] in ("1", "true"):
                        return self._watch(res, int(q.get("resourceVersion", ["0"])[0]))
                    return self._json(200, server.list_payload(res))
                if parts == ["healthz"]:
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

            def _watch(self, res: str, rv: int) -> None:
                self._acct = None  # a stream, not a request latency
                cache = server.caches[res]
                # join the watcher registry: fanout lag is the cache head
                # rv minus this stream's delivered rv, scraped on demand
                with cache.cond:
                    cache._watcher_seq += 1
                    wid = cache._watcher_seq
                    cache.watchers[wid] = rv
                try:
                    self._watch_stream(cache, rv, wid)
                finally:
                    with cache.cond:
                        cache.watchers.pop(wid, None)

            def _watch_stream(self, cache, rv: int, wid: int) -> None:
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk_raw(data: bytes) -> bool:
                    try:
                        self.wfile.write(hex(len(data))[2:].encode() + b"\r\n")
                        self.wfile.write(data + b"\r\n")
                        self.wfile.flush()
                        return True
                    except (BrokenPipeError, ConnectionError, OSError):
                        return False

                def chunk(payload: dict) -> bool:
                    return chunk_raw((json.dumps(payload) + "\n").encode())

                while True:
                    events = cache.since(rv, timeout=0.5)
                    if events is None:
                        chunk({"type": "ERROR", "code": 410})
                        break
                    if not events:
                        if not chunk({"type": "BOOKMARK", "rv": rv}):
                            return
                        continue
                    # coalesced emission: ONE chunked frame carries every
                    # pending event's pre-serialized line — a burst of N
                    # events costs one write+flush instead of N
                    rv = events[-1][0]
                    cache.watchers[wid] = rv  # plain store — progress report
                    if not chunk_raw(b"".join(e[1] for e in events)):
                        return
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

            def do_POST(self):  # noqa: N802
                self._begin("POST")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if len(parts) == 3 and parts[2] in ("nodes", "pods"):
                    mk = (
                        server._create_node
                        if parts[2] == "nodes"
                        else server._create_pod
                    )
                    if isinstance(body, dict) and "items" in body:
                        # bulk create: per-item results (null = created/
                        # idempotent-ok) so conflicts inside a batch are
                        # never silently reported as created
                        results = []
                        for env in body["items"]:
                            code, payload = mk(decode(env))
                            results.append(None if code < 400 else payload)
                        n_err = sum(1 for r in results if r is not None)
                        return self._json(
                            207 if n_err else 201,
                            {"ok": n_err == 0, "results": results},
                        )
                    code, payload = mk(decode(body))
                    return self._json(code, payload)
                if len(parts) == 3 and parts[2] == "bindings":
                    # BULK binding write: the per-pod binding subresource
                    # semantics applied item-wise under the server lock —
                    # the batch-first extension of assignPod
                    # (storage.go:169); per-item statuses come back so the
                    # scheduler can unwind exactly the pods that failed
                    results = []
                    with server._mu:
                        for item in body.get("items", []):
                            uid = item.get("uid")
                            pod = server.api.pods.get(uid)
                            if pod is None:
                                results.append(
                                    {"code": 404, "error": f"pod {uid} not found"}
                                )
                                continue
                            try:
                                server.api.bind(pod, item["node"])
                                results.append(None)
                            except RuntimeError as e:
                                results.append({"code": 409, "error": str(e)})
                            except KeyError as e:
                                results.append({"code": 404, "error": str(e)})
                    return self._json(200, {"results": results})
                if len(parts) == 5 and parts[2] == "pods" and parts[4] == "binding":
                    uid = unquote(parts[3])
                    # check-and-bind under the server lock: concurrent
                    # binding POSTs (two active schedulers) must serialize,
                    # and store-level failures translate to API statuses
                    # like assignPod's CAS conflict (storage.go:254)
                    with server._mu:
                        pod = server.api.pods.get(uid)
                        if pod is None:
                            return self._json(
                                404, {"error": f"pod {uid} not found"}
                            )
                        # the store's CAS is the authority (assignPod,
                        # storage.go:254): a conflicting node → 409, a
                        # same-node rebind is idempotent — which makes the
                        # client's transport-level POST retry safe when the
                        # first attempt succeeded but the response was lost
                        try:
                            server.api.bind(pod, body["node"])
                        except RuntimeError as e:
                            return self._json(409, {"error": str(e)})
                        except KeyError as e:
                            return self._json(404, {"error": str(e)})
                    return self._json(201, {"ok": True})
                return self._json(404, {"error": "not found"})

            def do_PUT(self):  # noqa: N802
                self._begin("PUT")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if len(parts) == 4 and parts[2] == "nodes":
                    server.api.update_node(decode(body))
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "leases":
                    # Lease CAS (resourcelock/leaselock.go over the wire):
                    # stale resourceVersion → 409, the elector backs off
                    from kubernetes_tpu.util.leases import lease_from_wire

                    rec = lease_from_wire(body)
                    if server.api.lease_store.update(unquote(parts[3]), rec):
                        return self._json(
                            200,
                            {"ok": True, "resourceVersion": rec.resource_version + 1},
                        )
                    return self._json(409, {"error": "lease CAS conflict"})
                return self._json(404, {"error": "not found"})

            def do_PATCH(self):  # noqa: N802
                self._begin("PATCH")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                length = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(length) or b"{}")
                if len(parts) == 5 and parts[2] == "pods" and parts[4] == "status":
                    # read-modify-write under the server lock: concurrent
                    # status patches (nomination vs kubelet phase report)
                    # must not resurrect each other's stale fields
                    with server._mu:
                        uid = unquote(parts[3])
                        pod = server.api.pods.get(uid)
                        if pod is None:
                            return self._json(404, {"error": "not found"})
                        if "nominatedNodeName" in body or "phase" in body:
                            # never mutate the store's instance directly —
                            # the store computes its own old/new delta
                            import copy as _copy

                            patched = _copy.copy(pod)
                            if "nominatedNodeName" in body:
                                patched.nominated_node_name = body[
                                    "nominatedNodeName"
                                ]
                            if "phase" in body:
                                patched.phase = body["phase"]
                            server.api.patch_pod_status(patched)
                    return self._json(200, {"ok": True})
                if len(parts) == 5 and parts[2] == "nodes" and parts[4] == "status":
                    # the kubelet heartbeat write (node status subresource):
                    # Ready condition + lastHeartbeatTime — atomic RMW
                    # under the server lock so a concurrent taint PUT is
                    # never erased by a pre-taint copy
                    with server._mu:
                        name = unquote(parts[3])
                        node = server.api.nodes.get(name)
                        if node is None:
                            return self._json(404, {"error": "not found"})
                        import copy as _copy

                        patched = _copy.copy(node)
                        if "ready" in body:
                            patched.ready = bool(body["ready"])
                        if "lastHeartbeat" in body:
                            patched.last_heartbeat = float(body["lastHeartbeat"])
                        server.api.update_node(patched)
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "nodes":
                    # ATOMIC taint/readiness patch — the node-lifecycle
                    # controller's write shape.  Server-side RMW under the
                    # lock: the controller's view may be stale, but only
                    # the named taints/readiness change; heartbeats written
                    # concurrently are preserved (nodes carry no
                    # resourceVersion, so client-side full-object PUTs
                    # would silently regress them)
                    with server._mu:
                        name = unquote(parts[3])
                        node = server.api.nodes.get(name)
                        if node is None:
                            return self._json(404, {"error": "not found"})
                        import copy as _copy

                        from kubernetes_tpu.api.types import Taint

                        patched = _copy.copy(node)
                        remove = set(body.get("removeTaintKeys", []))
                        taints = tuple(
                            t for t in patched.taints if t.key not in remove
                        )
                        for t in body.get("addTaints", []):
                            if not any(x.key == t["key"] for x in taints):
                                taints = taints + (
                                    Taint(
                                        key=t["key"],
                                        value=t.get("value", ""),
                                        effect=t.get("effect", "NoSchedule"),
                                    ),
                                )
                        patched.taints = taints
                        if "ready" in body:
                            patched.ready = bool(body["ready"])
                        server.api.update_node(patched)
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

            def do_DELETE(self):  # noqa: N802
                self._begin("DELETE")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                if len(parts) == 4 and parts[2] == "pods":
                    server.api.delete_pod(unquote(parts[3]))
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "nodes":
                    server.api.delete_node(unquote(parts[3]))
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

        class _Server(ThreadingHTTPServer):
            # registration storms open many sockets faster than accept()
            # drains them while the scheduler compiles — the default
            # backlog of 5 RSTs the overflow
            request_queue_size = 256
            daemon_threads = True

        self.http = _Server((host, port), Handler)
        self.port = self.http.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ----- store access -----------------------------------------------------

    def _record(self, res: str, etype: str, obj) -> None:
        rv = self.caches[res].record(etype, encode(obj))
        cp = self.cp
        if cp is not None and cp.enabled:
            # the api_write breadcrumb: this event's rv + its watch-cache
            # entry time — the root of every pod's causal pipeline chain
            cp.note_api_write(res, rv, obj)

    # Creates are IDEMPOTENT for replays of the same SPEC (the client's
    # transport-level POST retry can re-send a create whose response was
    # lost — by then the server may already have written status fields)
    # and 409 AlreadyExists for conflicting specs — no duplicate ADDED
    # event ever reaches the watchers.
    @staticmethod
    def _spec_wire(obj, status_fields):
        d = dict(encode(obj))
        body = d.get("object", d)
        for f in status_fields:
            body.pop(f, None)
        return d

    def _create_node(self, node):
        status = ("ready", "lastHeartbeat", "last_heartbeat")
        with self._mu:
            cur = self.api.nodes.get(node.name)
            if cur is not None:
                if self._spec_wire(cur, status) == self._spec_wire(node, status):
                    return 200, {"ok": True, "idempotent": True}
                return 409, {"error": f"node {node.name} already exists"}
            self.api.create_node(node)
        return 201, {"ok": True}

    def _create_pod(self, pod):
        status = (
            "nodeName",
            "node_name",
            "phase",
            "nominatedNodeName",
            "nominated_node_name",
            "startTime",
            "start_time",
        )
        with self._mu:
            cur = self.api.pods.get(pod.uid)
            if cur is not None:
                if self._spec_wire(cur, status) == self._spec_wire(pod, status):
                    return 200, {"ok": True, "idempotent": True}
                return 409, {"error": f"pod {pod.uid} already exists"}
            self.api.create_pod(pod)
        return 201, {"ok": True}

    def list_payload(self, res: str) -> dict:
        """Consistent list: snapshot + the rv of the last event applied
        (reflector lists at this rv, then watches from it).  Only the
        snapshot + rv capture happens under the watch-cache lock; encoding
        10k objects there would stall every writer and watch fan-out for
        the duration (replayed events are idempotent on the client, so an
        event racing the encode is harmless)."""
        cache = self.caches[res]
        with cache.cond:
            # dict.copy() is atomic under the GIL — handler threads mutate
            # the store concurrently and bare .values() iteration would
            # raise "dictionary changed size during iteration"
            store = self.api.nodes if res == "nodes" else self.api.pods
            snapshot = store.copy()
            rv = cache.rv
        return {
            "resourceVersion": rv,
            "items": [encode(obj) for obj in snapshot.values()],
        }

    # ----- lifecycle --------------------------------------------------------

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self.http.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.http.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
