"""HTTP API server: list + watch over a FakeCluster store.

The serving half of the reference's storage stack, shrunk to the
scheduler-relevant surface:

  * per-resource WATCH CACHE — a sliding window of (rv, type, object)
    events (apiserver/pkg/storage/cacher: watch_cache.go's rolling window)
    so watchers resume from a resourceVersion without hitting the store;
    a request older than the window gets 410 Gone, triggering the
    client's relist (reflector.go:340);
  * GET  /api/v1/{nodes,pods}                  → {"resourceVersion", "items"}
  * GET  /api/v1/{res}?watch=1&resourceVersion=N → chunked JSON-lines stream
  * POST /api/v1/{nodes,pods}                  → create (bare object, or
    {"items": [...]} for a bulk create in one request)
  * PUT  /api/v1/nodes/{name}                  → update
  * DELETE /api/v1/{res}/{key}                 → delete
  * POST /api/v1/pods/{uid}/binding            → the binding subresource
    (registry/core/pod/storage/storage.go:169 assignPod)
  * POST /api/v1/bindings                      → BULK bindings ({"items":
    [{"uid","node"}]} → per-item results) — the batch-first extension of
    the per-pod subresource
  * PATCH /api/v1/pods/{uid}/status            → nominatedNodeName patches

Writes go through the wrapped FakeCluster so its watch fan-out, PV
controller, and binding semantics stay authoritative; this server records
the fan-out into the watch cache and serves it over the wire.

WIRE FORMAT is content-negotiated (see client/wire_codec.py + WIRE.md):
JSON is the default — a request carrying ``Accept:`` /
``Content-Type: application/vnd.ktpu.wire+binary`` rides the binary
codec instead, where every watch event is encoded ONCE at append time
and the same bytes are shared by every watcher and the list path.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Deque, Dict, List, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from kubernetes_tpu.api.codec import decode, encode
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.client import wire_codec

WATCH_WINDOW = 4096  # events kept per resource (watch_cache.go capacity)

# idle-watcher bookmark cadence: how long a stream sleeps ON THE CONDITION
# VARIABLE before emitting a progress BOOKMARK.  Event delivery never
# waits on this — record() notifies and the watcher wakes in microseconds;
# the interval only bounds how stale a quiet stream's rv report gets.
BOOKMARK_INTERVAL_S = 0.5


class _Event:
    """One recorded watch event.

    The BINARY frame is encoded ONCE at append time — every binary
    watcher of every stream writes the same bytes, and the nested object
    blob inside it is ALSO what the binary list path splices, so neither
    fanout nor list ever re-serializes (cacher.go keeps one encoded
    object per event the same way).  The JSON line is memoized lazily on
    first use: JSON is the debug default, not the hot path, so idle
    debug-format cost is zero.  The legacy ``(rv, line)`` tuple shape is
    preserved for existing callers that unpack or index."""

    __slots__ = ("rv", "etype", "envelope", "frame", "_line")

    def __init__(self, rv: int, etype: str, envelope: dict, frame: bytes):
        self.rv = rv
        self.etype = etype
        self.envelope = envelope
        self.frame = frame  # full binary event frame (shared, immutable)
        self._line: Optional[bytes] = None

    @property
    def json_line(self) -> bytes:
        line = self._line
        if line is None:
            # benign race: two threads may both serialize; same value,
            # single-store publish under the GIL
            line = self._line = (
                json.dumps(
                    {"type": self.etype, "rv": self.rv, "object": self.envelope}
                )
                + "\n"
            ).encode()
        return line

    def __iter__(self):
        return iter((self.rv, self.json_line))

    def __getitem__(self, i):
        return (self.rv, self.json_line)[i]


class _WatchCache:
    """Sliding window of events with condition-variable wakeup.

    Each event carries its WIRE BYTES, serialized once at record time
    (see ``_Event``); ``obj_frames`` keeps the latest nested object blob
    per store key so binary list responses splice instead of re-encoding
    the full object set per request."""

    def __init__(self, window: int = WATCH_WINDOW):
        self.events: Deque[_Event] = deque(maxlen=window)
        self.rv = 0
        self.cond = threading.Condition()
        # latest nested binary blob per object key (the encode-once side
        # of the binary LIST path), maintained under the cond in record()
        self.obj_frames: Dict[str, bytes] = {}
        # observability counters (controlplane tier scrapes deltas):
        # compactions that dropped events, and 410s served — always-on
        # plain ints under the cond, like rv
        self.compactions = 0
        self.gone_total = 0
        # active watcher registry: watcher id → last rv delivered to that
        # stream.  Registration/removal under the cond; the per-iteration
        # position update is a plain dict store (GIL-atomic) so the watch
        # loop never takes the lock just to report progress.
        self.watchers: Dict[int, int] = {}
        self._watcher_seq = 0

    def record(self, event_type: str, envelope: dict, key: Optional[str] = None) -> int:
        with self.cond:
            self.rv += 1
            nested = wire_codec.encode_nested(envelope)
            frame = wire_codec.encode_event(event_type, self.rv, nested)
            self.events.append(_Event(self.rv, event_type, envelope, frame))
            if key is not None:
                if event_type == "DELETED":
                    self.obj_frames.pop(key, None)
                else:
                    self.obj_frames[key] = nested
            self.cond.notify_all()
            return self.rv

    def _stale(self, rv: int) -> bool:
        """rv precedes the retained window → the watcher must relist.

        With a NON-EMPTY window the oldest replayable position is
        events[0].rv - 1.  With an EMPTY window (deque wrap at maxlen 0
        during tests, explicit compaction, server restart) NOTHING is
        replayable, so any rv behind the head counter is stale — returning
        [] there would silently strand a watcher that can never catch up.
        """
        if self.events:
            return rv < self.events[0].rv - 1
        return rv < self.rv

    def since(self, rv: int, timeout: float) -> Optional[List[_Event]]:
        """Events with rv' > rv; None ⇒ rv fell out of the window (410).

        Blocks on the condition variable until an event lands (record()
        notifies — an idle watcher adds microseconds of delivery latency,
        not a poll interval) or ``timeout`` elapses ([] ⇒ still idle; the
        caller emits a BOOKMARK).  The wait loops against spurious
        wakeups and concurrent consumers racing for the same notify."""
        deadline = time.monotonic() + timeout
        with self.cond:
            while True:
                if self._stale(rv):
                    self.gone_total += 1
                    return None  # compacted away → 410 Gone
                out = [e for e in self.events if e.rv > rv]
                if out:
                    return out
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self.cond.wait(remaining)

    def compact(self, keep: int = 0) -> None:
        """Drop all but the last ``keep`` retained events (the etcd
        compaction shape, on demand — the chaos runner's forced-410 lever).
        Wakes blocked watchers so stale ones see the 410 immediately."""
        with self.cond:
            if len(self.events) > keep:
                self.compactions += 1
            while len(self.events) > keep:
                self.events.popleft()
            self.cond.notify_all()


class ApiServer:
    def __init__(self, api, host: str = "127.0.0.1", port: int = 0):
        self.api = api
        self._mu = threading.Lock()
        # optional ControlPlaneMonitor (observability/controlplane.py),
        # set by monitor.attach_api_server: api-write breadcrumbs +
        # per-request accounting.  Every producer site gates on one
        # attribute read, so the unwired server pays a load + branch.
        self.cp = None
        self.caches: Dict[str, _WatchCache] = {
            "nodes": _WatchCache(),
            "pods": _WatchCache(),
        }
        # wire-byte accounting: (codec, direction) → total bytes, from the
        # server's perspective (tx = responses/streams, rx = request
        # bodies).  Plain dict under a dedicated mutex — handler threads
        # increment, the controlplane monitor scrapes deltas into
        # scheduler_tpu_wire_bytes_total at scrape time.
        self.wire_bytes: Dict[Tuple[str, str], int] = {}
        self._wire_mu = threading.Lock()
        # subscribe to the store's fan-out so every mutation (from any
        # client, or in-proc drivers) lands in the watch caches
        api.watch_nodes(
            lambda n: self._record("nodes", "ADDED", n),
            lambda old, new: self._record("nodes", "MODIFIED", new),
            lambda n: self._record("nodes", "DELETED", n),
        )
        api.watch_pods(
            lambda p: self._record("pods", "ADDED", p),
            lambda old, new: self._record("pods", "MODIFIED", new),
            lambda p: self._record("pods", "DELETED", p),
        )
        server = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + the peer's delayed ACK turns every multi-write
            # response into a ~40ms stall on keep-alive connections —
            # fatal for per-pod request rates (kube-apiserver serves
            # HTTP/2 where this never applies).  StreamRequestHandler
            # applies this to the connection socket.
            disable_nagle_algorithm = True

            def log_message(self, fmt, *args):  # noqa: D401 — quiet
                pass

            # per-request accounting context, set by _begin at the top of
            # each verb handler and consumed by _json at response time
            _acct = None

            def _begin(self, verb: str) -> None:
                cp = server.cp
                if cp is None or not cp.enabled:
                    self._acct = None
                    return
                parts = [
                    p for p in urlparse(self.path).path.split("/") if p
                ]
                res = parts[2] if len(parts) >= 3 and parts[0] == "api" else (
                    parts[0] if parts else "other"
                )
                self._acct = (cp, verb, res, time.monotonic())

            # ----- content negotiation (Accept / Content-Type) ---------
            # JSON stays the DEBUG DEFAULT: a request that doesn't ask for
            # the binary content type gets exactly the old JSON wire, so
            # curl sessions, old clients, and the chaos journal's decoded
            # entries are untouched.

            def _wants_binary(self) -> bool:
                return wire_codec.CT_BINARY in (self.headers.get("Accept") or "")

            def _read_body(self):
                """Request body → value, negotiated via Content-Type."""
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length) if length else b""
                ct = self.headers.get("Content-Type") or ""
                if wire_codec.CT_BINARY in ct:
                    server._note_wire("binary", "rx", len(raw))
                    if not raw:
                        return {}
                    return wire_codec.decode_frame(raw)[0]
                server._note_wire("json", "rx", len(raw))
                return json.loads(raw or b"{}")

            def _send_raw(self, code: int, body: bytes, ctype: str, codec: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                server._note_wire(codec, "tx", len(body))
                acct = self._acct
                if acct is not None:
                    self._acct = None
                    cp, verb, res, t0 = acct
                    cp.note_request(verb, res, code, time.monotonic() - t0)

            def _json(self, code: int, payload) -> None:
                """Negotiated response: named for the historical default —
                answers in binary when the request's Accept asks for it."""
                if self._wants_binary():
                    return self._send_raw(
                        code,
                        wire_codec.encode_frame(payload),
                        wire_codec.CT_BINARY,
                        "binary",
                    )
                return self._send_raw(
                    code, json.dumps(payload).encode(), "application/json", "json"
                )

            def do_GET(self):  # noqa: N802
                self._begin("GET")
                u = urlparse(self.path)
                parts = [p for p in u.path.split("/") if p]
                q = parse_qs(u.query)
                if len(parts) == 4 and parts[:3] == ["api", "v1", "leases"]:
                    from kubernetes_tpu.util.leases import lease_to_wire

                    rec = server.api.lease_store.get(unquote(parts[3]))
                    if rec is None:
                        return self._json(404, {"error": "lease not found"})
                    return self._json(200, lease_to_wire(rec))
                if len(parts) == 3 and parts[:2] == ["api", "v1"]:
                    res = parts[2]
                    if res not in server.caches:
                        return self._json(404, {"error": "unknown resource"})
                    if q.get("watch", ["0"])[0] in ("1", "true"):
                        return self._watch(res, int(q.get("resourceVersion", ["0"])[0]))
                    if self._wants_binary():
                        # encode-once list: splice the watch cache's
                        # per-object blobs instead of re-serializing the
                        # full object set per request
                        return self._send_raw(
                            200,
                            server.list_frame(res),
                            wire_codec.CT_BINARY,
                            "binary",
                        )
                    return self._json(200, server.list_payload(res))
                if parts == ["healthz"]:
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

            def _watch(self, res: str, rv: int) -> None:
                self._acct = None  # a stream, not a request latency
                cache = server.caches[res]
                # join the watcher registry: fanout lag is the cache head
                # rv minus this stream's delivered rv, scraped on demand
                with cache.cond:
                    cache._watcher_seq += 1
                    wid = cache._watcher_seq
                    cache.watchers[wid] = rv
                try:
                    self._watch_stream(cache, rv, wid, self._wants_binary())
                finally:
                    with cache.cond:
                        cache.watchers.pop(wid, None)

            def _watch_stream(self, cache, rv: int, wid: int, binary: bool) -> None:
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    wire_codec.CT_BINARY if binary else "application/json",
                )
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()
                codec = "binary" if binary else "json"

                def chunk_raw(data: bytes) -> bool:
                    try:
                        self.wfile.write(hex(len(data))[2:].encode() + b"\r\n")
                        self.wfile.write(data + b"\r\n")
                        self.wfile.flush()
                        server._note_wire(codec, "tx", len(data))
                        return True
                    except (BrokenPipeError, ConnectionError, OSError):
                        return False

                def chunk(payload: dict) -> bool:
                    # control frames (bookmark/410) — built per stream,
                    # they carry stream-local state
                    if binary:
                        return chunk_raw(wire_codec.encode_frame(payload))
                    return chunk_raw((json.dumps(payload) + "\n").encode())

                while True:
                    events = cache.since(rv, timeout=BOOKMARK_INTERVAL_S)
                    if events is None:
                        chunk({"type": "ERROR", "code": 410})
                        break
                    if not events:
                        if not chunk({"type": "BOOKMARK", "rv": rv}):
                            return
                        continue
                    # coalesced emission: ONE chunked write carries every
                    # pending event's pre-serialized bytes — a burst of N
                    # events costs one write+flush instead of N, and the
                    # bytes are the SHARED per-event encoding (binary
                    # frames or memoized JSON lines), never re-serialized
                    # per watcher
                    rv = events[-1].rv
                    cache.watchers[wid] = rv  # plain store — progress report
                    payload = (
                        b"".join(e.frame for e in events)
                        if binary
                        else b"".join(e.json_line for e in events)
                    )
                    if not chunk_raw(payload):
                        return
                try:
                    self.wfile.write(b"0\r\n\r\n")
                except OSError:
                    pass

            def do_POST(self):  # noqa: N802
                self._begin("POST")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                body = self._read_body()
                if len(parts) == 3 and parts[2] in ("nodes", "pods"):
                    mk = (
                        server._create_node
                        if parts[2] == "nodes"
                        else server._create_pod
                    )
                    if isinstance(body, dict) and "items" in body:
                        # bulk create: per-item results (null = created/
                        # idempotent-ok) so conflicts inside a batch are
                        # never silently reported as created
                        results = []
                        for env in body["items"]:
                            code, payload = mk(decode(env))
                            results.append(None if code < 400 else payload)
                        n_err = sum(1 for r in results if r is not None)
                        return self._json(
                            207 if n_err else 201,
                            {"ok": n_err == 0, "results": results},
                        )
                    code, payload = mk(decode(body))
                    return self._json(code, payload)
                if len(parts) == 3 and parts[2] == "bindings":
                    # BULK binding write: the per-pod binding subresource
                    # semantics applied item-wise under the server lock —
                    # the batch-first extension of assignPod
                    # (storage.go:169); per-item statuses come back so the
                    # scheduler can unwind exactly the pods that failed
                    results = []
                    with server._mu:
                        for item in body.get("items", []):
                            uid = item.get("uid")
                            pod = server.api.pods.get(uid)
                            if pod is None:
                                results.append(
                                    {"code": 404, "error": f"pod {uid} not found"}
                                )
                                continue
                            try:
                                server.api.bind(pod, item["node"])
                                results.append(None)
                            except RuntimeError as e:
                                # the 409 carries the EXISTING binding so a
                                # client whose transport-level retry races
                                # its own applied first attempt can tell
                                # conflict-on-retry (node matches: success)
                                # from a real double-bind
                                results.append(
                                    {
                                        "code": 409,
                                        "error": str(e),
                                        "node": pod.node_name,
                                    }
                                )
                            except KeyError as e:
                                results.append({"code": 404, "error": str(e)})
                    return self._json(200, {"results": results})
                if len(parts) == 5 and parts[2] == "pods" and parts[4] == "binding":
                    uid = unquote(parts[3])
                    # check-and-bind under the server lock: concurrent
                    # binding POSTs (two active schedulers) must serialize,
                    # and store-level failures translate to API statuses
                    # like assignPod's CAS conflict (storage.go:254)
                    with server._mu:
                        pod = server.api.pods.get(uid)
                        if pod is None:
                            return self._json(
                                404, {"error": f"pod {uid} not found"}
                            )
                        # the store's CAS is the authority (assignPod,
                        # storage.go:254): a conflicting node → 409, a
                        # same-node rebind is idempotent — which makes the
                        # client's transport-level POST retry safe when the
                        # first attempt succeeded but the response was lost
                        try:
                            server.api.bind(pod, body["node"])
                        except RuntimeError as e:
                            # carry the existing binding (see the bulk
                            # route): conflict-on-retry where the node
                            # matches is the client's success signal
                            return self._json(
                                409, {"error": str(e), "node": pod.node_name}
                            )
                        except KeyError as e:
                            return self._json(404, {"error": str(e)})
                    return self._json(201, {"ok": True})
                return self._json(404, {"error": "not found"})

            def do_PUT(self):  # noqa: N802
                self._begin("PUT")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                body = self._read_body()
                if len(parts) == 4 and parts[2] == "nodes":
                    server.api.update_node(decode(body))
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "leases":
                    # Lease CAS (resourcelock/leaselock.go over the wire):
                    # stale resourceVersion → 409, the elector backs off
                    from kubernetes_tpu.util.leases import lease_from_wire

                    rec = lease_from_wire(body)
                    if server.api.lease_store.update(unquote(parts[3]), rec):
                        return self._json(
                            200,
                            {"ok": True, "resourceVersion": rec.resource_version + 1},
                        )
                    return self._json(409, {"error": "lease CAS conflict"})
                return self._json(404, {"error": "not found"})

            def do_PATCH(self):  # noqa: N802
                self._begin("PATCH")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                body = self._read_body()
                if len(parts) == 5 and parts[2] == "pods" and parts[4] == "status":
                    # read-modify-write under the server lock: concurrent
                    # status patches (nomination vs kubelet phase report)
                    # must not resurrect each other's stale fields
                    with server._mu:
                        uid = unquote(parts[3])
                        pod = server.api.pods.get(uid)
                        if pod is None:
                            return self._json(404, {"error": "not found"})
                        if "nominatedNodeName" in body or "phase" in body:
                            # never mutate the store's instance directly —
                            # the store computes its own old/new delta
                            import copy as _copy

                            patched = _copy.copy(pod)
                            if "nominatedNodeName" in body:
                                patched.nominated_node_name = body[
                                    "nominatedNodeName"
                                ]
                            if "phase" in body:
                                patched.phase = body["phase"]
                            server.api.patch_pod_status(patched)
                    return self._json(200, {"ok": True})
                if len(parts) == 5 and parts[2] == "nodes" and parts[4] == "status":
                    # the kubelet heartbeat write (node status subresource):
                    # Ready condition + lastHeartbeatTime — atomic RMW
                    # under the server lock so a concurrent taint PUT is
                    # never erased by a pre-taint copy
                    with server._mu:
                        name = unquote(parts[3])
                        node = server.api.nodes.get(name)
                        if node is None:
                            return self._json(404, {"error": "not found"})
                        import copy as _copy

                        patched = _copy.copy(node)
                        if "ready" in body:
                            patched.ready = bool(body["ready"])
                        if "lastHeartbeat" in body:
                            patched.last_heartbeat = float(body["lastHeartbeat"])
                        server.api.update_node(patched)
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "nodes":
                    # ATOMIC taint/readiness patch — the node-lifecycle
                    # controller's write shape.  Server-side RMW under the
                    # lock: the controller's view may be stale, but only
                    # the named taints/readiness change; heartbeats written
                    # concurrently are preserved (nodes carry no
                    # resourceVersion, so client-side full-object PUTs
                    # would silently regress them)
                    with server._mu:
                        name = unquote(parts[3])
                        node = server.api.nodes.get(name)
                        if node is None:
                            return self._json(404, {"error": "not found"})
                        import copy as _copy

                        from kubernetes_tpu.api.types import Taint

                        patched = _copy.copy(node)
                        remove = set(body.get("removeTaintKeys", []))
                        taints = tuple(
                            t for t in patched.taints if t.key not in remove
                        )
                        for t in body.get("addTaints", []):
                            if not any(x.key == t["key"] for x in taints):
                                taints = taints + (
                                    Taint(
                                        key=t["key"],
                                        value=t.get("value", ""),
                                        effect=t.get("effect", "NoSchedule"),
                                    ),
                                )
                        patched.taints = taints
                        if "ready" in body:
                            patched.ready = bool(body["ready"])
                        server.api.update_node(patched)
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

            def do_DELETE(self):  # noqa: N802
                self._begin("DELETE")
                parts = [p for p in urlparse(self.path).path.split("/") if p]
                if len(parts) == 4 and parts[2] == "pods":
                    server.api.delete_pod(unquote(parts[3]))
                    return self._json(200, {"ok": True})
                if len(parts) == 4 and parts[2] == "nodes":
                    server.api.delete_node(unquote(parts[3]))
                    return self._json(200, {"ok": True})
                return self._json(404, {"error": "not found"})

        class _Server(ThreadingHTTPServer):
            # registration storms open many sockets faster than accept()
            # drains them while the scheduler compiles — the default
            # backlog of 5 RSTs the overflow
            request_queue_size = 256
            daemon_threads = True

        self.http = _Server((host, port), Handler)
        self.port = self.http.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ----- store access -----------------------------------------------------

    def _record(self, res: str, etype: str, obj) -> None:
        key = obj.uid if isinstance(obj, Pod) else obj.name
        rv = self.caches[res].record(etype, encode(obj), key=key)
        cp = self.cp
        if cp is not None and cp.enabled:
            # the api_write breadcrumb: this event's rv + its watch-cache
            # entry time — the root of every pod's causal pipeline chain
            cp.note_api_write(res, rv, obj)

    def _note_wire(self, codec: str, direction: str, n: int) -> None:
        if not n:
            return
        key = (codec, direction)
        with self._wire_mu:
            self.wire_bytes[key] = self.wire_bytes.get(key, 0) + n

    # Creates are IDEMPOTENT for replays of the same SPEC (the client's
    # transport-level POST retry can re-send a create whose response was
    # lost — by then the server may already have written status fields)
    # and 409 AlreadyExists for conflicting specs — no duplicate ADDED
    # event ever reaches the watchers.
    @staticmethod
    def _spec_wire(obj, status_fields):
        d = dict(encode(obj))
        body = d.get("object", d)
        for f in status_fields:
            body.pop(f, None)
        return d

    def _create_node(self, node):
        status = ("ready", "lastHeartbeat", "last_heartbeat")
        with self._mu:
            cur = self.api.nodes.get(node.name)
            if cur is not None:
                if self._spec_wire(cur, status) == self._spec_wire(node, status):
                    return 200, {"ok": True, "idempotent": True}
                return 409, {"error": f"node {node.name} already exists"}
            self.api.create_node(node)
        return 201, {"ok": True}

    def _create_pod(self, pod):
        status = (
            "nodeName",
            "node_name",
            "phase",
            "nominatedNodeName",
            "nominated_node_name",
            "startTime",
            "start_time",
        )
        with self._mu:
            cur = self.api.pods.get(pod.uid)
            if cur is not None:
                if self._spec_wire(cur, status) == self._spec_wire(pod, status):
                    return 200, {"ok": True, "idempotent": True}
                return 409, {"error": f"pod {pod.uid} already exists"}
            self.api.create_pod(pod)
        return 201, {"ok": True}

    def list_payload(self, res: str) -> dict:
        """Consistent list: snapshot + the rv of the last event applied
        (reflector lists at this rv, then watches from it).  Only the
        snapshot + rv capture happens under the watch-cache lock; encoding
        10k objects there would stall every writer and watch fan-out for
        the duration (replayed events are idempotent on the client, so an
        event racing the encode is harmless)."""
        cache = self.caches[res]
        with cache.cond:
            # dict.copy() is atomic under the GIL — handler threads mutate
            # the store concurrently and bare .values() iteration would
            # raise "dictionary changed size during iteration"
            store = self.api.nodes if res == "nodes" else self.api.pods
            snapshot = store.copy()
            rv = cache.rv
        return {
            "resourceVersion": rv,
            "items": [encode(obj) for obj in snapshot.values()],
        }

    def list_frame(self, res: str) -> bytes:
        """The binary list response: same snapshot+rv discipline as
        ``list_payload``, but items are the watch cache's per-object
        nested blobs SPLICED into one frame — encode cost per request is
        O(items) concatenation, not O(items) serialization.  An object
        created before this server attached (no recorded event yet) falls
        back to a one-off encode; an object whose latest MODIFIED hasn't
        fanned out yet serves its previous blob, which the reflector's
        idempotent event replay corrects — the same race the JSON path
        tolerates in the other direction."""
        cache = self.caches[res]
        with cache.cond:
            store = self.api.nodes if res == "nodes" else self.api.pods
            snapshot = store.copy()
            frames = dict(cache.obj_frames)
            rv = cache.rv
        blobs = []
        for key, obj in snapshot.items():
            blob = frames.get(key)
            if blob is None:
                blob = wire_codec.encode_nested(encode(obj))
            blobs.append(blob)
        return wire_codec.encode_list_frame(rv, blobs)

    # ----- lifecycle --------------------------------------------------------

    def start(self) -> "ApiServer":
        self._thread = threading.Thread(
            target=self.http.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.http.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)
