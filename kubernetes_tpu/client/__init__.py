"""Client tier: HTTP list/watch API server + reflector-based client.

The wire half of SURVEY §2.4 — apiserver ↔ clients speak list + watch
(client-go reflector semantics) over HTTP; the scheduler consumes the
stream through RemoteClusterSource exactly like the in-proc FakeCluster.
"""

from kubernetes_tpu.client import wire_codec
from kubernetes_tpu.client.api_server import ApiServer
from kubernetes_tpu.client.client import (
    ApiClient,
    Reflector,
    RemoteClusterSource,
    RemoteLeaseStore,
    SharedInformer,
    pods_by_node_indexer,
)

__all__ = [
    "ApiServer",
    "ApiClient",
    "Reflector",
    "RemoteClusterSource",
    "RemoteLeaseStore",
    "SharedInformer",
    "pods_by_node_indexer",
    "wire_codec",
]
