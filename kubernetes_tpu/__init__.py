"""kubernetes_tpu — a TPU-native cluster-scheduling framework.

Re-implements the capability surface of Kubernetes' kube-scheduler
(reference: pkg/scheduler in M00nF1sh/kubernetes @ 2024-10-08) as a batched
constraint-satisfaction and scoring system on TPU via JAX/XLA.

The reference schedules one pod per cycle, running a Filter→Score plugin
pipeline over all nodes with a 16-way host thread pool
(pkg/scheduler/schedule_one.go:65).  This framework instead:

- mirrors the scheduler cache snapshot (pkg/scheduler/backend/cache/snapshot.go)
  into HBM as packed, interned int/float tensors,
- evaluates every Filter/Score plugin as a vmapped kernel over a
  ``(pending_pods × nodes)`` problem,
- commits a whole batch of pods with a sequential-equivalent ``lax.scan``
  so decisions match the reference's serial assume/bind protocol.

Package layout:
    api/        core object model (Pod, Node, quantities, selectors)
    snapshot/   string interning + packed device tensor schema
    oracle/     scalar golden model of plugin semantics (for property tests)
    ops/        batched JAX kernels, one per device-backed plugin
    framework/  plugin interface: extension points, Status, CycleState, runtime
    plugins/    in-tree plugins (device-backed or host-backed)
    cache/      host cache with assume protocol + incremental device mirror
    queue/      activeQ/backoffQ/unschedulable queue with queueing hints
    config/     KubeSchedulerConfiguration-shaped profile/config surface
    metrics/    Prometheus-style metrics registry
    utils/      misc helpers
"""

__version__ = "0.1.0"

# The score kernels do exact integer arithmetic in int64 (emulated on TPU;
# float64 is never used, so TPU compatibility is preserved).  Without x64,
# packing real-world quantities (memory in bytes > 2^31) overflows at the
# jit boundary, so the requirement is enforced at import.
import jax as _jax

try:
    _jax.config.update("jax_enable_x64", True)
except Exception:  # backend pinned by the embedding process — leave it be
    pass

# Shape-stable counter-based PRNG: the seeded tie-break contract is that
# ``random.bits(fold_in(key, attempt), (n,))[i]`` depends only on
# (key, attempt, i) — the device pipeline draws over the PADDED node bucket
# (n_cap) while the serial oracle draws over the real node count, and the
# two must agree on the shared prefix.  The legacy threefry lowering blocks
# counters by total shape, so the prefix differs between the two widths on
# boxes where jax defaults partitionable=False — pin it explicitly.
try:
    _jax.config.update("jax_threefry_partitionable", True)
except Exception:
    pass

# Persistent compilation cache (OPT-IN): the gang/chain pipelines compile
# in 20-50s per (shape, static-args) variant; caching executables on disk
# lets later processes reuse them (measured 75s -> 18s on a mixed drain).
# Opt in with KUBERNETES_TPU_COMPILE_CACHE=<dir>.  Not on by default: the
# current axon backend segfaults serializing SOME large executables
# (put_executable_and_time), so reliability wins until that's fixed
# upstream — in-process jit caching still amortizes compiles within one
# run either way.
import os as _os

_cache_dir = _os.environ.get("KUBERNETES_TPU_COMPILE_CACHE")
if _cache_dir:
    try:
        _jax.config.update("jax_compilation_cache_dir", _cache_dir)
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:  # older jax without the knobs
        pass
