"""Server wrapper: process entrypoint, serving, leader election, debugger.

The operational tier of cmd/kube-scheduler/app/server.go:163-318 rebuilt
around the embeddable Scheduler:

  * ``SchedulerServer`` — owns the scheduling loop thread, an HTTP mux
    serving /healthz, /readyz (handler-sync gated, server.go:202-211),
    /metrics (Prometheus text exposition), /configz, and the
    observability debug endpoints (OBSERVABILITY.md; the catalogue lives
    in ``DEBUG_ENDPOINTS`` and is served as a JSON index at /debug/):
    /debug/trace (start/stop/export span tracing),
    /debug/flightrecorder?pod= (per-pod lifecycle events),
    /debug/explain?pod= (per-node, per-plugin rejection reasons),
    /debug/slo (live SLI snapshot, per-stage latency breakdown,
    last-breach record + black-box trace),
    /debug/plan (counterfactual planners), and
    /debug/kernels (the device telemetry ledger's per-kernel table);
  * ``LeaseElector`` — Lease-based leader election
    (client-go/tools/leaderelection/leaderelection.go:116 semantics:
    LeaseDuration/RenewDeadline/RetryPeriod over a CAS'd lease record);
    only the leader runs scheduling cycles, a lost lease stops them;
  * ``CacheDebugger`` — SIGUSR2 dump of cache + queue and a comparer
    against the informer ground truth (backend/cache/debugger).
"""

from __future__ import annotations

import json
import signal
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional
from urllib.parse import parse_qs, urlparse

from kubernetes_tpu.scheduler import Scheduler

# ---------------------------------------------------------------------------
# Debug-endpoint catalogue: the ONE table both surfaces render from —
# GET /debug/ serves it as a JSON index, and the handler's plain-text
# help block is generated from it below (debug_help_text), so the two
# can never drift.
# ---------------------------------------------------------------------------

DEBUG_ENDPOINTS = (
    (
        "/debug/",
        "",
        "this JSON index of the debug endpoints",
    ),
    (
        "/debug/cache",
        "",
        "cache + queue dump with the informer ground-truth comparer (text)",
    ),
    (
        "/debug/trace",
        "?action=start|stop|export|status",
        "span tracer control + Perfetto-loadable export (default: status)",
    ),
    (
        "/debug/flightrecorder",
        "?pod=<uid|name>",
        "per-pod lifecycle breadcrumbs (default: ring stats + tail)",
    ),
    (
        "/debug/explain",
        "?pod=<uid|name>[&whatif_node=<node>][&max_nodes=N]",
        "per-node per-plugin rejection reasons; preemption what-if",
    ),
    (
        "/debug/slo",
        "?action=status|trace",
        "live SLI snapshot + burn rates; last breach's black-box trace",
    ),
    (
        "/debug/plan",
        "?planner=autoscale|deschedule|preempt_cost[&...]",
        "counterfactual planners over batched [K,P,N] snapshot forks "
        "(default: the planner catalogue)",
    ),
    (
        "/debug/kernels",
        "?cost=0|1",
        "device telemetry ledger: per-kernel dispatches, p50/p99 execute, "
        "compiles, est. FLOPs, d2h bytes, HBM, sentinel state",
    ),
    (
        "/debug/pipeline",
        "?pod=<uid|name>",
        "control-plane per-hop lag waterfall for one pod (api_write → "
        "watch_delivery → informer_handler → enqueue → pop → assumed → "
        "bind_start → bound); default: hop summary + staleness sentinel",
    ),
)


def debug_endpoint_index() -> dict:
    """The /debug/ response body."""
    return {
        "endpoints": [
            {"path": p, "params": params, "description": desc}
            for p, params, desc in DEBUG_ENDPOINTS
        ]
    }


def debug_help_text() -> str:
    """The plain-text help block, rendered from DEBUG_ENDPOINTS."""
    width = max(len(p + params) for p, params, _ in DEBUG_ENDPOINTS)
    return "\n".join(
        f"  {(p + params).ljust(width)}   {desc}"
        for p, params, desc in DEBUG_ENDPOINTS
    )


# ---------------------------------------------------------------------------
# Leader election (Lease objects + CAS)
# ---------------------------------------------------------------------------

# LeaseRecord/LeaseStore live in util.leases (shared with the API tier's
# /api/v1/leases resource and the HTTP RemoteLeaseStore); re-exported here
# for the established import path.
from kubernetes_tpu.util.leases import LeaseRecord, LeaseStore  # noqa: E402


class LeaseElector:
    """leaderelection.LeaderElector: acquire → renew loop → on lost, stop.

    tryAcquireOrRenew semantics (leaderelection.go:116): take the lease
    when empty, expired, or already ours; renewals CAS the renew_time.
    Expiry is judged against the LOCAL clock at which this elector last
    OBSERVED the record's resourceVersion change — never against the
    writer's timestamps — so two processes with skewed clocks still elect
    correctly (the reference's observedRecord/observedTime discipline)."""

    def __init__(
        self,
        store: LeaseStore,
        identity: str,
        lease_name: str = "kube-scheduler",
        lease_duration_s: float = 15.0,
        retry_period_s: float = 2.0,
        clock=time.monotonic,
    ):
        self.store = store
        self.identity = identity
        self.lease_name = lease_name
        self.lease_duration_s = lease_duration_s
        self.retry_period_s = retry_period_s
        self.clock = clock
        self._observed_rv = -1
        self._observed_time = 0.0

    def _observe(self, rec: Optional[LeaseRecord]) -> None:
        if rec is not None and rec.resource_version != self._observed_rv:
            self._observed_rv = rec.resource_version
            self._observed_time = self.clock()

    def try_acquire_or_renew(self) -> bool:
        now = self.clock()
        rec = self.store.get(self.lease_name)
        self._observe(rec)
        if rec is None:
            rec = LeaseRecord()
        expired = (
            not rec.holder
            or now >= self._observed_time + rec.lease_duration_s
        )
        if rec.holder != self.identity and not expired:
            return False
        if rec.holder != self.identity:
            rec.holder = self.identity
            rec.acquire_time = now
        rec.renew_time = now
        rec.lease_duration_s = self.lease_duration_s
        ok = self.store.update(self.lease_name, rec)
        if ok:
            # our own write: observe it immediately (the next get() sees
            # the bumped rv; counting renewal freshness from now is exact)
            self._observed_rv = rec.resource_version + 1
            self._observed_time = now
        return ok

    def is_leader(self) -> bool:
        rec = self.store.get(self.lease_name)
        self._observe(rec)
        return (
            rec is not None
            and rec.holder == self.identity
            and self.clock() < self._observed_time + rec.lease_duration_s
        )

    def release(self) -> None:
        rec = self.store.get(self.lease_name)
        if rec is not None and rec.holder == self.identity:
            rec.holder = ""
            self.store.update(self.lease_name, rec)


# ---------------------------------------------------------------------------
# Cache debugger (backend/cache/debugger)
# ---------------------------------------------------------------------------


class CacheDebugger:
    """Dump + compare on demand (SIGUSR2 in the reference,
    debugger.go:37-59)."""

    def __init__(self, scheduler: Scheduler, ground_truth=None):
        self.sched = scheduler
        # informer ground truth: () -> (node_names, {pod_uid: node_name});
        # FakeCluster supplies one, a real client would list the apiserver
        self.ground_truth = ground_truth

    def dump(self) -> str:
        with self.sched._mu:
            lines: List[str] = ["== cache dump =="]
            for cn in self.sched.cache.real_nodes():
                lines.append(
                    f"node {cn.node.name}: pods={sorted(p.name for p in cn.pods.values())} "
                    f"requested_cpu={cn.requested.milli_cpu}m"
                )
            lines.append(
                f"assumed: {sorted(self.sched.cache.assumed)}"
            )
            lines.append("== queue dump ==")
            for q, n in self.sched.queue.stats().items():
                lines.append(f"{q}: {n}")
            return "\n".join(lines)

    def compare(self) -> List[str]:
        """Cache vs informer ground truth (comparer.go): lists what the
        cache has that the API doesn't, and vice versa."""
        if self.ground_truth is None:
            return []
        api_nodes, api_pods = self.ground_truth()
        problems: List[str] = []
        with self.sched._mu:
            cache_nodes = {cn.node.name for cn in self.sched.cache.real_nodes()}
            missing = set(api_nodes) - cache_nodes
            extra = cache_nodes - set(api_nodes)
            if missing:
                problems.append(f"cache is missing nodes: {sorted(missing)}")
            if extra:
                problems.append(f"cache has ghost nodes: {sorted(extra)}")
            cache_pods = {
                uid: ps.pod.node_name
                for uid, ps in self.sched.cache.pod_states.items()
                if uid not in self.sched.cache.assumed
            }
            for uid, node in api_pods.items():
                if uid in cache_pods and cache_pods[uid] != node:
                    problems.append(
                        f"pod {uid}: cache says {cache_pods[uid]}, API says {node}"
                    )
            for uid in set(cache_pods) - set(api_pods):
                problems.append(f"cache has ghost pod {uid}")
        return problems

    def install_signal_handler(self) -> None:
        signal.signal(
            signal.SIGUSR2,
            lambda *_: print(self.dump() + "\n" + "\n".join(self.compare())),
        )


# ---------------------------------------------------------------------------
# HTTP serving + run loop
# ---------------------------------------------------------------------------


class SchedulerServer:
    """The kube-scheduler process body (app/server.go Run): healthz/readyz +
    metrics serving, leader election gate, scheduling loop."""

    def __init__(
        self,
        scheduler: Scheduler,
        elector: Optional[LeaseElector] = None,
        port: int = 0,
        poll_interval_s: float = 0.02,
        ground_truth=None,
    ):
        self.sched = scheduler
        self.elector = elector
        self.poll_interval_s = poll_interval_s
        self.debugger = CacheDebugger(scheduler, ground_truth)
        self._stop = threading.Event()
        self._synced = threading.Event()
        self._loop_thread: Optional[threading.Thread] = None
        self._le_thread: Optional[threading.Thread] = None
        self._is_leader = threading.Event()
        self.cycles = 0
        self.loop_errors = 0

        srv = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: str, ctype="text/plain"):
                data = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _send_json(self, obj, code: int = 200):
                self._send(
                    code, json.dumps(obj), ctype="application/json"
                )

            def do_GET(self):  # noqa: N802 — stdlib handler name
                parsed = urlparse(self.path)
                if parsed.path.startswith("/debug/"):
                    try:
                        self._debug_get(parsed)
                    except Exception as e:  # noqa: BLE001 — debug surface
                        self._send_json({"error": str(e)}, code=500)
                    return
                if self.path == "/healthz":
                    self._send(200, "ok")
                elif self.path == "/readyz":
                    # WaitForHandlersSync gate (server.go:202-211)
                    if srv._synced.is_set():
                        self._send(200, "ok")
                    else:
                        self._send(500, "informers not synced")
                elif self.path == "/metrics":
                    self._send(
                        200,
                        srv.sched.expose_metrics(),
                        ctype="text/plain; version=0.0.4",
                    )
                elif self.path == "/configz":
                    self._send(
                        200,
                        json.dumps(
                            {
                                "batchSize": srv.sched.config.batch_size,
                                "parallelism": srv.sched.config.parallelism,
                                "profiles": [
                                    p.scheduler_name
                                    for p in srv.sched.config.profiles
                                ],
                            }
                        ),
                        ctype="application/json",
                    )
                else:
                    self._send(404, "not found")

            def _debug_get(self, parsed):
                # docstring generated from DEBUG_ENDPOINTS after the
                # class body — one table, both surfaces
                q = parse_qs(parsed.query)
                path = parsed.path
                sched = srv.sched
                if path == "/debug/":
                    # the bare prefix: a JSON index of everything below,
                    # with ?format=text for the generated help block
                    if q.get("format", ["json"])[0] == "text":
                        self._send(
                            200,
                            "debug endpoints:\n" + debug_help_text() + "\n",
                        )
                    else:
                        self._send_json(debug_endpoint_index())
                elif path == "/debug/cache":
                    self._send(
                        200,
                        srv.debugger.dump()
                        + "\n"
                        + "\n".join(srv.debugger.compare()),
                    )
                elif path == "/debug/trace":
                    action = q.get("action", ["status"])[0]
                    tracer = sched.tracer
                    if action == "start":
                        tracer.start()
                        self._send_json(tracer.stats())
                    elif action == "stop":
                        tracer.stop()
                        self._send_json(tracer.stats())
                    elif action == "export":
                        out = tracer.export()
                        # a manual start() overrides an armed black-box
                        # ring; export is the terminal step of the manual
                        # start→stop→export flow, so RE-ARM here — without
                        # this, one manual capture silently disarms the
                        # "always-on" breach-dump guarantee until the next
                        # install_slo
                        slo = getattr(sched, "slo", None)
                        if (
                            slo is not None
                            and slo.config.blackbox
                            and not tracer.enabled
                        ):
                            tracer.blackbox_start(slo.config.blackbox_capacity)
                        self._send_json(out)
                    elif action == "status":
                        self._send_json(tracer.stats())
                    else:
                        self._send_json(
                            {"error": f"unknown action {action!r}"}, code=400
                        )
                elif path == "/debug/flightrecorder":
                    fr = sched.flight
                    ref = q.get("pod", [None])[0]
                    if ref is None:
                        out = fr.stats()
                        out["tail"] = fr.tail(50)
                        self._send_json(out)
                        return
                    from kubernetes_tpu.observability import find_pod

                    pod = find_pod(sched, ref)
                    uid = pod.uid if pod is not None else ref
                    events = fr.events_for(uid)
                    if not events and pod is None:
                        self._send_json(
                            {"error": f"no events for pod {ref!r}"}, code=404
                        )
                        return
                    self._send_json({"pod": uid, "events": events})
                elif path == "/debug/explain":
                    ref = q.get("pod", [None])[0]
                    if ref is None:
                        self._send_json(
                            {"error": "missing ?pod= parameter"}, code=400
                        )
                        return
                    from kubernetes_tpu.observability import (
                        explain_pod,
                        explain_whatif,
                        find_pod,
                    )

                    pod = find_pod(sched, ref)
                    if pod is None:
                        self._send_json(
                            {"error": f"pod {ref!r} not found"}, code=404
                        )
                        return
                    # ?whatif_node=X: preemption what-if — which victims
                    # would free node X for this pod (dry run, read-only)
                    whatif = q.get("whatif_node", [None])[0]
                    if whatif is not None:
                        self._send_json(explain_whatif(sched, pod, whatif))
                        return
                    try:
                        max_nodes = int(q.get("max_nodes", ["500"])[0])
                    except ValueError:
                        self._send_json(
                            {"error": "max_nodes must be an integer"},
                            code=400,
                        )
                        return
                    self._send_json(
                        explain_pod(sched, pod, max_nodes=max_nodes)
                    )
                elif path == "/debug/plan":
                    # the counterfactual planner tier (PLANNER.md): K
                    # what-if snapshot forks per fused device dispatch —
                    # autoscale / deschedule / preemption-cost planning
                    # the reference delegates to satellite projects
                    from kubernetes_tpu.planner import PLANNERS, run_planner

                    name = q.get("planner", ["list"])[0]
                    params = {k: v[0] for k, v in q.items()}
                    out = run_planner(sched, name, params)
                    bad = name != "list" and name not in PLANNERS
                    self._send_json(out, code=400 if bad else 200)
                elif path == "/debug/kernels":
                    # the device telemetry ledger (observability/
                    # kernels.py): per-kernel dispatch/compile/d2h
                    # accounting + live HBM + sentinel state.  ?cost=0
                    # skips the lazy FLOPs estimate (its first request
                    # per shape pays a lowering re-trace; memoized after)
                    led = sched.kernels
                    if not led.enabled:
                        self._send_json({"enabled": False})
                        return
                    want_cost = q.get("cost", ["1"])[0] not in ("0", "false")
                    self._send_json(led.snapshot(cost=want_cost))
                elif path == "/debug/pipeline":
                    # the control-plane pipeline tier (observability/
                    # controlplane.py): per-pod causal chain + hop
                    # waterfall; without ?pod=, the aggregate hop summary
                    # and staleness sentinel state
                    cp = getattr(sched, "controlplane", None)
                    if cp is None:
                        self._send_json({"enabled": False})
                        return
                    ref = q.get("pod", [None])[0]
                    if ref is None:
                        self._send_json(cp.snapshot())
                        return
                    from kubernetes_tpu.observability import find_pod

                    pod = find_pod(sched, ref)
                    uid = pod.uid if pod is not None else ref
                    out = cp.pipeline_for(uid)
                    if out is None:
                        self._send_json(
                            {"error": f"no pipeline chain for pod {ref!r}"},
                            code=404,
                        )
                        return
                    self._send_json(out)
                elif path == "/debug/slo":
                    # the steady-state SLO tier (observability/slo.py):
                    # live SLI snapshot + per-stage breakdown + last-breach
                    # record; ?action=trace serves the last breach's frozen
                    # black-box export when no dump_dir was configured
                    slo = getattr(sched, "slo", None)
                    if slo is None:
                        self._send_json({"enabled": False})
                        return
                    action = q.get("action", ["status"])[0]
                    if action == "status":
                        self._send_json(slo.snapshot())
                    elif action == "trace":
                        trace = slo.last_breach_trace()
                        if trace is None:
                            self._send_json(
                                {"error": "no breach trace captured"},
                                code=404,
                            )
                        else:
                            self._send_json(trace)
                    else:
                        self._send_json(
                            {"error": f"unknown action {action!r}"}, code=400
                        )
                else:
                    self._send_json(
                        {"error": "not found", **debug_endpoint_index()},
                        code=404,
                    )

            def log_message(self, *a):  # quiet
                pass

        # the mux help IS the endpoint table (satellite contract: the
        # JSON index and this text block cannot drift apart)
        Handler._debug_get.__doc__ = (
            "The observability debug mux (OBSERVABILITY.md):\n\n"
            + debug_help_text()
        )
        self.http = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self.http.server_port
        self._http_thread = threading.Thread(
            target=self.http.serve_forever, daemon=True
        )

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self._http_thread.start()
        self._synced.set()  # in-proc informers are synchronous
        if self.elector is not None:
            self._le_thread = threading.Thread(
                target=self._run_election, daemon=True
            )
            self._le_thread.start()
        self._loop_thread = threading.Thread(target=self._run_loop, daemon=True)
        self._loop_thread.start()

    def _run_election(self) -> None:
        """Dedicated renewal loop (the reference's leaderelection goroutine):
        the lease renews every retry period INDEPENDENTLY of scheduling
        cycles, so a long cycle (first jit compile, giant drain) cannot let
        the lease lapse under an active leader; a lost lease clears the
        flag and the scheduling loop stops at its next check."""
        renew_deadline = self.elector.lease_duration_s * (2.0 / 3.0)
        last_success = None
        while not self._stop.is_set():
            try:
                acquired = self.elector.try_acquire_or_renew()
            except Exception:  # noqa: BLE001 — remote store hiccup
                acquired = False
            now = self.elector.clock()
            if acquired:
                last_success = now
                self._is_leader.set()
            elif (
                self._is_leader.is_set()
                and last_success is not None
                and now - last_success < renew_deadline
            ):
                # a held lease survives transient renew failures until the
                # renew DEADLINE (leaderelection.go RenewDeadline) — one
                # dropped request must not stall scheduling while no
                # standby can legally take over anyway
                pass
            else:
                self._is_leader.clear()
            self._stop.wait(self.elector.retry_period_s)

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            if self.elector is not None and not self._is_leader.is_set():
                self._stop.wait(self.elector.retry_period_s)
                continue
            try:
                outs = self.sched.schedule_pending()
                if outs:
                    self.cycles += 1
            except Exception:  # noqa: BLE001 — loop must survive
                # a persistent failure (bad config/plugin) must be visible:
                # log with traceback and count it on /metrics so the loop
                # never becomes a silent busy-wait
                import logging

                logging.getLogger("kubernetes_tpu.server").exception(
                    "scheduling cycle failed"
                )
                self.loop_errors += 1
                try:
                    self.sched.metrics["errors"] += 1
                except Exception:  # noqa: BLE001
                    pass
            self._stop.wait(self.poll_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._loop_thread is not None:
            self._loop_thread.join(timeout=5)
        if self._le_thread is not None:
            # settle the renewal loop BEFORE releasing, or a concurrent
            # renew can defeat the release and strand the lease on this
            # dead process for a full lease_duration
            self._le_thread.join(timeout=5)
        if self.elector is not None:
            self.elector.release()
        self.http.shutdown()

    def is_leading(self) -> bool:
        return self.elector is None or self.elector.is_leader()


def main(argv: Optional[List[str]] = None) -> int:
    """cmd/kube-scheduler entrypoint: --config file → run loop + serving."""
    import argparse

    from kubernetes_tpu.framework.config import load_config
    from kubernetes_tpu.testing.fake_cluster import FakeCluster

    ap = argparse.ArgumentParser(prog="kubernetes-tpu-scheduler")
    ap.add_argument("--config", help="KubeSchedulerConfiguration YAML")
    ap.add_argument("--port", type=int, default=10259)
    ap.add_argument(
        "--leader-elect", action="store_true", default=False
    )
    ap.add_argument("--lease-duration", type=float, default=15.0)
    ap.add_argument("--retry-period", type=float, default=2.0)
    ap.add_argument(
        "--api-endpoint",
        help="HTTP list/watch API endpoint (e.g. http://127.0.0.1:8001); "
        "when omitted the process serves an in-proc FakeCluster",
    )
    args = ap.parse_args(argv)

    conf = load_config(args.config) if args.config else None
    # event broadcaster started before the scheduler runs
    # (cmd/kube-scheduler/app/server.go:179)
    from kubernetes_tpu.events import EventBroadcaster

    broadcaster = EventBroadcaster()
    sched = Scheduler(configuration=conf, event_broadcaster=broadcaster)
    ground_truth = None
    elector = None
    if args.api_endpoint:
        # real wire tier: reflector-based list/watch client
        from kubernetes_tpu.client import RemoteClusterSource, RemoteLeaseStore

        source = RemoteClusterSource(args.api_endpoint)
        source.connect(sched)
        source.start()
        source.wait_for_sync()
        if args.leader_elect:
            import os

            elector = LeaseElector(
                RemoteLeaseStore(source.client),
                identity=f"pid-{os.getpid()}",
                lease_duration_s=args.lease_duration,
                retry_period_s=args.retry_period,
            )
    else:
        # in-proc cluster (the FakeCluster source)
        api = FakeCluster()
        api.connect(sched)
        ground_truth = api.ground_truth
        if args.leader_elect:
            elector = LeaseElector(
                api.lease_store,
                identity=f"pid-{id(sched)}",
                lease_duration_s=args.lease_duration,
                retry_period_s=args.retry_period,
            )
    server = SchedulerServer(
        sched, elector=elector, port=args.port, ground_truth=ground_truth
    )
    server.debugger.install_signal_handler()
    server.start()
    print(f"serving on 127.0.0.1:{server.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
