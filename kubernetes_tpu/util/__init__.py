"""Shared scheduler utilities (reference pkg/scheduler/util)."""
