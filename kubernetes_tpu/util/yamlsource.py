"""Shared YAML/dict source loading for config-shaped inputs."""

from __future__ import annotations

import os


def load_yaml_source(source) -> dict:
    """Accepts a dict (returned as-is), a filesystem path, or a YAML
    string; returns the parsed mapping ({} for empty)."""
    if isinstance(source, dict):
        return source
    import yaml

    if isinstance(source, str):
        try:
            is_path = os.path.exists(source)
        except (ValueError, OSError):  # e.g. NUL bytes in a YAML string
            is_path = False
        if is_path:
            with open(source) as f:
                return yaml.safe_load(f) or {}
    return yaml.safe_load(source) or {}
