"""Lease objects + CAS store (coordination.k8s.io/v1 over etcd3 semantics).

The resourcelock.LeaseLock analogue (client-go/tools/leaderelection/
resourcelock/leaselock.go): a named record with holder/renew metadata whose
updates are optimistic-concurrency CAS'd on resourceVersion.  The in-proc
``LeaseStore`` backs single-process deployments and the API server's
``/api/v1/leases`` resource; ``kubernetes_tpu.client.RemoteLeaseStore``
speaks the same get/update surface over HTTP so two real scheduler
processes elect through one API server.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Dict, Optional


@dataclass
class LeaseRecord:
    """coordination.k8s.io/v1 Lease spec fields the elector uses."""

    holder: str = ""
    acquire_time: float = 0.0
    renew_time: float = 0.0
    lease_duration_s: float = 15.0
    resource_version: int = 0


def lease_to_wire(rec: LeaseRecord) -> dict:
    return {
        "holder": rec.holder,
        "acquireTime": rec.acquire_time,
        "renewTime": rec.renew_time,
        "leaseDurationSeconds": rec.lease_duration_s,
        "resourceVersion": rec.resource_version,
    }


def lease_from_wire(d: dict) -> LeaseRecord:
    return LeaseRecord(
        holder=d.get("holder", ""),
        acquire_time=d.get("acquireTime", 0.0),
        renew_time=d.get("renewTime", 0.0),
        lease_duration_s=d.get("leaseDurationSeconds", 15.0),
        resource_version=d.get("resourceVersion", 0),
    )


class LeaseStore:
    """In-proc lease registry with optimistic-concurrency updates — the
    storage half of LeaseLock (a real client CASes through the apiserver;
    FakeCluster embeds one of these and ApiServer serves it)."""

    def __init__(self) -> None:
        self._leases: Dict[str, LeaseRecord] = {}
        self._mu = threading.Lock()

    def get(self, name: str) -> Optional[LeaseRecord]:
        with self._mu:
            rec = self._leases.get(name)
            return None if rec is None else LeaseRecord(**rec.__dict__)

    def update(self, name: str, rec: LeaseRecord) -> bool:
        """CAS on resource_version (GuaranteedUpdate, etcd3/store.go)."""
        with self._mu:
            cur = self._leases.get(name)
            cur_rv = cur.resource_version if cur is not None else 0
            if rec.resource_version != cur_rv:
                return False
            stored = LeaseRecord(**rec.__dict__)
            stored.resource_version = cur_rv + 1
            self._leases[name] = stored
            return True
