"""Zone-interleaved node iteration order (backend/cache/node_tree.go).

The reference's scheduler cache keeps nodes in a nodeTree: a map of zone →
node list, with zones remembered in FIRST-SEEN order, and produces its
snapshot list by round-robining one node per zone per round (exhausted
zones skipped, node_tree.go:119-143).  Every order-sensitive mechanism —
adaptive-sampling windows, nextStartNodeIndex rotation, first-max
tie-breaks — rides that order, so multi-zone decision parity requires
reproducing it exactly.  This build keeps PACKED tensor slots stable for
delta uploads and instead threads a visit-rank permutation through the
sampling-compat paths; this module is the one shared definition of the
order, used by the snapshot mirror and the host oracle alike.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

ZONE_LABEL = "topology.kubernetes.io/zone"


def node_tree_order(zone_per_node: Sequence[Optional[str]]) -> List[int]:
    """Indices 0..n-1 reordered zone-round-robin.

    ``zone_per_node[i]`` is node i's zone label value ("" / None for
    unzoned nodes, which form their own bucket like the reference's empty
    zone key).  Zones iterate in first-seen order; nodes within a zone keep
    their given order; each round takes at most one node per zone.
    """
    by_zone: Dict[str, List[int]] = {}
    zones: List[str] = []
    for i, z in enumerate(zone_per_node):
        z = z or ""
        bucket = by_zone.get(z)
        if bucket is None:
            bucket = by_zone[z] = []
            zones.append(z)
        bucket.append(i)
    out: List[int] = []
    round_no = 0
    n = len(zone_per_node)
    while len(out) < n:
        for z in zones:
            bucket = by_zone[z]
            if round_no < len(bucket):
                out.append(bucket[round_no])
        round_no += 1
    return out
