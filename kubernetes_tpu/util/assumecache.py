"""Generic assume cache (reference pkg/scheduler/util/assumecache/assume_cache.go).

An informer-backed object store that lets the scheduler optimistically
"assume" a newer version of an object ahead of the watch confirming it:

  * informer add/update events only overwrite an entry when the incoming
    ``resource_version`` is >= the stored one (assume_cache.go:218-263 —
    an event older than the assumed object is the watch still catching up,
    so the assumed version wins);
  * ``assume(obj)`` installs a local version; it must carry the SAME
    resource_version as the currently stored object (the optimistic-
    concurrency precondition, :426-462) — it is replaced as soon as the
    watch delivers the real post-write object with a bumped version;
  * ``restore(key)`` reverts an assumed entry to the latest API object
    (:464).

Objects must expose ``.key`` (unique id) and ``.resource_version`` (int).
Single-writer scheduler loop ⇒ no locking needed (the reference's mutex
guards informer goroutines; here events are delivered on the same thread).
"""

from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")


class AssumeCacheError(Exception):
    pass


class _Entry(Generic[T]):
    __slots__ = ("latest_api_obj", "obj")

    def __init__(self, api_obj: T):
        self.latest_api_obj = api_obj  # last object seen from the informer
        self.obj = api_obj  # what Get returns (assumed or api)


class AssumeCache(Generic[T]):
    def __init__(self, description: str = "") -> None:
        self.description = description
        self._entries: Dict[str, _Entry[T]] = {}

    # ----- informer event handlers -----------------------------------------

    def on_add(self, obj: T) -> None:
        if obj is None:
            return
        cur = self._entries.get(obj.key)
        if cur is not None and obj.resource_version <= cur.obj.resource_version:
            # Stale or same-version redelivery (resync/at-least-once watch):
            # keep the stored object — an assumed object carries the SAME
            # version as the API object it shadows (assume_cache.go:249
            # skips on newVersion <= storedVersion for exactly this case).
            return
        self._entries[obj.key] = _Entry(obj)

    def on_update(self, old: Optional[T], new: T) -> None:
        self.on_add(new)

    def on_delete(self, obj: T) -> None:
        if obj is not None:
            self._entries.pop(obj.key, None)

    # ----- reads -------------------------------------------------------------

    def get(self, key: str) -> Optional[T]:
        e = self._entries.get(key)
        return e.obj if e else None

    def get_api_obj(self, key: str) -> Optional[T]:
        e = self._entries.get(key)
        return e.latest_api_obj if e else None

    def list(self, predicate: Optional[Callable[[T], bool]] = None) -> List[T]:
        out = [e.obj for e in self._entries.values()]
        if predicate is not None:
            out = [o for o in out if predicate(o)]
        return out

    def __len__(self) -> int:
        return len(self._entries)

    # ----- assume / restore ---------------------------------------------------

    def assume(self, obj: T) -> None:
        """Install a locally-modified version of a stored object.  The
        incoming object must carry the stored object's resource_version
        (assume_cache.go:426: 'can only assume latest version')."""
        e = self._entries.get(obj.key)
        if e is None:
            raise AssumeCacheError(f"{self.description}: {obj.key!r} not found")
        if obj.resource_version != e.obj.resource_version:
            raise AssumeCacheError(
                f"{self.description}: assume {obj.key!r} at version "
                f"{obj.resource_version}, cache has {e.obj.resource_version}"
            )
        e.obj = obj

    def restore(self, key: str) -> None:
        e = self._entries.get(key)
        if e is not None:
            e.obj = e.latest_api_obj
