"""Host-side wave partitioning for the gang scan's wave-commit mode.

SURVEY §7 "intra-batch conflicts": the reference schedules strictly one
pod at a time, so batched evaluation must be sequential-equivalent.  The
gang scan achieves that with one scan step per pod — but the step's
expensive pieces (spread/inter-pod contractions against already-placed
peers) only CHANGE when a pod whose labels/terms interact with a later
pod commits.  A *wave* is a maximal CONTIGUOUS run of batch pods that
provably cannot interact through spread selectors, affinity/anti-affinity
terms, or host ports; within a wave the expensive tensors are frozen and
only the cheap state (resources, scores, normalization) evolves pod by
pod.  Contiguity preserves commit order, so decisions stay bit-identical
to the serial scan (classic-vs-wave bit parity property-tested in
tests/test_waves.py).

The interaction predicate is CONSERVATIVE (may declare interaction where
none exists — only costs wave length, never correctness):

  * pod A's spread constraint interacts with pod B when they share a
    namespace and the constraint's selector matches B's labels
    (podtopologyspread counts same-namespace pods only,
    filtering.go:236-310);
  * pod A's affinity/anti term interacts with B when the term's namespace
    set admits B (a namespaceSelector conservatively admits everything)
    and its label selector matches B's labels
    (interpodaffinity/filtering.go:306-365) — checked in BOTH directions
    because placed pods' terms also constrain newcomers (symmetry);
  * any two pods that both request host ports interact (the port-conflict
    pair check, nodeports).

Pods collapse into *interaction groups* (identical namespace + labels +
constraint signature); pair decisions are memoized per group pair, so
partitioning a batch is O(P · distinct-groups) with dict lookups.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import LabelSelector, Pod

# selector ops the host matcher understands; anything else → conservative
_MATCH_ANY = object()


def _selector_sig(sel: Optional[LabelSelector]):
    if sel is None:
        return None
    return (
        tuple(sorted((sel.match_labels or {}).items())),
        tuple(
            (e.key, e.operator, tuple(e.values or ()))
            for e in (sel.match_expressions or ())
        ),
    )


def _selector_matches(sel: Optional[LabelSelector], labels: Dict[str, str]) -> bool:
    """LabelSelector match; unknown operators match conservatively."""
    if sel is None:
        # a nil selector matches nothing (labels.Nothing()) in spread
        # counting; the callers that mean "everything" pass empty selector
        return False
    for k, v in (sel.match_labels or {}).items():
        if labels.get(k) != v:
            return False
    for e in sel.match_expressions or ():
        op = e.operator
        if op == "In":
            if labels.get(e.key) not in (e.values or ()):
                return False
        elif op == "NotIn":
            if e.key in labels and labels[e.key] in (e.values or ()):
                return False
        elif op == "Exists":
            if e.key not in labels:
                return False
        elif op == "DoesNotExist":
            if e.key in labels:
                return False
        else:  # unknown op: conservative
            return True
    return True


class _Probe:
    """One selector-with-namespace-scope an interacting pod would match."""

    __slots__ = ("sel", "ns_any", "namespaces")

    def __init__(self, sel, ns_any: bool, namespaces: Tuple[str, ...]):
        self.sel = sel
        self.ns_any = ns_any
        self.namespaces = namespaces

    def admits(self, pod: Pod) -> bool:
        if not self.ns_any and pod.namespace not in self.namespaces:
            return False
        return _selector_matches(self.sel, pod.labels)


def _pod_probes(pod: Pod) -> List[_Probe]:
    probes: List[_Probe] = []
    for c in pod.topology_spread_constraints:
        probes.append(_Probe(c.label_selector, False, (pod.namespace,)))
    aff = pod.affinity
    terms = []
    if aff is not None:
        for grp in (aff.pod_affinity, aff.pod_anti_affinity):
            if grp is None:
                continue
            terms.extend(
                grp.required_during_scheduling_ignored_during_execution or ()
            )
            for wt in (
                grp.preferred_during_scheduling_ignored_during_execution or ()
            ):
                terms.append(wt.pod_affinity_term)
    for t in terms:
        if getattr(t, "namespace_selector", None) is not None:
            probes.append(_Probe(t.label_selector, True, ()))
        else:
            nss = tuple(t.namespaces or ()) or (pod.namespace,)
            probes.append(_Probe(t.label_selector, False, nss))
    return probes


def _group_key(pod: Pod):
    """Pods with equal keys behave identically in the interaction test."""
    aff_sig: tuple = ()
    if pod.affinity is not None:
        parts = []
        for grp in (pod.affinity.pod_affinity, pod.affinity.pod_anti_affinity):
            if grp is None:
                parts.append(None)
                continue
            sig = []
            for t in (
                grp.required_during_scheduling_ignored_during_execution or ()
            ):
                sig.append(
                    (
                        _selector_sig(t.label_selector),
                        tuple(t.namespaces or ()),
                        t.namespace_selector is not None,
                    )
                )
            for wt in (
                grp.preferred_during_scheduling_ignored_during_execution or ()
            ):
                t = wt.pod_affinity_term
                sig.append(
                    (
                        _selector_sig(t.label_selector),
                        tuple(t.namespaces or ()),
                        t.namespace_selector is not None,
                    )
                )
            parts.append(tuple(sig))
        aff_sig = tuple(parts)
    return (
        pod.namespace,
        tuple(sorted(pod.labels.items())),
        tuple(
            (_selector_sig(c.label_selector),) for c in pod.topology_spread_constraints
        ),
        aff_sig,
        bool(pod.host_ports()),
    )


class WaveBuilder:
    """Partitions batches into waves, memoizing group-pair interactions
    across batches (steady-state drains see the same few groups)."""

    def __init__(self) -> None:
        self._pair: Dict[Tuple, bool] = {}
        self._probes: Dict[Tuple, List[_Probe]] = {}

    def _interacts(self, ka, pa: Pod, kb, pb: Pod) -> bool:
        key = (ka, kb)
        hit = self._pair.get(key)
        if hit is not None:
            return hit
        if pa.host_ports() and pb.host_ports():
            out = True
        else:
            probes_a = self._probes.setdefault(ka, _pod_probes(pa))
            probes_b = self._probes.setdefault(kb, _pod_probes(pb))
            out = any(p.admits(pb) for p in probes_a) or any(
                p.admits(pa) for p in probes_b
            )
        self._pair[key] = out
        self._pair[(kb, ka)] = out
        if len(self._pair) > 65536:
            self._pair.clear()
        if len(self._probes) > 4096:
            self._probes.clear()
        return out

    def build(self, pods: Sequence[Pod]) -> List[List[int]]:
        """Contiguous runs of mutually non-interacting pods, in order.
        The incoming pod is tested against the current wave's DISTINCT
        group keys only (group members are interchangeable for the
        predicate), so a uniform batch costs O(P) lookups, not O(P²)."""
        waves: List[List[int]] = []
        cur: List[int] = []
        cur_distinct: Dict[Tuple, Pod] = {}
        keys = [self._key_of(p) for p in pods]
        for i, pod in enumerate(pods):
            ki = keys[i]
            if any(
                self._interacts(ki, pod, kj, rep)
                for kj, rep in cur_distinct.items()
            ):
                waves.append(cur)
                cur, cur_distinct = [], {}
            cur.append(i)
            cur_distinct.setdefault(ki, pod)
        if cur:
            waves.append(cur)
        return waves

    @staticmethod
    def _key_of(pod: Pod):
        d = pod.__dict__
        memo = d.get("_wave_key_memo")
        if memo is None:
            memo = d["_wave_key_memo"] = _group_key(pod)
        return memo
