"""In-process cluster: object store + watch fan-out + binding subresource."""

from __future__ import annotations

import copy
from dataclasses import replace
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api import storage as st
from kubernetes_tpu.api.types import Node, NodeSelector, NodeSelectorRequirement, NodeSelectorTerm, Pod


class _ObjectStore:
    """One watched resource kind: name-keyed store with resource-version
    bumping and add/update/delete handler fan-out (the per-resource slice of
    a real apiserver's watch cache)."""

    def __init__(self, cluster: "FakeCluster") -> None:
        self._cluster = cluster
        self.objects: Dict[str, object] = {}
        self.handlers: List[tuple] = []  # (add, update, delete)

    def watch(self, on_add, on_update, on_delete) -> None:
        self.handlers.append((on_add, on_update, on_delete))
        for obj in list(self.objects.values()):
            on_add(copy.deepcopy(obj))

    def create(self, obj) -> None:
        obj = copy.deepcopy(obj)
        obj.resource_version = self._cluster._next_rv()
        self.objects[obj.key] = obj
        for add, _, _ in self.handlers:
            add(copy.deepcopy(obj))

    def update(self, obj) -> None:
        obj = copy.deepcopy(obj)
        old = self.objects.get(obj.key)
        obj.resource_version = self._cluster._next_rv()
        self.objects[obj.key] = obj
        for _, update, _ in self.handlers:
            update(copy.deepcopy(old), copy.deepcopy(obj))

    def delete(self, key: str) -> None:
        obj = self.objects.pop(key, None)
        if obj is None:
            return
        for _, _, delete in self.handlers:
            delete(copy.deepcopy(obj))

    def get(self, key: str):
        return self.objects.get(key)


class FakeCluster:
    """A miniature apiserver: CRUD on nodes/pods, watch handler fan-out, and
    the pods/binding subresource (registry/core/pod/storage/storage.go:169
    assignPod semantics — sets spec.nodeName via the store, then notifies
    watchers).  Storage objects (PV/PVC/StorageClass/CSINode/CSIDriver/
    CSIStorageCapacity) live in generic watched stores; ``pv_controller``
    emulates kube-controller-manager's PV binder + an external dynamic
    provisioner so VolumeBinding's PreBind write-and-wait completes in-proc
    (the integration-test role of the real PV controller)."""

    def __init__(self, pv_controller: bool = True) -> None:
        self.nodes: Dict[str, Node] = {}
        self.pods: Dict[str, Pod] = {}
        self.pdbs: Dict[str, object] = {}  # name → PodDisruptionBudget
        self._node_handlers: List[tuple] = []  # (add, update, delete)
        self._pod_handlers: List[tuple] = []
        self.bindings: Dict[str, str] = {}  # pod uid → node name
        self.evictions: List[str] = []  # uids deleted via preemption
        self.events: List[object] = []  # recorded Events (events.k8s.io)
        self._rv = 0
        self.pvs = _ObjectStore(self)
        self.pvcs = _ObjectStore(self)
        self.storage_classes = _ObjectStore(self)
        self.csinodes = _ObjectStore(self)
        self.csidrivers = _ObjectStore(self)
        self.capacities = _ObjectStore(self)
        self.resource_claims = _ObjectStore(self)
        self.resource_slices = _ObjectStore(self)
        self.device_classes = _ObjectStore(self)
        self.pod_groups = _ObjectStore(self)  # coscheduling PodGroups
        self._pv_controller = pv_controller
        self.provisioned: List[str] = []  # PV names the fake provisioner made
        # coordination.k8s.io Lease objects (leader election, server.py)
        from kubernetes_tpu.server import LeaseStore

        self.lease_store = LeaseStore()

    def ground_truth(self):
        """(node_names, {pod_uid: node_name}) — the informer view the cache
        debugger compares against (backend/cache/debugger/comparer.go)."""
        return (
            list(self.nodes),
            {
                uid: p.node_name
                for uid, p in self.pods.items()
                if p.node_name
            },
        )

    def _next_rv(self) -> int:
        self._rv += 1
        return self._rv

    # ----- the in-proc PV controller + provisioner ---------------------------

    def _reconcile_volumes(self) -> None:
        """Bind PVs whose claimRef is set (the PV controller's syncVolume)
        and provision WaitForFirstConsumer claims annotated with a selected
        node (an external provisioner's watch loop)."""
        if not self._pv_controller:
            return
        changed = True
        while changed:
            changed = False
            for pv in list(self.pvs.objects.values()):
                if pv.claim_ref is None:
                    continue
                pvc = self.pvcs.get(f"{pv.claim_ref.namespace}/{pv.claim_ref.name}")
                if pvc is None:
                    continue
                if pvc.volume_name != pv.name or pvc.phase != st.PVC_BOUND:
                    pvc = pvc.clone()
                    pvc.volume_name = pv.name
                    pvc.phase = st.PVC_BOUND
                    self.pvcs.update(pvc)
                    changed = True
                if pv.phase != st.PV_BOUND:
                    pv = pv.clone()
                    pv.phase = st.PV_BOUND
                    self.pvs.update(pv)
                    changed = True
            for pvc in list(self.pvcs.objects.values()):
                node_name = pvc.annotations.get(st.ANN_SELECTED_NODE)
                if not node_name or pvc.volume_name:
                    continue
                sc = self.storage_classes.get(pvc.storage_class_name or "")
                if sc is None or sc.provisioner == st.NO_PROVISIONER:
                    continue
                pv_name = f"pv-provisioned-{pvc.namespace}-{pvc.name}"
                if self.pvs.get(pv_name) is not None:
                    continue
                affinity = NodeSelector(
                    (
                        NodeSelectorTerm(
                            match_fields=(
                                NodeSelectorRequirement(
                                    "metadata.name", "In", (node_name,)
                                ),
                            )
                        ),
                    )
                )
                pv = st.PersistentVolume(
                    name=pv_name,
                    capacity=pvc.request,
                    access_modes=pvc.access_modes,
                    storage_class_name=pvc.storage_class_name or "",
                    node_affinity=affinity,
                    claim_ref=st.ObjectRef(pvc.namespace, pvc.name),
                    csi_driver=sc.provisioner,
                    source_id=pv_name,
                )
                self.provisioned.append(pv_name)
                self.pvs.create(pv)
                changed = True

    # ----- watch registration ----------------------------------------------

    def watch_nodes(self, on_add, on_update, on_delete) -> None:
        self._node_handlers.append((on_add, on_update, on_delete))
        for node in self.nodes.values():
            on_add(node)

    def watch_pods(self, on_add, on_update, on_delete) -> None:
        self._pod_handlers.append((on_add, on_update, on_delete))
        for pod in self.pods.values():
            on_add(pod)

    # ----- nodes ------------------------------------------------------------

    def create_node(self, node: Node) -> None:
        self.nodes[node.name] = node
        for add, _, _ in self._node_handlers:
            add(node)

    def update_node(self, node: Node) -> None:
        old = self.nodes.get(node.name)
        self.nodes[node.name] = node
        for _, update, _ in self._node_handlers:
            update(old, node)

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node is None:
            return
        for _, _, delete in self._node_handlers:
            delete(node)

    # ----- pods -------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        # The store owns its copy and every delivered event carries a fresh
        # copy — callers keep mutating theirs (assume sets nodeName on the
        # scheduler's object) without ever aliasing the "API" state.
        pod = copy.deepcopy(pod)
        self.pods[pod.uid] = pod
        for add, _, _ in self._pod_handlers:
            add(copy.deepcopy(pod))

    def update_pod(self, pod: Pod) -> None:
        pod = copy.deepcopy(pod)
        old = self.pods.get(pod.uid)
        self.pods[pod.uid] = pod
        for _, update, _ in self._pod_handlers:
            update(copy.deepcopy(old), copy.deepcopy(pod))

    def delete_pod(self, uid: str) -> None:
        pod = self.pods.pop(uid, None)
        if pod is None:
            return
        # the binding ceases to exist with the pod — bindings is the
        # CURRENTLY-bound set (the HTTP tier and benches read it as such)
        self.bindings.pop(uid, None)
        for _, _, delete in self._pod_handlers:
            delete(pod)

    # ----- binding subresource ----------------------------------------------

    # The in-proc store is where extender binds must ALSO be mirrored (a
    # real deployment's extender writes the binding itself and the watch
    # delivers it; see Scheduler binder_override).
    mirror_extender_binds = True

    def bind(self, pod: Pod, node_name: str) -> None:
        """POST pods/{name}/binding: CAS-sets nodeName, rejects doubles."""
        stored = self.pods.get(pod.uid)
        if stored is None:
            raise KeyError(f"binding unknown pod {pod.key}")
        if stored.node_name and stored.node_name != node_name:
            raise RuntimeError(
                f"pod {pod.key} already bound to {stored.node_name}"
            )
        if stored.node_name == node_name and node_name:
            # same-node rebind: a transport-level POST retry replaying an
            # applied binding.  TRUE no-op — re-firing update handlers
            # here would fan a duplicate MODIFIED event to every watcher
            return
        if node_name not in self.nodes:
            raise KeyError(f"binding to unknown node {node_name}")
        old = copy.deepcopy(stored)
        stored.node_name = node_name
        self.bindings[pod.uid] = node_name
        for _, update, _ in self._pod_handlers:
            update(old, copy.deepcopy(stored))

    # ----- pod status subresource -------------------------------------------

    def patch_pod_status(self, pod: Pod) -> None:
        """PATCH pods/{name}/status: the scheduler's nomination/condition
        writes (util.PatchPodStatus)."""
        stored = self.pods.get(pod.uid)
        if stored is None:
            return
        old = copy.deepcopy(stored)
        stored.nominated_node_name = pod.nominated_node_name
        stored.phase = pod.phase
        for _, update, _ in self._pod_handlers:
            update(old, copy.deepcopy(stored))

    # ----- PDBs -------------------------------------------------------------

    def create_pdb(self, pdb) -> None:
        self.pdbs[pdb.name] = pdb

    # ----- storage objects ----------------------------------------------------

    def create_pv(self, pv: st.PersistentVolume) -> None:
        self.pvs.create(pv)
        self._reconcile_volumes()

    def update_pv(self, pv: st.PersistentVolume) -> None:
        self.pvs.update(pv)
        self._reconcile_volumes()

    def create_pvc(self, pvc: st.PersistentVolumeClaim) -> None:
        self.pvcs.create(pvc)
        self._reconcile_volumes()

    def update_pvc(self, pvc: st.PersistentVolumeClaim) -> None:
        self.pvcs.update(pvc)
        self._reconcile_volumes()

    def create_storage_class(self, sc: st.StorageClass) -> None:
        self.storage_classes.create(sc)

    def create_csinode(self, cn: st.CSINode) -> None:
        self.csinodes.create(cn)

    def create_csidriver(self, d: st.CSIDriver) -> None:
        self.csidrivers.create(d)

    def create_capacity(self, c: st.CSIStorageCapacity) -> None:
        self.capacities.create(c)

    # ----- events API (events.k8s.io store) ---------------------------------

    def record_event(self, event, is_new: bool = True) -> None:
        """Event sink (the API's events registry shape): a NEW series
        appends; an update REPLACES the stored snapshot for its key, so
        counts reflect the latest aggregation without double-posting."""
        idx = self.__dict__.setdefault("_event_idx", {})
        key = getattr(event, "key", None)
        if key is None:
            self.events.append(event)
            return
        pos = idx.get(key)
        if pos is None or is_new:
            idx[key] = len(self.events)
            self.events.append(event)
        else:
            self.events[pos] = event

    def list_events(self, reason: Optional[str] = None) -> List[object]:
        return [e for e in self.events if reason is None or e.reason == reason]

    # ----- wiring -----------------------------------------------------------

    def connect(self, scheduler) -> None:
        """Attach a Scheduler's event handlers (addAllEventHandlers)."""
        # events API sink: the scheduler's broadcaster (when wired) lands
        # Events here like the real events.k8s.io API would store them
        if getattr(scheduler, "event_broadcaster", None) is not None:
            scheduler.event_broadcaster.start_recording_to_sink(
                self.record_event
            )
        self.watch_nodes(
            scheduler.on_node_add, scheduler.on_node_update, scheduler.on_node_delete
        )
        self.watch_pods(
            scheduler.on_pod_add, scheduler.on_pod_update, scheduler.on_pod_delete
        )
        scheduler.binding_sink = self.bind

        def evict(pod):
            self.evictions.append(pod.uid)
            self.delete_pod(pod.uid)

        scheduler.pod_deleter = evict
        scheduler.pdb_lister = lambda: list(self.pdbs.values())
        scheduler.status_patcher = self.patch_pod_status

        # storage informers → scheduler assume caches + requeue events
        # (the per-GVK dynamic handlers of eventhandlers.go:431)
        from kubernetes_tpu.framework.interface import EventResource

        for store, res in (
            (self.pvs, EventResource.PV),
            (self.pvcs, EventResource.PVC),
            (self.storage_classes, EventResource.STORAGE_CLASS),
            (self.csinodes, EventResource.CSI_NODE),
            (self.csidrivers, EventResource.CSI_DRIVER),
            (self.capacities, EventResource.CSI_STORAGE_CAPACITY),
            (self.resource_claims, EventResource.RESOURCE_CLAIM),
            (self.resource_slices, EventResource.RESOURCE_SLICE),
            (self.device_classes, EventResource.DEVICE_CLASS),
            (self.pod_groups, EventResource.POD_GROUP),
        ):
            store.watch(*scheduler.storage_handlers(res))
        scheduler.pvc_writer = self.update_pvc
        scheduler.pv_writer = self.update_pv
        scheduler.claim_writer = self.resource_claims.update
