"""In-process cluster: object store + watch fan-out + binding subresource."""

from __future__ import annotations

import copy
from dataclasses import replace
from typing import Callable, Dict, List, Optional

from kubernetes_tpu.api.types import Node, Pod


class FakeCluster:
    """A miniature apiserver: CRUD on nodes/pods, watch handler fan-out, and
    the pods/binding subresource (registry/core/pod/storage/storage.go:169
    assignPod semantics — sets spec.nodeName via the store, then notifies
    watchers)."""

    def __init__(self) -> None:
        self.nodes: Dict[str, Node] = {}
        self.pods: Dict[str, Pod] = {}
        self.pdbs: Dict[str, object] = {}  # name → PodDisruptionBudget
        self._node_handlers: List[tuple] = []  # (add, update, delete)
        self._pod_handlers: List[tuple] = []
        self.bindings: Dict[str, str] = {}  # pod uid → node name
        self.evictions: List[str] = []  # uids deleted via preemption

    # ----- watch registration ----------------------------------------------

    def watch_nodes(self, on_add, on_update, on_delete) -> None:
        self._node_handlers.append((on_add, on_update, on_delete))
        for node in self.nodes.values():
            on_add(node)

    def watch_pods(self, on_add, on_update, on_delete) -> None:
        self._pod_handlers.append((on_add, on_update, on_delete))
        for pod in self.pods.values():
            on_add(pod)

    # ----- nodes ------------------------------------------------------------

    def create_node(self, node: Node) -> None:
        self.nodes[node.name] = node
        for add, _, _ in self._node_handlers:
            add(node)

    def update_node(self, node: Node) -> None:
        old = self.nodes.get(node.name)
        self.nodes[node.name] = node
        for _, update, _ in self._node_handlers:
            update(old, node)

    def delete_node(self, name: str) -> None:
        node = self.nodes.pop(name, None)
        if node is None:
            return
        for _, _, delete in self._node_handlers:
            delete(node)

    # ----- pods -------------------------------------------------------------

    def create_pod(self, pod: Pod) -> None:
        # The store owns its copy and every delivered event carries a fresh
        # copy — callers keep mutating theirs (assume sets nodeName on the
        # scheduler's object) without ever aliasing the "API" state.
        pod = copy.deepcopy(pod)
        self.pods[pod.uid] = pod
        for add, _, _ in self._pod_handlers:
            add(copy.deepcopy(pod))

    def update_pod(self, pod: Pod) -> None:
        pod = copy.deepcopy(pod)
        old = self.pods.get(pod.uid)
        self.pods[pod.uid] = pod
        for _, update, _ in self._pod_handlers:
            update(copy.deepcopy(old), copy.deepcopy(pod))

    def delete_pod(self, uid: str) -> None:
        pod = self.pods.pop(uid, None)
        if pod is None:
            return
        for _, _, delete in self._pod_handlers:
            delete(pod)

    # ----- binding subresource ----------------------------------------------

    def bind(self, pod: Pod, node_name: str) -> None:
        """POST pods/{name}/binding: CAS-sets nodeName, rejects doubles."""
        stored = self.pods.get(pod.uid)
        if stored is None:
            raise KeyError(f"binding unknown pod {pod.key}")
        if stored.node_name and stored.node_name != node_name:
            raise RuntimeError(
                f"pod {pod.key} already bound to {stored.node_name}"
            )
        if node_name not in self.nodes:
            raise KeyError(f"binding to unknown node {node_name}")
        old = copy.deepcopy(stored)
        stored.node_name = node_name
        self.bindings[pod.uid] = node_name
        for _, update, _ in self._pod_handlers:
            update(old, copy.deepcopy(stored))

    # ----- pod status subresource -------------------------------------------

    def patch_pod_status(self, pod: Pod) -> None:
        """PATCH pods/{name}/status: the scheduler's nomination/condition
        writes (util.PatchPodStatus)."""
        stored = self.pods.get(pod.uid)
        if stored is None:
            return
        old = copy.deepcopy(stored)
        stored.nominated_node_name = pod.nominated_node_name
        stored.phase = pod.phase
        for _, update, _ in self._pod_handlers:
            update(old, copy.deepcopy(stored))

    # ----- PDBs -------------------------------------------------------------

    def create_pdb(self, pdb) -> None:
        self.pdbs[pdb.name] = pdb

    # ----- wiring -----------------------------------------------------------

    def connect(self, scheduler) -> None:
        """Attach a Scheduler's event handlers (addAllEventHandlers)."""
        self.watch_nodes(
            scheduler.on_node_add, scheduler.on_node_update, scheduler.on_node_delete
        )
        self.watch_pods(
            scheduler.on_pod_add, scheduler.on_pod_update, scheduler.on_pod_delete
        )
        scheduler.binding_sink = self.bind

        def evict(pod):
            self.evictions.append(pod.uid)
            self.delete_pod(pod.uid)

        scheduler.pod_deleter = evict
        scheduler.pdb_lister = lambda: list(self.pdbs.values())
        scheduler.status_patcher = self.patch_pod_status
