"""Test fixtures: fake cluster (in-process API + watch stream).

The analogue of the reference's fake clientset + StartTestServer pattern
(SURVEY.md §4 tiers 1-2): nodes and pods are plain objects in an in-memory
store; mutations fan out to registered handlers exactly like the informer
delivery path; binding loops back as an assigned-pod Add event the way
apiserver → etcd → watch → informer does (SURVEY.md §3.5).
"""

from kubernetes_tpu.testing.fake_cluster import FakeCluster  # noqa: F401
