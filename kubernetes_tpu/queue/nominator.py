"""Nominated-pod tracking (backend/queue/nominator.go).

Preemptor pods carry status.nominatedNodeName while their victims exit; the
nominator makes those reservations visible to scheduling cycles so the
capacity they are about to consume is respected
(RunFilterPluginsWithNominatedPods, runtime/framework.go:973).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from kubernetes_tpu.api.types import Pod


class Nominator:
    def __init__(self) -> None:
        self._by_node: Dict[str, Dict[str, Pod]] = {}
        self._node_of: Dict[str, str] = {}

    def add(self, pod: Pod, node_name: Optional[str] = None) -> None:
        node = node_name or pod.nominated_node_name
        if not node:
            return
        self.delete(pod)
        self._by_node.setdefault(node, {})[pod.uid] = pod
        self._node_of[pod.uid] = node
        pod.nominated_node_name = node

    def delete(self, pod: Pod) -> None:
        node = self._node_of.pop(pod.uid, None)
        if node:
            self._by_node.get(node, {}).pop(pod.uid, None)
            if not self._by_node.get(node):
                self._by_node.pop(node, None)

    def update(self, old: Pod, new: Pod) -> None:
        # Keep nomination unless the update carries a new one
        node = new.nominated_node_name or self._node_of.get(old.uid, "")
        self.delete(old)
        if node:
            self.add(new, node)

    def pods_for_node(self, node_name: str) -> List[Pod]:
        return list(self._by_node.get(node_name, {}).values())

    def entries(self) -> List[tuple]:
        """All (node_name, pod) nominations — the gang dispatch charges
        these to their nodes for lower-priority pods."""
        return [
            (node, pod)
            for node, pods in self._by_node.items()
            for pod in pods.values()
        ]

    def __len__(self) -> int:
        return len(self._node_of)

    def nominated_node(self, uid: str) -> Optional[str]:
        return self._node_of.get(uid)
