"""Scheduling queue (pkg/scheduler/backend/queue)."""

from kubernetes_tpu.queue.scheduling_queue import (  # noqa: F401
    QueuedPodInfo,
    SchedulingQueue,
)
