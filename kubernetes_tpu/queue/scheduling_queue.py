"""3-tier scheduling queue with queueing hints and batch pop.

Mirrors pkg/scheduler/backend/queue/scheduling_queue.go:

  * activeQ       — heap ordered by the profile's QueueSort (priority desc,
                    then enqueue time);
  * podBackoffQ   — heap by backoff expiry; exponential 1s→10s per attempt
                    (:1230-1266);
  * unschedulablePods — map, flushed to active/backoff after 5 min (:63).

Requeue is driven by ClusterEvent → QueueingHintFn maps built from the
plugins' EventsToRegister (isPodWorthRequeuing :401-475): an event requeues
an unschedulable pod only if one of the plugins that rejected it registered
a matching hint that returns QUEUE.  The in-flight ledger reproduces
active_queue.go:74-126 — events arriving while a pod is being scheduled are
replayed when the pod is marked done, so nothing is lost to the race.

The TPU-native extension is ``pop_batch(k)``: up to k pods in exact
QueueSort order, feeding one gang dispatch instead of one pod per cycle.
"""

from __future__ import annotations

import heapq
import itertools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    ClusterEventWithHint,
    EventResource,
    QueueingHint,
)

DEFAULT_POD_INITIAL_BACKOFF = 1.0
DEFAULT_POD_MAX_BACKOFF = 10.0
DEFAULT_UNSCHEDULABLE_TIMEOUT = 5 * 60.0
DEFAULT_UNSCHEDULABLE_FLUSH_INTERVAL = 30.0  # scheduling_queue.go:356

# Lock-discipline registry (kubernetes_tpu.analysis): like the cache, the
# queue trusts its caller's lock — the reference queue carries its own
# mutex (scheduling_queue.go:146); here the Scheduler's _mu spans queue,
# cache and mirror so a commit's queue.done + cache.finish_binding settle
# atomically with respect to informer handlers.
_KTPU_GUARDED = {
    "SchedulingQueue": {
        "external_lock": "Scheduler._mu",
        "readonly": [
            "pending_pods",
            "stats",
            "depth_age_stats",
            "_find",
            "_entry_live",
            "_is_worth_requeuing",
            "_backoff_expiry",
            "_active_key",
            "_default_less",
        ],
    },
}

_seq = itertools.count()


class _LessKey:
    """Adapts a QueueSort ``less(a, b)`` to the heap's ordering protocol."""

    __slots__ = ("qp", "less")

    def __init__(self, qp, less):
        self.qp = qp
        self.less = less

    def __lt__(self, other) -> bool:
        return self.less(self.qp, other.qp)

    def __eq__(self, other) -> bool:
        return not self.less(self.qp, other.qp) and not self.less(
            other.qp, self.qp
        )


@dataclass
class QueuedPodInfo:
    """framework.QueuedPodInfo (types.go:234)."""

    pod: Pod
    timestamp: float = 0.0  # first enqueue time (queue clock — ordering)
    # first enqueue on the REAL monotonic clock: every latency/SLI duration
    # derives from this, never from the (injectable, wall-or-manual) queue
    # clock — a clock jump must not skew a latency delta
    mono_timestamp: float = 0.0
    attempts: int = 0
    unschedulable_plugins: set = field(default_factory=set)
    pending_plugins: set = field(default_factory=set)
    gated: bool = False
    # bookkeeping
    last_failure_time: float = 0.0

    @property
    def uid(self) -> str:
        return self.pod.uid


class SchedulingQueue:
    def __init__(
        self,
        less_fn: Optional[Callable[[QueuedPodInfo, QueuedPodInfo], bool]] = None,
        queueing_hints: Optional[
            Dict[str, List[ClusterEventWithHint]]
        ] = None,
        pre_enqueue_check: Optional[Callable[[Pod], Any]] = None,
        initial_backoff_s: float = DEFAULT_POD_INITIAL_BACKOFF,
        max_backoff_s: float = DEFAULT_POD_MAX_BACKOFF,
        unschedulable_timeout_s: float = DEFAULT_UNSCHEDULABLE_TIMEOUT,
        clock: Callable[[], float] = time.monotonic,
        key_fn: Optional[Callable[[QueuedPodInfo], Any]] = None,
        mono_clock: Callable[[], float] = time.monotonic,
    ):
        self.less = less_fn or self._default_less
        # optional totally-ordered tuple key consistent with less —
        # compares at C speed (QueueSort plugins may expose sort_key)
        self.key_fn = key_fn
        self.hints = queueing_hints or {}
        self.pre_enqueue_check = pre_enqueue_check
        self.initial_backoff = initial_backoff_s
        self.max_backoff = max_backoff_s
        self.unschedulable_timeout = unschedulable_timeout_s
        self.clock = clock
        # durations/SLIs stamp against this, independent of the injectable
        # ordering clock (tests inject manual clocks to skip backoff waits;
        # latency metrics must not inherit those jumps)
        self.mono_clock = mono_clock

        self._active: List[Tuple[Any, int, QueuedPodInfo]] = []  # heap
        self._backoff: List[Tuple[float, int, QueuedPodInfo]] = []  # heap
        self._unschedulable: Dict[str, QueuedPodInfo] = {}
        self._gated: Dict[str, QueuedPodInfo] = {}
        self._in_queue: Dict[str, str] = {}  # uid → which structure
        # uid → the LIVE heap entry's sequence id.  Lazy heap deletion keys
        # liveness on (location, entry id) so a pod re-entering the same heap
        # never resurrects a stale earlier entry.
        self._live: Dict[str, int] = {}
        self._items: Dict[str, QueuedPodInfo] = {}  # uid → qp (O(1) lookup)
        # in-flight pods + events ledger (active_queue.go:74-126)
        self._in_flight: Dict[str, List[Tuple[ClusterEvent, Any, Any]]] = {}
        self._last_unsched_flush = self.clock()
        # optional queue_incoming_pods_total Counter (metrics.py)
        self.incoming_counter = None
        # optional observability.FlightRecorder: per-pod lifecycle
        # breadcrumbs (enqueue/pop/requeue) — every producer site gates on
        # its `enabled` attribute so the off path is one load + branch
        self.flight = None

    # ----- ordering --------------------------------------------------------

    @staticmethod
    def _default_less(a: QueuedPodInfo, b: QueuedPodInfo) -> bool:
        """PrioritySort semantics (queuesort/priority_sort.go:43)."""
        if a.pod.priority != b.pod.priority:
            return a.pod.priority > b.pod.priority
        return a.timestamp < b.timestamp

    def _active_key(self, qp: QueuedPodInfo):
        """Heap key honoring the configured QueueSort less function — a
        custom QueueSort plugin orders the activeQ end to end (the
        reference builds the activeQ heap directly on the profile's Less,
        scheduler.go:340).  The key SNAPSHOTS the pod at push time: heap
        invariants require immutable keys, and updates re-push a fresh
        entry (the stale one dies lazily via _entry_live)."""
        if self.key_fn is not None:
            return self.key_fn(qp)
        if self.less is SchedulingQueue._default_less:
            # common case: a plain tuple key compares at C speed
            return (-qp.pod.priority, qp.timestamp)
        snap = QueuedPodInfo(
            pod=qp.pod,
            timestamp=qp.timestamp,
            attempts=qp.attempts,
            gated=qp.gated,
            last_failure_time=qp.last_failure_time,
        )
        return _LessKey(snap, self.less)

    def _push_active(self, qp: QueuedPodInfo) -> None:
        eid = next(_seq)
        heapq.heappush(self._active, (self._active_key(qp), eid, qp))
        self._in_queue[qp.uid] = "active"
        self._live[qp.uid] = eid
        self._items[qp.uid] = qp

    def _push_backoff(self, qp: QueuedPodInfo) -> None:
        eid = next(_seq)
        heapq.heappush(self._backoff, (self._backoff_expiry(qp), eid, qp))
        self._in_queue[qp.uid] = "backoff"
        self._live[qp.uid] = eid
        self._items[qp.uid] = qp

    def _entry_live(self, qp: QueuedPodInfo, eid: int, location: str) -> bool:
        return (
            self._in_queue.get(qp.uid) == location
            and self._live.get(qp.uid) == eid
        )

    def _backoff_expiry(self, qp: QueuedPodInfo) -> float:
        """Exponential: initial·2^(attempts-1), capped (scheduling_queue.go:1230)."""
        d = self.initial_backoff * (2 ** max(qp.attempts - 1, 0))
        return qp.last_failure_time + min(d, self.max_backoff)

    # ----- add paths --------------------------------------------------------

    def add(self, pod: Pod) -> None:
        """New unscheduled pod from the informer (PreEnqueue gating,
        scheduling_queue.go:499-538)."""
        if pod.uid in self._in_queue or pod.uid in self._in_flight:
            return
        qp = QueuedPodInfo(
            pod=pod,
            timestamp=self.clock(),
            mono_timestamp=self.mono_clock(),
        )
        if self.pre_enqueue_check is not None:
            status = self.pre_enqueue_check(pod)
            if status is not None and not getattr(status, "ok", True):
                qp.gated = True
                qp.unschedulable_plugins.add(getattr(status, "plugin", ""))
                self._gated[pod.uid] = qp
                self._in_queue[pod.uid] = "gated"
                self._items[pod.uid] = qp
                self._count_incoming("gated", "PodAdd")
                fr = self.flight
                if fr is not None and fr.enabled:
                    fr.record(
                        pod.uid,
                        "enqueue",
                        {"queue": "gated", "plugin": getattr(status, "plugin", "")},
                    )
                return
        self._push_active(qp)
        self._count_incoming("active", "PodAdd")
        fr = self.flight
        if fr is not None and fr.enabled:
            fr.record(pod.uid, "enqueue", {"queue": "active"})

    def update(self, old: Optional[Pod], new: Pod) -> None:
        where = self._in_queue.get(new.uid)
        if where is None:
            if new.uid in self._in_flight:
                # Record for replay at add_unschedulable; the live attempt
                # keeps running on the spec the kernel evaluated — the new
                # spec is adopted only at requeue time.
                self._in_flight[new.uid].append(
                    (ClusterEvent_from_pod_update(), old, new)
                )
                return
            self.add(new)
            return
        qp = self._find(new.uid)
        if qp is None:
            return
        old_key = self._active_key(qp) if where == "active" else None
        qp.pod = new
        if where == "gated":
            # Re-run gating: removing the last gate activates the pod.
            if self.pre_enqueue_check is not None:
                status = self.pre_enqueue_check(new)
                if status is None or getattr(status, "ok", True):
                    del self._gated[new.uid]
                    qp.gated = False
                    self._push_active(qp)
        elif where == "unschedulable":
            # Spec updates may make it schedulable (scheduling_queue.go update path).
            del self._unschedulable[new.uid]
            self._requeue(qp, immediately=False)
        elif where == "active" and self._active_key(qp) != old_key:
            # Re-push so a priority change reorders the heap; the old entry
            # goes stale through its entry id.  Key-neutral updates skip the
            # re-push so informer churn doesn't grow the heap.
            self._push_active(qp)
        # backoff ordering is by expiry, which no pod field affects — the
        # in-place qp.pod update above suffices.

    def delete(self, pod: Pod) -> None:
        where = self._in_queue.pop(pod.uid, None)
        if where == "unschedulable":
            self._unschedulable.pop(pod.uid, None)
        elif where == "gated":
            self._gated.pop(pod.uid, None)
        elif where in ("active", "backoff"):
            # lazy deletion: heap entries are skipped when their uid is
            # no longer registered
            pass
        self._live.pop(pod.uid, None)
        self._items.pop(pod.uid, None)
        self._in_flight.pop(pod.uid, None)

    # ----- pop --------------------------------------------------------------

    def _flush_backoff(self) -> None:
        now = self.clock()
        while self._backoff:
            expiry, eid, qp = self._backoff[0]
            if not self._entry_live(qp, eid, "backoff"):
                heapq.heappop(self._backoff)
                continue
            if expiry > now:
                break
            heapq.heappop(self._backoff)
            self._push_active(qp)

    def flush_unschedulable_leftover(self) -> None:
        """Pods stuck unschedulable > timeout move back
        (flushUnschedulablePodsLeftover, :802)."""
        now = self.clock()
        for uid in list(self._unschedulable):
            qp = self._unschedulable[uid]
            if now - qp.last_failure_time >= self.unschedulable_timeout:
                del self._unschedulable[uid]
                self._requeue(qp, immediately=False)

    def pop_batch(self, k: int) -> List[QueuedPodInfo]:
        """Up to k pods in QueueSort order — the gang dispatch feed.

        Each popped pod enters the in-flight ledger; call done(uid) after
        its scheduling attempt concludes.
        """
        now = self.clock()
        if now - self._last_unsched_flush >= DEFAULT_UNSCHEDULABLE_FLUSH_INTERVAL:
            self._last_unsched_flush = now
            self.flush_unschedulable_leftover()
        self._flush_backoff()
        out: List[QueuedPodInfo] = []
        while len(out) < k and self._active:
            _, eid, qp = heapq.heappop(self._active)
            if not self._entry_live(qp, eid, "active"):
                continue  # lazily-deleted or superseded entry
            del self._in_queue[qp.uid]
            self._live.pop(qp.uid, None)
            self._items.pop(qp.uid, None)
            qp.attempts += 1
            self._in_flight[qp.uid] = []
            out.append(qp)
        fr = self.flight
        if fr is not None and fr.enabled:
            fr.record_many(
                (qp.uid, "pop", {"attempt": qp.attempts}) for qp in out
            )
        return out

    def pop_batch_while(self, k, predicate) -> List[QueuedPodInfo]:
        """Up to k MORE pods in QueueSort order, stopping (without popping)
        at the first live entry the predicate rejects — the batch-extension
        feed for dispatch paths whose per-pod cost is flat enough that
        bigger batches amortize the device round trip.  Queue order is
        preserved exactly: the rejected pod stays at the head for the next
        pop_batch.  Call immediately after pop_batch (shares its backoff /
        unschedulable flush)."""
        out: List[QueuedPodInfo] = []
        while len(out) < k and self._active:
            _, eid, qp = self._active[0]
            if not self._entry_live(qp, eid, "active"):
                heapq.heappop(self._active)
                continue
            if not predicate(qp):
                break
            heapq.heappop(self._active)
            del self._in_queue[qp.uid]
            self._live.pop(qp.uid, None)
            self._items.pop(qp.uid, None)
            qp.attempts += 1
            self._in_flight[qp.uid] = []
            out.append(qp)
        fr = self.flight
        if fr is not None and fr.enabled:
            fr.record_many(
                (qp.uid, "pop", {"attempt": qp.attempts}) for qp in out
            )
        return out

    def pop_siblings(self, match) -> List[QueuedPodInfo]:
        """Pop every ACTIVE pod matching ``match`` regardless of heap
        position — the gang sibling-pull feed: popping one member pulls
        its READY siblings into the same batch, so a gang split across pop
        batches converges in one dispatch instead of by retry.  Pods in
        backoff / unschedulable / gated stay put (their gates still
        apply).  Matched entries are removed in QueueSort order; everyone
        else keeps their positions exactly (stale heap entries
        lazy-delete, the discipline pop_batch already relies on)."""
        picked = [
            entry
            for entry in self._active
            if self._entry_live(entry[2], entry[1], "active")
            and match(entry[2])
        ]
        picked.sort(key=lambda e: (e[0], e[1]))
        out: List[QueuedPodInfo] = []
        for _key, eid, qp in picked:
            if not self._entry_live(qp, eid, "active"):
                continue
            del self._in_queue[qp.uid]
            self._live.pop(qp.uid, None)
            self._items.pop(qp.uid, None)
            qp.attempts += 1
            self._in_flight[qp.uid] = []
            out.append(qp)
        fr = self.flight
        if fr is not None and fr.enabled:
            fr.record_many(
                (qp.uid, "pop", {"attempt": qp.attempts}) for qp in out
            )
        return out

    def pop(self) -> Optional[QueuedPodInfo]:
        batch = self.pop_batch(1)
        return batch[0] if batch else None

    # ----- scheduling-attempt outcomes --------------------------------------

    def add_unschedulable(self, qp: QueuedPodInfo, unschedulable_plugins) -> None:
        """AddUnschedulableIfNotPresent (:723): failed pod parks in the
        unschedulable map with the plugins that rejected it; events recorded
        while it was in flight are replayed first (done() semantics)."""
        if qp.uid not in self._in_flight:
            # The pod was deleted (or otherwise concluded) mid-attempt —
            # re-parking it would resurrect a ghost no delete event will
            # ever clear.
            return
        qp.unschedulable_plugins = set(unschedulable_plugins or ())
        qp.last_failure_time = self.clock()
        events = self._in_flight.pop(qp.uid)
        # Adopt the newest spec delivered mid-attempt (reference: the
        # informer update lands in the queue's copy before requeue).
        for ev, old, new in events:
            if (
                ev.resource == EventResource.UNSCHEDULED_POD
                and ev.action & ActionType.UPDATE
                and isinstance(new, Pod)
                and new.uid == qp.uid
            ):
                qp.pod = new
        fr = self.flight
        if not qp.unschedulable_plugins:
            # No failed plugin is associated — something unusual (an
            # apiserver error during binding, etc).  No queueing hint will
            # ever fire for it, so retry after backoff instead of parking
            # in the unschedulable map (scheduling_queue.go:642-647).
            if fr is not None and fr.enabled:
                fr.record(qp.uid, "requeue", {"to": "backoff"})
            self._requeue(qp, immediately=False, event="ScheduleAttemptFailure")
            return
        for ev, old, new in events:
            if self._is_worth_requeuing(qp, ev, old, new):
                if fr is not None and fr.enabled:
                    fr.record(
                        qp.uid,
                        "requeue",
                        {
                            "to": "backoff",
                            "plugins": sorted(qp.unschedulable_plugins),
                        },
                    )
                self._requeue(qp, immediately=False, event="ScheduleAttemptFailure")
                return
        self._unschedulable[qp.uid] = qp
        self._in_queue[qp.uid] = "unschedulable"
        self._items[qp.uid] = qp
        self._count_incoming("unschedulable", "ScheduleAttemptFailure")
        if fr is not None and fr.enabled:
            fr.record(
                qp.uid,
                "requeue",
                {
                    "to": "unschedulable",
                    "plugins": sorted(qp.unschedulable_plugins),
                },
            )

    def done(self, uid: str) -> None:
        """Pod's scheduling attempt fully concluded (bound or failed)."""
        self._in_flight.pop(uid, None)

    def activate(self, pods: Sequence[Pod]) -> None:
        """Plugins may force-activate specific pods (:589)."""
        for pod in pods:
            qp = self._find(pod.uid)
            if qp is None:
                continue
            where = self._in_queue.get(pod.uid)
            if where in ("unschedulable", "backoff"):
                if where == "unschedulable":
                    self._unschedulable.pop(pod.uid, None)
                self._push_active(qp)

    # ----- cluster events → requeue (the reactive path) ---------------------

    def move_all_on_event(
        self, event: ClusterEvent, old: Any = None, new: Any = None
    ) -> int:
        """MoveAllToActiveOrBackoffQueue (:1014).  Returns #requeued."""
        # record for in-flight pods first (replayed at done)
        for uid in self._in_flight:
            self._in_flight[uid].append((event, old, new))

        moved = 0
        for uid in list(self._unschedulable):
            qp = self._unschedulable[uid]
            if self._is_worth_requeuing(qp, event, old, new):
                del self._unschedulable[uid]
                self._requeue(qp, immediately=False)
                moved += 1
        # Gated (PreEnqueue-rejected) pods re-run their gate when an event
        # their gating plugin registered for fires (e.g. DRA's claim-created
        # hint) — pod updates alone aren't the only ungating trigger.
        for uid in list(self._gated):
            qp = self._gated[uid]
            if not self._is_worth_requeuing(qp, event, old, new):
                continue
            if self.pre_enqueue_check is not None:
                status = self.pre_enqueue_check(qp.pod)
                if status is not None and not getattr(status, "ok", True):
                    continue  # still gated
            del self._gated[uid]
            qp.gated = False
            self._push_active(qp)
            moved += 1
        return moved

    def _is_worth_requeuing(
        self, qp: QueuedPodInfo, event: ClusterEvent, old: Any, new: Any
    ) -> bool:
        """isPodWorthRequeuing (:401): only hints of the plugins that
        rejected the pod run, for matching events."""
        plugins = qp.unschedulable_plugins | qp.pending_plugins
        if not plugins:
            return True  # rejected by no plugin (e.g. error) → always retry
        for name in plugins:
            for ewh in self.hints.get(name, []):
                if not ewh.event.match(event):
                    continue
                if ewh.hint_fn is None:
                    return True
                try:
                    if ewh.hint_fn(qp.pod, old, new) == QueueingHint.QUEUE:
                        return True
                except Exception:
                    return True  # hint error → requeue (fail open, :447)
        return False

    def _requeue(self, qp: QueuedPodInfo, immediately: bool, event: str = "ClusterEvent") -> None:
        if immediately or self._backoff_expiry(qp) <= self.clock():
            self._push_active(qp)
            self._count_incoming("active", event)
        else:
            self._push_backoff(qp)
            self._count_incoming("backoff", event)

    def _count_incoming(self, queue: str, event: str) -> None:
        """queue_incoming_pods_total (metrics.go:200)."""
        if self.incoming_counter is not None:
            self.incoming_counter.inc(queue=queue, event=event)

    # ----- introspection ----------------------------------------------------

    def _find(self, uid: str) -> Optional[QueuedPodInfo]:
        if self._in_queue.get(uid) is None:
            return None
        return self._items.get(uid)

    def stats(self) -> Dict[str, int]:
        """Live counts per sub-queue (feeds the pending_pods gauge)."""
        p = self.pending_pods()
        return {name: len(pods) for name, pods in p.items()}

    def depth_age_stats(self) -> Dict[str, Tuple[int, float]]:
        """Per-sub-queue (depth, oldest-pod age in seconds) — the
        queue_depth / queue_oldest_age gauges' scrape feed.  Age derives
        from the REAL monotonic first-enqueue stamp (never the injectable
        ordering clock), so a manual-clock test can't skew it."""
        now = self.mono_clock()
        live: Dict[str, List[QueuedPodInfo]] = {
            "active": [
                qp
                for _, eid, qp in self._active
                if self._entry_live(qp, eid, "active")
            ],
            "backoff": [
                qp
                for _, eid, qp in self._backoff
                if self._entry_live(qp, eid, "backoff")
            ],
            "unschedulable": list(self._unschedulable.values()),
            "gated": list(self._gated.values()),
        }
        out: Dict[str, Tuple[int, float]] = {}
        for name, qps in live.items():
            oldest = max(
                (now - qp.mono_timestamp for qp in qps if qp.mono_timestamp),
                default=0.0,
            )
            out[name] = (len(qps), max(oldest, 0.0))
        return out

    def pending_pods(self) -> Dict[str, List[Pod]]:
        """PendingPods introspection (:1146)."""
        active = [
            qp.pod
            for _, eid, qp in self._active
            if self._entry_live(qp, eid, "active")
        ]
        backoff = [
            qp.pod
            for _, eid, qp in self._backoff
            if self._entry_live(qp, eid, "backoff")
        ]
        return {
            "active": active,
            "backoff": backoff,
            "unschedulable": [qp.pod for qp in self._unschedulable.values()],
            "gated": [qp.pod for qp in self._gated.values()],
        }

    def __len__(self) -> int:
        p = self.pending_pods()
        return sum(len(v) for v in p.values())


def ClusterEvent_from_pod_update():
    from kubernetes_tpu.framework.interface import ActionType, EventResource

    return ClusterEvent(EventResource.UNSCHEDULED_POD, ActionType.UPDATE)
