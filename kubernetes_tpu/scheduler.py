"""The scheduler: cache + queue + device pipeline + binding, wired.

The batched counterpart of pkg/scheduler/scheduler.go + schedule_one.go:
``Scheduler.schedule_pending()`` pops a whole batch in queue order, brings
the device mirror up to date (incremental, generation-gated), runs ONE
fused gang dispatch (sequential-equivalent — decisions identical to the
reference's one-pod-at-a-time loop), then walks the per-pod results through
assume → reserve → permit → bind exactly like schedulingCycle/bindingCycle
(schedule_one.go:135-340).

API access is abstracted behind ``ClusterSource`` (list/watch events in) and
the handle's ``bind`` (writes out) — a fake in-process implementation lives
in kubernetes_tpu.testing; a real client would speak the same interface.
"""

from __future__ import annotations

import logging
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.analysis import sanitizer
from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.cache import Cache, SnapshotMirror
from kubernetes_tpu.framework import config as cfg
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    Code,
    CycleState,
    EventResource,
    Status,
)
from kubernetes_tpu.framework.registry import Registry, default_registry
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL
from kubernetes_tpu.oracle.state import NodeState, OracleState
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.queue.nominator import Nominator
from kubernetes_tpu.snapshot.interner import PAD
from kubernetes_tpu.snapshot.schema import bucket_cap, pack_pod_batch
from kubernetes_tpu.workloads import gang as wlg

logger = logging.getLogger(__name__)

# Lock-discipline registry read by kubernetes_tpu.analysis (AST-only — the
# analyzer literal-evals this without importing the module).  Fields listed
# under "guards" may only be mutated while holding Scheduler._mu; methods in
# "requires_lock" are entered with the lock already held (the analyzer
# verifies every caller), same contract as the *_under_lock name suffix.
_KTPU_GUARDED = {
    "Scheduler": {
        "lock": "_mu",
        "guards": {
            "cache": "Cache",
            "queue": "SchedulingQueue",
            "mirror": "SnapshotMirror",
            "nominator": "Nominator",
            "_external_mutations": None,
            "_oracle_cache": None,
            "_nonfast_commits": None,
            "metrics": None,
            # PodGroup registry + gang bookkeeping (workloads/gang.py):
            # mutated by informer handlers, the workloads dispatch, and
            # bind-failure unwinds — all under _mu
            "gangs": "GangDirectory",
        },
        "requires_lock": [
            "_view_pod_added",
            "_view_pod_removed",
            "_invalidate_view",
            "_is_confirmation",
            "_repack_mirror",
            "_sync_mirror_external",
            "_wave_tables",
            "_hostnames_unique",
            "_pull_gang_siblings",
        ],
    },
    "Nominator": {
        "external_lock": "Scheduler._mu",
        "readonly": ["entries", "pods_for_node", "nominated_node"],
    },
}

_MISSING = object()  # dict-miss sentinel (cached signature keys can be None)

# One immutable success Status shared by bulk commits: success statuses are
# never mutated anywhere (failure paths REPLACE outcome.status wholesale).
STATUS_SUCCESS = Status.success()


@dataclass
class _BindTask:
    """One pod's buffered binding cycle (the goroutine-per-pod payload)."""

    fwk: object
    state: object
    qp: object
    node_name: str
    waited: bool
    binder_override: object
    outcome: "ScheduleOutcome"
    lean: bool = False

    def lean_eligible(self) -> bool:
        return self.lean and not self.waited and self.binder_override is None


@dataclass
class _BulkBindTask:
    """A contiguous run of LEAN fast-path binding cycles: one worker
    submit, one sink write (bulk when the API tier installed one), one
    lock acquisition for the whole post-bind bookkeeping tail.  Built only
    by _commit_fast_bulk, whose gate proved every per-pod extension-point
    walk a no-op for these pods."""

    fwk: object
    state: object
    items: list  # [(qp, node_name, outcome)]


@dataclass
class ScheduleOutcome:
    pod: Pod
    node: Optional[str]
    status: Status
    n_feasible: int = 0
    # plugin name → count of nodes it rejected (Diagnosis.NodeToStatus
    # aggregate, framework/types.go:367)
    diagnosis: Optional[Dict[str, int]] = None
    # metrics context (pod_scheduling_sli/attempts series).  The SLI
    # duration derives from the MONOTONIC pair (a wall/manual-clock jump
    # must not skew it); the queue-clock stamp stays for display/ordering.
    pod_attempts: int = 1
    first_enqueue_time: Optional[float] = None
    first_enqueue_mono: Optional[float] = None


# FitError reason strings keyed by diagnosis kernel (types.go:420-465 /
# the per-plugin ErrReason constants).
_DIAG_REASONS = {
    "NodeUnschedulable": "node(s) were unschedulable",
    "NodeName": "node(s) didn't match the requested node name",
    "TaintToleration": "node(s) had untolerated taints",
    "NodeAffinity": "node(s) didn't match Pod's node affinity/selector",
    "NodePorts": "node(s) didn't have free ports for the requested pod ports",
    "HostFilters": "node(s) were rejected by host filter plugins",
    "NodeResourcesFit": "node(s) had insufficient resources",
    "PodTopologySpread": "node(s) didn't match pod topology spread constraints",
    "InterPodAffinity": "node(s) didn't satisfy inter-pod affinity/anti-affinity rules",
}


def fit_error_message(num_nodes: int, diagnosis: Dict[str, int]) -> str:
    """FitError.Error() shape: '0/N nodes are available: <reasons>.'"""
    if not diagnosis:
        return f"0/{num_nodes} nodes are available"
    parts = [
        f"{c} {_DIAG_REASONS.get(k, k)}"
        for k, c in sorted(diagnosis.items(), key=lambda kv: -kv[1])
    ]
    return f"0/{num_nodes} nodes are available: " + ", ".join(parts)


class Handle:
    """framework.Handle analogue — what plugins see of the scheduler."""

    def __init__(self, scheduler: "Scheduler"):
        self._s = scheduler

    def bind(self, pod: Pod, node_name: str) -> None:
        self._s.binding_sink(pod, node_name)

    # -- storage listers / assume caches (scheduler.go:298-302) -------------

    @property
    def pv_cache(self):
        return self._s.pv_cache

    @property
    def pvc_cache(self):
        return self._s.pvc_cache

    @property
    def claim_cache(self):
        return self._s.claim_cache

    def get_storage_class(self, name: str):
        return self._s.storage_classes.get(name)

    def get_csinode(self, name: str):
        return self._s.csinodes.get(name)

    def get_csi_driver(self, name: str):
        return self._s.csidrivers.get(name)

    def list_capacities(self):
        return list(self._s.capacities.values())

    def list_resource_slices(self):
        return list(self._s.resource_slices.values())

    def get_device_class(self, name: str):
        return self._s.device_classes.get(name)

    def write_pv(self, pv) -> None:
        self._s.pv_writer(pv)

    def write_pvc(self, pvc) -> None:
        self._s.pvc_writer(pvc)

    def write_claim(self, claim) -> None:
        self._s.claim_writer(claim)

    def oracle_state(self) -> OracleState:
        return self._s.oracle_view()

    @property
    def nominator(self) -> Nominator:
        return self._s.nominator

    def delete_pod(self, pod: Pod) -> None:
        """Victim eviction — the preemption API write (preemption.go:380)."""
        self._s.pod_deleter(pod)

    def list_pdbs(self):
        return self._s.pdb_lister()

    def framework_for(self, pod: Pod):
        return self._s.profiles.get(pod.scheduler_name)


    def list_extenders(self):
        return list(self._s.extenders)

    @property
    def prom(self):
        return getattr(self._s, "prom", None)

    def get_waiting_pod(self, uid: str):
        for fwk in self._s.profiles.values():
            wp = fwk.waiting_pods.get(uid)
            if wp is not None:
                return wp
        return None

    def activate(self, pods) -> None:
        with self._s._mu:
            self._s.queue.activate(pods)

    def recorder_for(self, pod: Pod):
        """The profile's event recorder (framework.Handle EventRecorder)."""
        from kubernetes_tpu.events import NullRecorder

        return self._s.recorders.get(pod.scheduler_name) or NullRecorder()


class Scheduler:
    def __init__(
        self,
        configuration: Optional[cfg.SchedulerConfiguration] = None,
        registry: Optional[Registry] = None,
        binding_sink=None,
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        clock=time.monotonic,
        extenders=None,
        event_broadcaster=None,
        profile_dir: Optional[str] = None,
    ):
        self.config = configuration or cfg.SchedulerConfiguration()
        self.config.validate()
        from kubernetes_tpu.extender import build_extenders

        # HTTP extenders from config + injected in-proc extenders (the
        # fake-extender test pattern, testing/framework/fake_extender.go)
        self.extenders = build_extenders(self.config.extenders) + list(
            extenders or []
        )
        self.binding_sink = binding_sink or (lambda pod, node: None)
        # optional BULK sink ([(pod, node)] → per-item error or None); the
        # API tier installs it so a chunk's bindings ride one write
        self.binding_sink_many = None
        self.pod_deleter = lambda pod: None  # victim eviction sink
        self.pdb_lister = lambda: []
        self.status_patcher = lambda pod: None  # pod status writes (nomination)
        self.namespace_labels = namespace_labels or {}
        self.clock = clock

        self.cache = Cache()
        self.mirror = SnapshotMirror()
        from kubernetes_tpu.cache.device_mirror import DeviceClusterCache

        # Mesh-partitioned dispatch (MULTICHIP.md): resolve the
        # ('pods','nodes') mesh once per scheduler.  meshDispatch None =
        # AUTO — partition whenever the backend exposes >1 device; the
        # admission engine's decisions are bit-identical either way
        # (multichip_vs_singlechip paritycheck), the mesh only changes
        # where the flops run.
        from kubernetes_tpu.parallel import mesh as pmesh

        mesh_on = self.config.mesh_dispatch
        if mesh_on is None:
            mesh_on = pmesh.auto_enabled()
        self.mesh = (
            pmesh.make_mesh(pods_axis=self.config.mesh_pods_axis)
            if mesh_on
            else None
        )
        if self.mesh is not None:
            # every node pack must split evenly over the nodes axis
            # (cluster_shardings asserts; pack_nodes pads)
            self.mirror.node_pad_multiple = self.mesh.shape["nodes"]

        self._dc_cache = DeviceClusterCache(mesh=self.mesh)
        self._p_cap_max = 1  # sticky batch bucket: avoids per-size recompiles
        if self.mesh is not None:
            # pod buckets must split evenly over the pods axis — seed the
            # sticky bucket so bucket_cap(n, 1) growth stays a multiple
            # (power-of-two buckets ≥ a power-of-two axis always divide;
            # non-power-of-two axes ride pad_to_multiple)
            self._p_cap_max = pmesh.pad_to_multiple(
                bucket_cap(self.mesh.shape["pods"], 1),
                self.mesh.shape["pods"],
            )
        self.nominator = Nominator()
        # Async binding pipeline (schedule_one.go:117-129): the scheduling
        # loop stops at assume+reserve+permit; wait/prebind/bind/postbind run
        # on worker threads against the assumed cache state, overlapping the
        # next batch's device dispatch.  self._mu is the cache.mu analogue —
        # every cache/queue mutation (informer handlers, commits, unwinds)
        # holds it; the device dispatch and bind RTTs run outside it.
        self._mu = threading.RLock()
        # KTPU_SANITIZE=1: lock-ownership probes at the annotated mutation
        # sites + the post-drain mirror-consistency check.  Captured once
        # per scheduler so the per-POD commit probe is a plain attribute
        # branch, not a function call, when the mode is off.
        self._sanitize = sanitizer.enabled()
        if self._sanitize:
            # the cache carries a backref to the guarding lock so its own
            # assert_owned works without knowing about the scheduler
            self.cache._ktpu_lock = self._mu
        self._bind_pool: Optional[ThreadPoolExecutor] = None
        self._inflight_binds: List = []
        self._bind_buffer: List = []
        self._bulk_bind_buffer: List = []  # _BulkBindTask runs (fast path)
        # chained-dispatch state (see _try_dispatch_chained)
        self._chain = None

        # storage/DRA object views: assume caches for the objects plugins
        # optimistically mutate (PV/PVC/ResourceClaim, scheduler.go:298-302),
        # plain lister maps for the rest
        from kubernetes_tpu.util.assumecache import AssumeCache

        self.pv_cache = AssumeCache("persistent volumes")
        self.pvc_cache = AssumeCache("persistent volume claims")
        self.claim_cache = AssumeCache("resource claims")
        self.storage_classes: Dict[str, object] = {}
        self.csinodes: Dict[str, object] = {}
        self.csidrivers: Dict[str, object] = {}
        self.capacities: Dict[str, object] = {}
        self.resource_slices: Dict[str, object] = {}
        self.device_classes: Dict[str, object] = {}
        # gang/coscheduling tier: PodGroup registry + quorum bookkeeping
        # (workloads/gang.py; fed by the POD_GROUP informer or directly)
        self.gangs = wlg.GangDirectory(clock=clock)
        self.pv_writer = lambda pv: None
        self.pvc_writer = lambda pvc: None
        self.claim_writer = lambda claim: None

        # Event recorders, one per profile (profile.go:86) — NullRecorder
        # when no broadcaster is wired (bare unit-test Schedulers).
        from kubernetes_tpu.events import NullRecorder

        self.event_broadcaster = event_broadcaster
        self.recorders: Dict[str, object] = {}
        for p in self.config.profiles:
            self.recorders[p.scheduler_name] = (
                event_broadcaster.new_recorder(p.scheduler_name)
                if event_broadcaster is not None
                else NullRecorder()
            )

        handle = Handle(self)
        reg = registry or default_registry()
        self.profiles: Dict[str, Framework] = {
            p.scheduler_name: Framework(
                p, reg, handle, feature_gates=self.config.feature_gates
            )
            for p in self.config.profiles
        }

        # queueing hints: union over profiles (eventhandlers.go:431)
        hints: Dict[str, list] = {}
        for fwk in self.profiles.values():
            for name, evs in fwk.events_to_register().items():
                hints.setdefault(name, []).extend(evs)
        # gang barrier rejections ("waiting for members" / rollback / quorum
        # timeout) requeue on PodGroup events — the workloads dispatch fires
        # a synthetic one when a missing member finally arrives (the
        # coscheduling plugin's Pod-Add EventsToRegister analogue)
        from kubernetes_tpu.framework.interface import ClusterEventWithHint

        hints.setdefault("Coscheduling", []).append(
            ClusterEventWithHint(
                ClusterEvent(
                    EventResource.POD_GROUP,
                    ActionType.ADD | ActionType.UPDATE,
                )
            )
        )

        def pre_enqueue(pod: Pod):
            # PreEnqueue runs under the pod's OWN profile
            # (schedule_one.go:376 frameworkForPod).
            fwk = self.profiles.get(pod.scheduler_name)
            return fwk.run_pre_enqueue(pod) if fwk is not None else None

        # One queue serves all profiles, ordered by the QueueSort of the
        # first profile — the reference requires every profile to configure
        # the SAME QueueSort (apis/config/validation) and builds the activeQ
        # on its Less (scheduler.go:340).
        qs_names = {
            (fwk.queue_sort.name if fwk.queue_sort else None)
            for fwk in self.profiles.values()
        }
        if len(qs_names) > 1:
            raise ValueError(
                f"all profiles must use the same QueueSort plugin, got {qs_names}"
            )
        first = self.profiles[self.config.profiles[0].scheduler_name]
        less_fn = key_fn = None
        if first.queue_sort is not None:
            qs = first.queue_sort
            less_fn = lambda a, b: qs.less(a, b)  # noqa: E731
            # QueueSort plugins exposing a tuple sort_key consistent with
            # less() give the activeQ C-speed heap comparisons.  Only honor
            # sort_key when it is defined at (or below) the class that
            # defines less — a subclass overriding less() alone must not
            # inherit the base's now-inconsistent key.
            qs_cls = type(qs)
            def_sort = next(
                (c for c in qs_cls.__mro__ if "sort_key" in c.__dict__), None
            )
            def_less = next(
                (c for c in qs_cls.__mro__ if "less" in c.__dict__), None
            )
            if (
                def_sort is not None
                and def_less is not None
                and issubclass(def_sort, def_less)
            ):
                key_fn = qs.sort_key

        self.queue = SchedulingQueue(
            less_fn=less_fn,
            queueing_hints=hints,
            pre_enqueue_check=pre_enqueue,
            initial_backoff_s=self.config.pod_initial_backoff_seconds,
            max_backoff_s=self.config.pod_max_backoff_seconds,
            clock=clock,
            key_fn=key_fn,
        )
        from kubernetes_tpu.metrics import PhaseAccumulator, SchedulerMetrics

        self.prom = SchedulerMetrics()
        if self._sanitize:
            sanitizer.register_counter(self.prom.sanitizer_violations)
            # retrace hook: post-warmup compilation-cache misses land in
            # scheduler_tpu_jit_recompiles_total{fn=} once a caller marks
            # the warm watermark (sanitizer.mark_jit_warm)
            sanitizer.register_recompile_counter(self.prom.jit_recompiles)
            sanitizer.install_retrace_hook()
            # eval_shape cross-check failures (run once per process at
            # the first sanitized drain) land in
            # scheduler_tpu_shape_check_failures_total{fn=}
            sanitizer.register_shape_counter(self.prom.shape_check_failures)
        # Per-phase hot-loop attribution (queue_pop/pack/h2d/device/d2h/
        # commit/bind) — the scheduler_perf-style breakdown bench.py emits
        # as config0_phases.  Feeds the phase_duration histogram too.
        self.phases = PhaseAccumulator(hist=self.prom.phase_duration)
        # Observability layer (observability/): span tracer (off until
        # /debug/trace?action=start — a disabled tracer is one attribute
        # read per site, zero device-path cost) + per-pod flight recorder
        # (bounded ring, on by default).  The phase accumulator doubles as
        # the tracer's phase-span feed; the queue records its own
        # enqueue/pop/requeue breadcrumbs.
        from kubernetes_tpu.observability import FlightRecorder, Tracer

        self.tracer = Tracer()
        self.flight = FlightRecorder()
        self.phases.tracer = self.tracer
        self.queue.flight = self.flight
        # steady-state SLO tier (observability/slo.py) — None until
        # install_slo wires it; /debug/slo serves {"enabled": false} then
        self.slo = None
        # control-plane pipeline tier (observability/controlplane.py) —
        # None until install_controlplane; every producer site below is
        # one attribute read + None check when off
        self.controlplane = None
        # device telemetry ledger (observability/kernels.py): per-kernel
        # dispatch/compile/d2h accounting over every registered jit root,
        # plus the execute-time regression sentinel (breaches reuse the
        # SLO tier's black-box freeze→dump).  The root wrappers are
        # process-global; dispatches route to the ACTIVE ledger, d2h
        # attribution records into THIS scheduler's ledger exactly.
        from kubernetes_tpu.observability import kernels as kernels_mod

        self_ref = weakref.ref(self)

        def _slo_of():
            s = self_ref()
            return s.slo if s is not None else None

        self.kernels = kernels_mod.DispatchLedger(
            prom=self.prom, tracer=self.tracer, slo_getter=_slo_of
        )
        if getattr(self.config, "kernel_ledger", True):
            kernels_mod.install()
            kernels_mod.activate(self.kernels)
        else:
            self.kernels.enabled = False
        self._batch_seq = 0  # trace batch ids (scheduling-loop thread only)
        # jax.profiler trace hook (SURVEY §5; the --profiling/pprof analog,
        # apis/config/types.go:60): when set, schedule_pending wraps each
        # drain in jax.profiler.trace(profile_dir).
        import os as _os

        self.profile_dir = profile_dir or _os.environ.get("KTPU_PROFILE_DIR")
        self._profiling = False  # reentrancy guard (nested drains)
        self.queue.incoming_counter = self.prom.queue_incoming_pods
        self._dirty_pending = False
        self._oracle_cache: Optional[OracleState] = None
        # bumped on every EXTERNAL node-state mutation (informer events,
        # forgets) — NOT on this scheduler's own commits, which the fast
        # committer already tracks itself
        self._external_mutations = 0
        self.metrics: Dict[str, float] = {
            "schedule_attempts": 0,
            "scheduled": 0,
            "unschedulable": 0,
            "errors": 0,
            "fast_batches": 0,
            "scan_batches": 0,
            "wave_batches": 0,
            "wave_pods": 0,
            "wave_admitted": 0,
            "resident_batches": 0,
            "resident_pods": 0,
            "resident_rounds": 0,
            "workload_batches": 0,
            "workload_spec_admitted": 0,
            "gang_admitted": 0,
            "gang_rolled_back": 0,
            "dra_pods": 0,
            "dra_claims_allocated": 0,
        }

    # ----- event handlers (eventhandlers.go:345-428) ------------------------

    def on_node_add(self, node: Node) -> None:
        with self._mu:
            cp = self.controlplane
            if cp is not None and cp.enabled:
                cp.note_applied()
            self._invalidate_view()
            self._external_mutations += 1
            self.cache.add_node(node)
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.NODE, ActionType.ADD), None, node
            )

    def on_node_update(self, old: Node, new: Node) -> None:
      with self._mu:
        cp = self.controlplane
        if cp is not None and cp.enabled:
            cp.note_applied()
        import copy as _copy

        probe = _copy.copy(old)
        probe.ready = new.ready
        probe.last_heartbeat = new.last_heartbeat
        if probe == new:
            # heartbeat-only update (Ready condition / lastHeartbeatTime):
            # nothing the snapshot or queue reads moved — refresh the cache
            # object without invalidating the device pipeline, or 5000
            # kubelets heartbeating would repack the mirror continuously.
            # (Full-equality probe, not a field allowlist: a change to ANY
            # other Node field — present or future — takes the safe path.)
            cn = self.cache.nodes.get(new.name)
            if cn is not None and cn.node is not None:
                cn.node = new
                return
        self._invalidate_view()
        self._external_mutations += 1
        self.cache.update_node(new)
        action = ActionType(0)
        if old.labels != new.labels:
            action |= ActionType.UPDATE_NODE_LABEL
        if old.taints != new.taints or old.unschedulable != new.unschedulable:
            action |= ActionType.UPDATE_NODE_TAINT
        if (
            old.allocatable.milli_cpu != new.allocatable.milli_cpu
            or old.allocatable.memory != new.allocatable.memory
            or old.allocatable.scalars != new.allocatable.scalars
        ):
            action |= ActionType.UPDATE_NODE_ALLOCATABLE
        if action:
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.NODE, action), old, new
            )

    def on_node_delete(self, node: Node) -> None:
      with self._mu:
        cp = self.controlplane
        if cp is not None and cp.enabled:
            cp.note_applied()
        self._invalidate_view()
        self._external_mutations += 1
        self.cache.remove_node(node.name)
        self.queue.move_all_on_event(
            ClusterEvent(EventResource.NODE, ActionType.DELETE), node, None
        )

    def _is_confirmation(self, pod: Pod) -> bool:
        """True when the event is the informer CONFIRMING our assumed pod
        unchanged — same node AND same labels AND same deletionTimestamp
        (the _adopt_equivalent field set): only then may the chain epoch
        and device mirror treat it as a no-op (cache.go:484)."""
        if pod.uid not in self.cache.assumed:
            return False
        ps = self.cache.pod_states.get(pod.uid)
        return (
            ps is not None
            and ps.pod.node_name == pod.node_name
            and ps.pod.labels == pod.labels
            and ps.pod.deletion_timestamp == pod.deletion_timestamp
            # requests too: in-place pod resize can mutate the spec while
            # node/labels stay equal — the view must be repatched then
            and ps.pod.compute_requests() == pod.compute_requests()
        )

    def on_pod_add(self, pod: Pod) -> None:
      with self._mu:
        cp = self.controlplane
        if cp is not None and cp.enabled:
            cp.note_applied()
            if not pod.node_name:
                # the informer_handler hop: stamped ahead of queue.add so
                # the chain orders informer_handler < enqueue
                cp.note_pod_handled(pod.uid)
        if pod.node_name:
            self.gangs.note_placed(pod)
            # Confirmation of OUR assumed pod on the same node changes no
            # capacity state (the assume already counted it) — don't treat
            # it as an external mutation (cache.go:484 reconciliation).
            ps = self.cache.pod_states.get(pod.uid)
            confirmed = self._is_confirmation(pod)
            if not confirmed:
                self._external_mutations += 1
                if ps is None:
                    self._view_pod_added(pod)
                elif ps.pod.node_name == pod.node_name:
                    self._view_pod_removed(ps.pod)
                    self._view_pod_added(pod)
                else:
                    self._invalidate_view()
            self.cache.add_pod(pod)
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.ADD),
                None,
                pod,
            )
        elif self._responsible_for(pod):
            self.queue.add(pod)
            # a new member can complete a waiting gang's quorum — kick its
            # siblings out of the unschedulable pool via the group event
            key = wlg.group_key_of(pod)
            if key is not None:
                pg = self.gangs.get(key)
                if pg is not None:
                    self.queue.move_all_on_event(
                        ClusterEvent(
                            EventResource.POD_GROUP, ActionType.UPDATE
                        ),
                        pg,
                        pg,
                    )

    def on_pod_update(self, old: Pod, new: Pod) -> None:
      with self._mu:
        cp = self.controlplane
        if cp is not None and cp.enabled:
            cp.note_applied()
        if new.node_name:
            self.gangs.note_placed(new)
            ps = self.cache.pod_states.get(new.uid)
            if (
                ps is not None
                and ps.pod.node_name == new.node_name
                # an ASSUMED pod's echo is the binding CONFIRMATION — it
                # must take the full path (assumed → added transition)
                and new.uid not in self.cache.assumed
            ):
                import copy as _copy

                probe = _copy.copy(old)
                probe.phase = new.phase
                probe.start_time = new.start_time
                probe.node_name = new.node_name
                if probe == new:
                    # STATUS-only update of a pod we already account on
                    # that node (the kubelet's phase=Running report):
                    # nothing packed in the snapshot reads phase/startTime
                    # — swap the stored object without invalidating the
                    # device pipeline, or every kubelet status report
                    # would force a mirror repack mid-drain
                    cn = self.cache.nodes.get(new.node_name)
                    if cn is not None and new.uid in cn.pods:
                        cn.pods[new.uid] = new
                        ps.pod = new
                        return
            confirmed = (
                self._is_confirmation(new) and old.labels == new.labels
            )
            if not confirmed:
                self._external_mutations += 1
                if ps is not None and ps.pod.node_name == new.node_name:
                    self._view_pod_removed(ps.pod)
                    self._view_pod_added(new)
                elif ps is None and not old.node_name:
                    self._view_pod_added(new)
                else:
                    self._invalidate_view()
            if old.node_name:
                self.cache.update_pod(old, new)
            else:
                self.cache.add_pod(new)
                # the pod was assigned by SOMEONE ELSE (another scheduler —
                # the HA standby case) while still sitting in our queue:
                # the reference's unassigned-pod informer sees this
                # transition as a delete from the scheduling queue
                # (eventhandlers.go assignedPod split) — without it the
                # standby would later pop and re-schedule a bound pod
                self.queue.delete(new)
            action = ActionType(0)
            if old.labels != new.labels:
                action |= ActionType.UPDATE_POD_LABEL
            if action:
                self.queue.move_all_on_event(
                    ClusterEvent(EventResource.ASSIGNED_POD, action), old, new
                )
        else:
            self.queue.update(old, new)

    def on_pod_delete(self, pod: Pod) -> None:
      with self._mu:
        cp = self.controlplane
        if cp is not None and cp.enabled:
            cp.note_applied()
        self.gangs.note_removed(pod)
        if pod.node_name:
            self._external_mutations += 1
            ps = self.cache.pod_states.get(pod.uid)
            self._view_pod_removed(
                ps.pod if ps is not None else pod,
                ps.pod.node_name if ps is not None else None,
            )
            self.cache.remove_pod(pod)
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE),
                pod,
                None,
            )
        else:
            self.queue.delete(pod)
        self.nominator.delete(pod)

    def _responsible_for(self, pod: Pod) -> bool:
        return pod.scheduler_name in self.profiles

    def storage_handlers(self, resource: EventResource):
        """(add, update, delete) informer handlers for a storage/DRA
        resource kind — feed the right cache, then requeue through the
        queueing-hint machinery (the dynamic per-GVK handlers of
        eventhandlers.go:431-602)."""
        assume_caches = {
            EventResource.PV: self.pv_cache,
            EventResource.PVC: self.pvc_cache,
            EventResource.RESOURCE_CLAIM: self.claim_cache,
        }
        lister_maps = {
            EventResource.STORAGE_CLASS: self.storage_classes,
            EventResource.CSI_NODE: self.csinodes,
            EventResource.CSI_DRIVER: self.csidrivers,
            EventResource.CSI_STORAGE_CAPACITY: self.capacities,
            EventResource.RESOURCE_SLICE: self.resource_slices,
            EventResource.DEVICE_CLASS: self.device_classes,
        }
        cache = assume_caches.get(resource)
        lister = lister_maps.get(resource)

        is_pod_group = resource == EventResource.POD_GROUP

        def on_add(obj):
            with self._mu:
                if cache is not None:
                    cache.on_add(obj)
                if lister is not None:
                    lister[obj.key] = obj
                if is_pod_group:
                    self.gangs.upsert(obj)
                self.queue.move_all_on_event(
                    ClusterEvent(resource, ActionType.ADD), None, obj
                )

        def on_update(old, new):
            with self._mu:
                if cache is not None:
                    cache.on_update(old, new)
                if lister is not None:
                    lister[new.key] = new
                if is_pod_group:
                    self.gangs.upsert(new)
                self.queue.move_all_on_event(
                    ClusterEvent(resource, ActionType.UPDATE), old, new
                )

        def on_delete(obj):
            with self._mu:
                if cache is not None:
                    cache.on_delete(obj)
                if lister is not None:
                    lister.pop(obj.key, None)
                if is_pod_group:
                    self.gangs.delete(obj.key)
                self.queue.move_all_on_event(
                    ClusterEvent(resource, ActionType.DELETE), obj, None
                )

        return on_add, on_update, on_delete

    # ----- views ------------------------------------------------------------

    def _invalidate_view(self) -> None:
        self._oracle_cache = None

    # Incremental view maintenance: pod-level cache mutations patch the
    # cached OracleState in place instead of discarding it — a full rebuild
    # is O(all pods) and preemption storms mutate once per eviction
    # (the r3 bench spent ~6s/500 preempts rebuilding).  Node-level events
    # still invalidate.  Any surprise (unknown node, uid miss) falls back
    # to invalidation, so correctness never depends on these paths.

    def _view_pod_added(self, pod: Pod) -> None:
        st = self._oracle_cache
        if st is None:
            return
        ns = st.nodes.get(pod.node_name)
        if ns is None:
            self._oracle_cache = None
            return
        ns.add_pod(pod)

    def _view_pod_removed(self, pod: Pod, node_name: Optional[str] = None) -> None:
        st = self._oracle_cache
        if st is None:
            return
        ns = st.nodes.get(node_name or pod.node_name)
        if ns is None or not ns.remove_pod(pod):
            self._oracle_cache = None

    def oracle_view(self) -> OracleState:
        """Host-object view of the cache for host-backed plugins/oracle.
        Cached until any cache mutation (informer event, assume/forget) —
        a batch's PostFilter calls share one build."""
        with self._mu:
            if self._oracle_cache is None:
                st = OracleState(namespace_labels=self.namespace_labels)
                for cn in self.cache.real_nodes():
                    ns = NodeState(node=cn.node)
                    for p in cn.pods.values():
                        ns.add_pod(p)
                    st.nodes[cn.node.name] = ns
                self._oracle_cache = st
            return self._oracle_cache

    # ----- the scheduling loop ---------------------------------------------

    def schedule_pending(self, max_batches: Optional[int] = None) -> List[ScheduleOutcome]:
        """Drain the active queue in gang batches; returns all outcomes.

        With ``profile_dir`` set (ctor arg or KTPU_PROFILE_DIR), the whole
        drain runs under ``jax.profiler.trace`` — one xplane artifact per
        drain, the device-dispatch answer to scheduler_perf's -cpuprofile.
        """
        if self.profile_dir and not self._profiling:
            import jax.profiler as _jprof

            self._profiling = True
            try:
                with _jprof.trace(self.profile_dir):
                    return self._schedule_pending_impl(max_batches)
            finally:
                self._profiling = False
        return self._schedule_pending_impl(max_batches)

    def _schedule_pending_impl(
        self, max_batches: Optional[int] = None
    ) -> List[ScheduleOutcome]:
        outcomes: List[ScheduleOutcome] = []
        batches = 0
        tr = self.tracer
        # None (not 0.0) when tracing was off at drain start: a trace
        # STARTED mid-drain must not produce a span with a garbage origin
        t_drain = tr.now() if tr.enabled else None
        # Pre-size the placed-pod tensor axes for the whole drain: every
        # distinct shape costs an XLA recompile of the gang pipeline.  One
        # extra batch of margin covers the chained append's bucket-stride
        # padding on the final partial batch.
        with self._mu:
            self.mirror.e_cap_hint = max(
                self.mirror.e_cap_hint,
                len(self.cache.pod_states)
                + len(self.queue)
                + self.config.batch_size,
            )
        from collections import deque

        pending: deque = deque()  # pipelined batches awaiting result harvest

        def flush(keep: int = 0) -> None:
            while len(pending) > keep:
                rec = pending.popleft()
                if rec.get("kind") == "fast":
                    outcomes.extend(self._finish_fast(rec))
                else:
                    outcomes.extend(self._finish_chained(rec))

        while True:
            t_pop = time.perf_counter()
            with self._mu:
                batch = self.queue.pop_batch(self.config.batch_size)
                if batch and self.config.gang_dispatch:
                    # gang sibling-pull: a gang split across pop batches
                    # previously converged by waiting-retry; pull its
                    # ready members into THIS batch so quorum is judged
                    # once (PR 10 remainder; cheap for gang-free batches)
                    batch.extend(self._pull_gang_siblings(batch))
            self.phases.add("queue_pop", time.perf_counter() - t_pop)
            if not batch:
                break
            # Segregate by profile (schedule_one.go:376-382): each group
            # runs ONE gang dispatch under its own framework's plugin set.
            groups: Dict[str, list] = {}
            for qp in batch:
                groups.setdefault(qp.pod.scheduler_name, []).append(qp)
            for profile_name, group in groups.items():
                fwk = self.profiles.get(
                    profile_name, next(iter(self.profiles.values()))
                )
                rec = None
                if self._chain_quickcheck(fwk, group):
                    rec = self._try_dispatch_chained(
                        fwk, group, outcomes, can_restart=not pending
                    )
                    if rec == "flush":
                        flush(0)
                        rec = self._try_dispatch_chained(
                            fwk, group, outcomes, can_restart=True
                        )
                if isinstance(rec, tuple) and rec and rec[0] == "serial":
                    # breaker fallback for an abandoned chained dispatch:
                    # settle the pipeline (its commits must land first),
                    # then drain the live batch serially OUTSIDE the
                    # scheduler lock
                    flush(0)
                    t0 = time.perf_counter()
                    outs = self._schedule_batch_serial(fwk, rec[1])
                    self._record_batch_metrics(
                        profile_name, rec[1], outs, time.perf_counter() - t0
                    )
                    outcomes.extend(outs)
                    continue
                if isinstance(rec, dict):
                    # pipelined: keep up to two batches in flight so the
                    # harvest of batch k overlaps k+1's device compute AND
                    # k+2's dispatch (the async result copy finishes before
                    # the blocking fetch).  With Reserve/Permit plugins in
                    # play a commit can realistically fail (and forget), so
                    # harvest eagerly — one batch in flight — to keep the
                    # optimism window close to the reference's (a forget is
                    # visible to the very next scheduling cycle).  When every
                    # Reserve/Permit plugin is also a host Filter the gate
                    # already proved irrelevant (the default volumebinding/
                    # DRA shape), their walks are no-ops for these batches —
                    # keep the full two-deep double buffer.
                    pending.append(rec)
                    flush(1 if self._rp_can_fail(fwk) else 2)
                    continue
                if rec == "handled":
                    continue
                # pipelined fast path: same ≤2-in-flight discipline as the
                # chain — the sig_scan kernel's state chains on device, so
                # the harvest of batch k overlaps k+1's dispatch and the
                # device link's round trip hides behind host work
                frec = self._try_dispatch_fast(
                    fwk,
                    group,
                    outcomes,
                    chain_settled=not any(
                        r.get("kind") != "fast" for r in pending
                    ),
                    pipeline_empty=not pending,
                )
                if frec == "flush":
                    flush(0)
                    frec = self._try_dispatch_fast(
                        fwk, group, outcomes, chain_settled=True
                    )
                if isinstance(frec, dict):
                    pending.append(frec)
                    if frec.get(
                        "rstats_dev"
                    ) is not None and not getattr(
                        self.config, "resident_serial_tail", False
                    ):
                        # a resident run may finish its conflict tail on
                        # the HOST committer, after which the chained
                        # device state is stale — harvest immediately so
                        # no later dispatch rides a state that a host
                        # tail is about to overtake
                        flush(0)
                    else:
                        flush(1 if self._rp_can_fail(fwk) else 2)
                    continue
                if frec == "handled":
                    continue
                # direct path: settle the pipeline first — its commits must
                # land before a non-chained dispatch reads host state — and
                # drop the chain (these commits happen outside it)
                flush(0)
                self._chain = None
                t0 = time.perf_counter()
                outs = self._schedule_batch(group)
                dt = time.perf_counter() - t0
                self._record_batch_metrics(profile_name, group, outs, dt)
                outcomes.extend(outs)
            # hand this batch's buffered binds to the workers — they overlap
            # the next batch's device dispatch (the async binding pipeline)
            self._flush_binds()
            batches += 1
            if max_batches is not None and batches >= max_batches:
                break
        flush(0)
        # End-of-drain barrier: binding cycles of the LAST batches may still
        # be in flight (they overlapped the later dispatches); callers read
        # final outcomes, so settle them here.  Failed binds have been
        # requeued with backoff by now — they surface on a later drain,
        # exactly like the reference's retry flow.
        self.wait_for_bindings()
        if self._sanitize:
            # KTPU_SANITIZE drift probe: every usage row the mirror claims
            # current must match a fresh recomputation from the cache
            with self._mu:
                sanitizer.check_mirror_consistency(self.cache, self.mirror)
            # one-shot per process: the symbolic shape interpreter's root
            # summaries must agree with jax.eval_shape on representative
            # instantiations (mismatches count into the shape_check metric)
            sanitizer.check_root_shapes()
        if t_drain is not None and tr.enabled:
            tr.complete(
                "drain",
                t_drain,
                cat="drain",
                pods=len(outcomes),
                batches=batches,
                scheduled=sum(1 for o in outcomes if o.node is not None),
            )
        return outcomes

    def _rp_can_fail(self, fwk) -> bool:
        """True when a Reserve/Permit plugin could actually reject a
        pipelined batch's pod — the case that caps the pipeline at one
        batch in flight.  Plugins covered by the host-filter gate are
        no-ops for gated batches (reserve_permit_covered_by_host_filters),
        so the default registry double-buffers at full depth."""
        return (
            fwk.has_reserve_or_permit()
            and not fwk.reserve_permit_covered_by_host_filters()
        )

    def _trace_dispatch(self, kind: str, t0: float, batch, rec=None) -> int:
        """Stamp a monotonically-increasing batch id and — when tracing —
        record the dispatch-half span with pod context (batch id, pod
        count, the first few uids).  Scheduling-loop thread only."""
        self._batch_seq += 1
        bid = self._batch_seq
        if rec is not None:
            rec["bid"] = bid
        cp = self.controlplane
        if cp is not None and cp.enabled:
            # the staleness sentinel samples at every dispatch: how far
            # behind the newest DELIVERED informer event the snapshot this
            # batch scheduled against ran
            cp.note_dispatch(bid)
        tr = self.tracer
        if tr.enabled:
            tr.complete(
                f"dispatch.{kind}",
                t0,
                cat="batch",
                bid=bid,
                pods=len(batch),
                uids=[qp.pod.uid for qp in batch[:8]],
            )
        return bid

    def _d2h(self, value, kernel: Optional[str] = None):
        """Blocking device→host fetch with round-trip accounting: every
        harvest-side ``jax.device_get`` goes through here so
        scheduler_tpu_host_roundtrips_total / d2h_bytes_total measure the
        quantity the resident drain exists to minimize.  ``kernel`` tags
        the fetch with the jit root whose results it harvests — the
        dispatch ledger splits the aggregate bytes per kernel (untagged
        fetches land under ``_untagged`` so the split always sums to the
        total)."""
        led = self.kernels
        t0 = time.perf_counter() if led.enabled else 0.0
        out = jax.device_get(value)
        prom = self.prom
        prom.host_roundtrips.inc()
        nb = sum(
            a.nbytes
            for a in jax.tree_util.tree_leaves(out)
            if hasattr(a, "nbytes")
        )
        prom.d2h_bytes.inc(nb)
        if led.enabled:
            led.record_d2h(kernel, nb, time.perf_counter() - t0)
        return out

    # ----- device-fault tier (ISSUE 15): breaker routing, guarded
    # readbacks, epoch-guarded resync, mesh degradation -----------------------

    def _breaker_blocked(self, kernel: str) -> bool:
        """Routing-gate check against ``kernel``'s circuit breaker: True
        routes the dispatch family to its registered fallback engine
        (kernels._KTPU_BREAKER_FALLBACKS) and counts the event in
        scheduler_tpu_wave_fallback_total{reason="breaker"} — degraded
        placements stay bit-identical (the fallbacks are the engines
        paritycheck certifies), only the flops move."""
        led = self.kernels
        if not led.enabled:
            return False
        if led.breaker_allows(kernel):
            return False
        self.prom.wave_fallback.inc(reason="breaker")
        return True

    def _note_dispatch_failure(self, exc) -> None:
        """Bookkeeping for an abandoned kernel dispatch: log it, count
        the breaker-routed fallback (every fallback site calls this, so
        the wave_fallback{reason="breaker"} series — the engagement
        evidence CHAOS.md and the paritycheck assert lean on — can never
        silently miss a site), and for a mesh device loss re-form the
        mesh before the next dispatch (the current batch rides the
        serial fallback either way)."""
        kind = getattr(exc, "kind", "dispatch_error")
        logger.warning(
            "kernel dispatch abandoned (%s: %s) — batch takes the "
            "fallback engine",
            kind,
            exc,
        )
        self.prom.wave_fallback.inc(reason="breaker")
        if kind == "mesh_device_loss":
            self._degrade_mesh()

    def _degrade_mesh(self) -> bool:
        """A device dropped from the mesh: re-form ``meshDispatch`` on a
        smaller device set — halving, preserving the configured
        meshPodsAxis layout when it still divides — or fall back to
        single-chip, rebuild the device snapshot cache against the new
        placement, and resync the fast lineage's device copy.  Decisions
        are unaffected — the mesh only changes where the flops run
        (multichip_vs_singlechip parity) — so degradation is a pure
        capacity event.  Caveat: jax reports no per-device health, so
        the smaller mesh is drawn from the same device list and may
        still contain the dead chip — the next loss halves again, and
        the floor is always the single-chip engine (then the serial
        oracle under its breaker)."""
        from kubernetes_tpu.cache.device_mirror import DeviceClusterCache
        from kubernetes_tpu.parallel import mesh as pmesh

        with self._mu:
            if self.mesh is None:
                new_mesh = None
            else:
                n = int(self.mesh.devices.size) // 2
                pa = self.config.mesh_pods_axis
                if not (pa and n >= 2 and n % pa == 0):
                    pa = None  # make_mesh default (pods-major, pow2)
                new_mesh = (
                    pmesh.make_mesh(n_devices=n, pods_axis=pa)
                    if n >= 2
                    else None
                )
            self.mesh = new_mesh
            self.mirror.node_pad_multiple = (
                new_mesh.shape["nodes"] if new_mesh is not None else 1
            )
            self._dc_cache = DeviceClusterCache(mesh=new_mesh)
            self._chain = None
            holder = getattr(self, "_fastdev", None)
            if holder is not None:
                # the old placement's device copy is suspect — the host
                # committer stays authoritative; rematerialize on the
                # degraded mesh at the next dispatch
                holder["dev"] = None
                holder["epoch"] = holder.get("epoch", 0) + 1
                holder["dev_sum"] = None
        self.prom.resident_resyncs.inc(reason="mesh_degraded")
        logger.warning(
            "mesh degraded to %s after device loss",
            dict(new_mesh.shape) if new_mesh is not None else "single-chip",
        )
        return True

    def _sync_device_cluster(self, vocab):
        """DeviceClusterCache.sync with hbm_oom recovery: a failed
        donation/placement (chaos hbm_oom, or a real RESOURCE_EXHAUSTED)
        invalidates the cache and rebuilds the snapshot whole from the
        host mirror — the full-pack path.  Bounded retries; persistent
        failure surfaces as DispatchFailed so callers route the batch to
        the serial fallback."""
        from kubernetes_tpu.observability import kernels as kernels_mod

        last = None
        for _ in range(3):
            try:
                return self._dc_cache.sync(self.mirror, vocab)
            except kernels_mod.DispatchFailed:
                raise
            except Exception as e:  # noqa: BLE001 — backend failure class
                last = e
                self.kernels.record_breaker_failure(
                    "device_mirror.apply", "hbm_oom"
                )
                self.prom.resident_resyncs.inc(reason="hbm_oom")
                self._dc_cache.invalidate()
        raise kernels_mod.DispatchFailed(
            "device_mirror.apply", last, kind="hbm_oom"
        )

    def _d2h_guarded(self, value, kernel: str, validate=None, retries: int = 2):
        """Blocking fetch (through ``_d2h``) with readback validation:
        float leaves must be finite, signed-int leaves must not carry the
        poison sentinel, and ``validate(fetched)`` (when given) must
        return None.  A bad readback books a poisoned_output breaker
        failure and re-fetches — the device array is intact, so an
        injected poison heals, while a REAL non-finite kernel output
        keeps failing and raises DispatchFailed for the caller's fallback
        engine.  Chaos poison is injected here (and ONLY here: unguarded
        fetches are never corrupted — a fault nobody validates would be
        an undetectable wrong answer, not a recoverable one)."""
        import numpy as np

        from kubernetes_tpu.observability import kernels as kernels_mod

        poison_i32 = -(2**31)
        attempt = 0
        while True:
            out = self._d2h(value, kernel=kernel)
            inj = kernels_mod.fault_injector()
            if inj is not None and self.kernels.enabled:
                out, _fired = inj.poison(kernel, out)
            err = None
            for leaf in jax.tree_util.tree_leaves(out):
                if not isinstance(leaf, np.ndarray) or leaf.size == 0:
                    continue
                if np.issubdtype(leaf.dtype, np.floating):
                    if not np.isfinite(leaf).all():
                        err = "non-finite float readback"
                        break
                elif np.issubdtype(leaf.dtype, np.signedinteger):
                    if (leaf == leaf.dtype.type(poison_i32)).any():
                        err = "out-of-range int readback"
                        break
            if err is None and validate is not None:
                err = validate(out)
            if err is None:
                return out
            self.kernels.record_breaker_failure(kernel, "poisoned_output")
            if attempt >= retries:
                raise kernels_mod.DispatchFailed(
                    kernel, err, kind="poisoned_output"
                )
            attempt += 1

    def _schedule_batch_serial(self, fwk, batch) -> List[ScheduleOutcome]:
        """Breaker fallback: the batch degrades to one-pod host-oracle
        cycles — the fallback ladder's floor, bit-identical to the device
        engines by the parity property.  This is the drain path while a
        kernel family's breaker is open (or after its dispatch was
        abandoned mid-batch)."""
        outs: List[ScheduleOutcome] = []
        for qp in batch:
            if qp.pod.nominated_node_name:
                outs.extend(self._schedule_one_nominated(fwk, qp))
            else:
                outs.extend(self._schedule_one_extender(fwk, qp))
        return outs

    def _record_batch_metrics(self, profile, group, outs, dt: float) -> None:
        """Attempt counters + latency histograms (metrics.go:86-147).  The
        batch shares one device dispatch, so per-pod attempt latency is the
        batch latency amortized over its pods."""
        from kubernetes_tpu import metrics as M

        prom = self.prom
        prom.batch_size_hist.observe(len(group))
        prom.recorder.observe(prom.algorithm_duration, dt, profile=profile)
        per_pod = dt / max(len(outs), 1)
        # one batched dispatch smears its latency over the batch: the
        # coarse batch label lets the serving analysis separate real
        # per-pod samples (batch=1) from drain averages (batch=4096+)
        bsz = M.batch_size_bucket(len(group))
        now_mono = time.monotonic()
        # Aggregate per-pod series by (result / attempts) before touching
        # the registry: the batch shares one latency, so one bucket update
        # per distinct label set replaces len(batch) walks.
        by_result: Dict[str, int] = {}
        by_attempts: Dict[int, int] = {}
        for o in outs:
            if o.node is not None:
                result = M.SCHEDULED
                a = o.pod_attempts or 1
                by_attempts[a] = by_attempts.get(a, 0) + 1
                # e2e SLI from the MONOTONIC enqueue stamp: the queue
                # clock is injectable (manual/wall), and a clock jump —
                # NTP step, chaos skew, a test skipping backoff — must
                # not skew the latency distribution
                if o.first_enqueue_mono is not None:
                    prom.pod_scheduling_sli_duration.observe(
                        max(now_mono - o.first_enqueue_mono, 0.0),
                        attempts=str(min(a, 16)),
                    )
            elif o.status.code == Code.ERROR:
                result = M.ERROR
            else:
                result = M.UNSCHEDULABLE
            by_result[result] = by_result.get(result, 0) + 1
        for result, n in by_result.items():
            prom.schedule_attempts.inc(n, result=result, profile=profile)
            prom.attempt_duration.observe_n(
                per_pod, n, result=result, profile=profile, batch=bsz
            )
        for a, n in by_attempts.items():
            prom.pod_scheduling_attempts.observe_n(a, n)

    def refresh_gauges(self) -> None:
        """pending_pods / cache_size gauges (metrics.go:180-220), refreshed
        on scrape rather than on every mutation."""
        stats = self.queue.stats()
        for queue_name, n in stats.items():
            self.prom.pending_pods.set(n, queue=queue_name)
        self.prom.cache_size.set(len(self.cache.real_nodes()), type="nodes")
        self.prom.cache_size.set(len(self.cache.pod_states), type="pods")
        self.prom.cache_size.set(len(self.cache.assumed), type="assumed_pods")
        # observability-layer overhead counters, sampled on scrape so the
        # recording hot paths never touch the registry
        ts = self.tracer.stats()
        self.prom.trace_buffered.set(ts["events"])
        self.prom.trace_dropped.set(ts["dropped"])
        self.prom.trace_evicted.set(ts["evicted"])
        self.prom.tracer_overhead.set(ts["overhead_s"])
        fs = self.flight.stats()
        self.prom.flightrec_events.set(fs["events"])
        self.prom.flightrec_evicted.set(fs["evicted_total"])
        slo = self.slo
        if slo is not None:
            for objective, burn in slo.gauge_rows():
                self.prom.slo_burn_rate.set(burn, objective=objective)
        # queue depth + oldest-pod age per sub-queue: the age walk reads
        # live heap entries, so it samples under the scheduler lock
        with self._mu:
            depth_age = self.queue.depth_age_stats()
        for queue_name, (depth, age) in depth_age.items():
            self.prom.queue_depth.set(depth, queue=queue_name)
            self.prom.queue_oldest_age.set(age, queue=queue_name)
        cp = self.controlplane
        if cp is not None:
            cp.sync_registry(self.prom)
        # live device memory where the backend reports it (None on CPU)
        if self.kernels.enabled:
            for row in self.kernels.hbm_rows():
                for kind in (
                    "bytes_in_use",
                    "peak_bytes_in_use",
                    "bytes_limit",
                ):
                    self.prom.device_hbm_bytes.set(
                        row[kind], device=row["device"], kind=kind
                    )

    def install_slo(self, slo_config=None):
        """Install the steady-state SLO tier (observability/slo.py): wires
        the evaluator as the flight recorder's streaming sink (per-stage
        latency attribution + objective/burn-rate tracking) and, unless
        disabled in the config, arms the tracer's always-on black-box ring
        so an SLO breach can freeze and dump the trace of the bad window.
        Returns the evaluator (also at ``self.slo``; served at
        /debug/slo)."""
        from kubernetes_tpu.observability.slo import SLOConfig, SLOEvaluator

        cfg = slo_config or SLOConfig()
        ev = SLOEvaluator(cfg, prom=self.prom, tracer=self.tracer)
        self.slo = ev
        # attribution needs the breadcrumbs flowing; the async sink keeps
        # producer threads at one buffer append — joining runs inline at
        # an amortized threshold, with the worker as the idle-tail backstop
        self.flight.enabled = True
        sink = ev.ingest_async
        cp = self.controlplane
        if cp is not None:
            # keep the control-plane monitor upstream of the evaluator —
            # install order between the two tiers must not matter
            sink = cp.make_sink(sink)
        self.flight.sink = sink
        if cfg.blackbox:
            self.tracer.blackbox_start(cfg.blackbox_capacity)
        return ev

    def install_controlplane(self, config=None, api_server=None, source=None):
        """Install the control-plane pipeline tier
        (observability/controlplane.py): causal per-pod chains across
        api_write → watch_delivery → informer_handler → enqueue → pop →
        assumed → bind_start → bound (served at /debug/pipeline), the
        snapshot-staleness sentinel sampled at every dispatch (sustained
        breaches file through the SLO tier's black-box machinery when
        installed), and — with ``api_server``/``source`` wired — the
        serving tier's per-request and delivery-lag accounting.  Returns
        the monitor (also at ``self.controlplane``)."""
        from kubernetes_tpu.observability.controlplane import (
            ControlPlaneConfig,
            ControlPlaneMonitor,
        )

        self_ref = weakref.ref(self)

        def _slo_of():
            s = self_ref()
            return s.slo if s is not None else None

        mon = ControlPlaneMonitor(
            config or ControlPlaneConfig(),
            tracer=self.tracer,
            slo_getter=_slo_of,
        )
        # a chaos journal attached before install already stamps the
        # tracer — inherit its logical clock for chain breadcrumbs
        mon.logical_time = self.tracer.logical_time
        self.controlplane = mon
        # scheduler-side hops ride the existing breadcrumb stream: chain
        # in front of whatever sink is installed (the SLO evaluator's)
        self.flight.enabled = True
        self.flight.sink = mon.make_sink(self.flight.sink)
        if api_server is not None:
            mon.attach_api_server(api_server)
        if source is not None:
            mon.attach_source(source)
        return mon

    def expose_metrics(self) -> str:
        """Prometheus text exposition (the /metrics handler body)."""
        self.refresh_gauges()
        return self.prom.expose()

    def _schedule_batch(
        self, batch, try_workloads: bool = True
    ) -> List[ScheduleOutcome]:
        fwk = self.profiles.get(
            batch[0].pod.scheduler_name, next(iter(self.profiles.values()))
        )
        outcomes: List[ScheduleOutcome] = []
        # direct-path commits happen outside any device chain
        self._chain = None

        # the workloads tier: gang/coscheduling + DRA + volume topology
        # batches take ONE fused dispatch with all-or-nothing gang
        # admission instead of degrading to one-pod host-plugin cycles
        if try_workloads and self.config.gang_dispatch:
            wl_out = self._try_dispatch_workloads(fwk, batch)
            if wl_out is not None:
                return wl_out
            # mixed batch: one disqualifying pod (nominated / extender /
            # host ports / uncovered plugin) must not silently drop the
            # quorum semantics for gang members sharing its batch — peel
            # the members out and retry the workloads dispatch on them
            # alone; only a member that ITSELF disqualifies falls through
            gang_qps = [
                qp
                for qp in batch
                if self._workloads_group_of(qp.pod) is not None
            ]
            if gang_qps and len(gang_qps) < len(batch):
                rest = [
                    qp
                    for qp in batch
                    if self._workloads_group_of(qp.pod) is None
                ]
                wl_out = self._try_dispatch_workloads(fwk, gang_qps)
                if wl_out is not None:
                    return wl_out + self._schedule_batch(rest)

        if len(batch) > 1:
            # Host-stateful Filter plugins (volumebinding/DRA class) judge
            # against cache state that earlier commits in the SAME batch
            # mutate — their veto masks can't be batched; extender webhooks
            # are serial per-pod HTTP round-trips by protocol.  Pods either
            # could act on (cheap spec check — maybe_relevant/is_interested)
            # degrade to one-pod cycles (the reference's native granularity,
            # schedule_one.go:65); contiguous runs of clean pods stay on the
            # batched device path.  Runs preserve queue order, so decisions
            # stay sequential-equivalent.
            hf = fwk.host_filter_plugins()
            ns_plugins = self._normalizing_score_plugins(fwk)
            any_nom = any(qp.pod.nominated_node_name for qp in batch)
            if hf or self.extenders or ns_plugins or any_nom:
                run: List = []
                split = False
                for qp in batch:
                    if (
                        not qp.pod.nominated_node_name
                        and not any(p.maybe_relevant(qp.pod) for p in hf)
                        and not any(
                            e.is_interested(qp.pod) for e in self.extenders
                        )
                        and not any(
                            p.score_relevant(qp.pod) for p in ns_plugins
                        )
                    ):
                        run.append(qp)
                        continue
                    split = True
                    if run:
                        outcomes.extend(self._schedule_batch(run))
                        run = []
                    if qp.pod.nominated_node_name:
                        outcomes.extend(self._schedule_one_nominated(fwk, qp))
                    else:
                        outcomes.extend(self._schedule_batch([qp]))
                if split:
                    if run:
                        outcomes.extend(self._schedule_batch(run))
                    return outcomes

        if len(batch) == 1 and batch[0].pod.nominated_node_name:
            return self._schedule_one_nominated(fwk, batch[0])

        if len(batch) == 1 and (
            any(e.is_interested(batch[0].pod) for e in self.extenders)
            # a host Score plugin with a CUSTOM normalize must score over
            # the true feasible set (runtime/framework.go:1158 runs
            # NormalizeScore post-Filter) — the oracle one-pod cycle does;
            # the batched extra_score merge cannot
            or any(
                p.score_relevant(batch[0].pod)
                for p in self._normalizing_score_plugins(fwk)
            )
        ):
            return self._schedule_one_extender(fwk, batch[0])

        # Host-side preparation reads cache/mirror/assume-cache state that
        # async binding workers mutate under self._mu — hold it for the
        # whole prep (the device dispatch below runs outside the lock).
        with self._mu:
            state = CycleState()

            # 0. PreFilter (runtime:698): per-pod rejection + Skip bookkeeping
            pf_failures = fwk.run_pre_filter(state, [qp.pod for qp in batch])
            if pf_failures:
                live = []
                for qp in batch:
                    s = pf_failures.get(qp.pod.uid)
                    if s is None:
                        live.append(qp)
                        continue
                    self.metrics["schedule_attempts"] += 1
                    outcomes.append(self._post_filter_or_fail(fwk, state, qp, s, 0))
                batch = live
                if not batch:
                    return outcomes
            pods = [qp.pod for qp in batch]
            from kubernetes_tpu.metrics import Trace

            trace = Trace(
                "Scheduling batch",
                clock=time.perf_counter,
                pods=len(pods),
                profile=fwk.profile_name,
            )
            trace.step("PreFilter done")

            # 1. intern pod labels FIRST so a fresh full pack covers them
            # (stale val-int tables would force a second repack next cycle).
            # The FULL mirror repack is deferred past the fast path: fast
            # batches never read the per-node usage tensors (the committer
            # tracks usage itself), so steady-state fast drains skip the
            # per-batch repack entirely; _sync_mirror_external below brings
            # the mirror up to date only when non-fast state moved.
            vocab = self.mirror.vocab
            for pod in pods:
                for k, v in pod.labels.items():
                    vocab.intern_label(k, v)
            self._sync_mirror_external()
            trace.step("Snapshot mirror synced")

            # 1a. FAST PATH: when the batch has no batch-dynamic constraints
            # beyond resources (no inter-pod/spread/ports/nominations/host
            # filters), pods collapse into signatures — one tiny device static
            # eval + exact host greedy replaces the per-pod device scan.
            enabled = fwk.device_enabled()
            weights = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
            active_host = fwk.active_host_filters(state, pods)
            # Host PreScore/Score plugins (runtime/framework.go:1052,1101):
            # PreScore may Skip; surviving plugins contribute a pre-weighted
            # [P, N] score matrix merged before the device argmax.
            fwk.run_pre_score(state, pods, self.mirror.nodes.names)
            active_scores = fwk.active_host_scores(state, pods)
            if (
                not active_host
                and not active_scores
                and self._fast_gate_ok(batch)
                # the signature committer assumes the default fit scoring,
                # full-width evaluation, and first-max tie-break
                and fwk.fit_strategy() == gang.DEFAULT_FIT_STRATEGY
                and not self._sampling_active(fwk)
            ):
                fast = self._try_fast_schedule(
                    fwk, state, batch, enabled, weights, outcomes
                )
                if fast is not None:
                    # fast_batches + gang_dispatch_duration(path=fast) are
                    # both recorded inside the dispatch/harvest halves
                    trace.step("Fast-path commit done")
                    trace.log_if_long()
                    return fast

            # scan path: bring the full mirror (usage tensors included) up
            # to date — its kernels read requested/num_pods per node.
            t_pack = time.perf_counter()
            self._repack_mirror()
            self.prom.recorder.observe(
                self.prom.snapshot_pack_duration, time.perf_counter() - t_pack
            )
            self.phases.add("pack", time.perf_counter() - t_pack)
            trace.step("Snapshot mirror updated")

            self._p_cap_max = max(self._p_cap_max, self._p_bucket(len(pods)))
            p_cap = self._p_cap_max
            pb = pack_pod_batch(
                pods,
                vocab,
                k_cap=self.mirror.nodes.k_cap,
                p_cap=p_cap,
                namespace_labels=self.namespace_labels,
            )
            t_sync = time.perf_counter()
            from kubernetes_tpu.observability import kernels as kernels_mod

            try:
                dc = self._sync_device_cluster(vocab)
            except kernels_mod.DispatchFailed as e:
                # persistent snapshot-placement failure (hbm_oom class):
                # the batch drains on the serial host-oracle path
                self._note_dispatch_failure(e)
                return outcomes + self._schedule_batch_serial(fwk, batch)
            db = self._place_db(DeviceBatch.from_host(pb))
            self.prom.recorder.observe(
                self.prom.snapshot_pack_duration,
                time.perf_counter() - t_sync,
                phase="device_sync",
            )
            self.phases.add("h2d", time.perf_counter() - t_sync)
            v_cap = bucket_cap(len(vocab.label_vals))
            hostname_key = self._hostname_dev(vocab)
            tables = self._gang_tables(pb, vocab)

            has_interpod = bool(
                (pb.aff_kind != PAD).any()
                or (self.mirror.existing.term_kind != PAD).any()
            )
            has_spread = bool((pb.tsc_topo_key != PAD).any())
            has_images = bool((pb.img_ids >= 0).any())
            has_ports = bool(
                (pb.want_ppk != PAD).any() or (self.mirror.nodes.used_ppk != PAD).any()
            )

            # 1a'. WAVE eligibility: batches carrying their own cross-pod
            # constraints — spread/inter-pod terms OR in-batch host ports
            # — ride the speculative wave dispatch (ops/wave.py):
            # speculation + term-factored conflict resolution,
            # bit-identical to the scan at a fraction of its per-step
            # cost.  Port users ride the [Tpt, N] occupancy carry and
            # sampling-compat / seeded-tie drains replay their window +
            # rotation per step, so neither falls back any more; the only
            # remaining disqualifier is duplicate hostname labels
            # (_wave_tables → mirror.hostnames_unique).  Every fallback
            # bumps scheduler_tpu_wave_fallback_total{reason=}.
            wave_shaped = bool(
                (pb.aff_kind != PAD).any()
                or (pb.tsc_topo_key != PAD).any()
                or (pb.want_ppk != PAD).any()
            )
            wt = None
            if wave_shaped:
                if not self.config.wave_dispatch:
                    self.prom.wave_fallback.inc(reason="kill_switch")
                elif self._breaker_blocked("wave.wave_run"):
                    pass  # open breaker: the batch rides the scan fallback
                else:
                    wt = self._wave_tables(pb)
                    if wt is None:
                        self.prom.wave_fallback.inc(reason="dup_hostname")
            # an OPEN gang-scan breaker has no device engine left under it:
            # the batch degrades to one-pod host-oracle cycles (the ladder's
            # floor, bit-identical by the parity property)
            if wt is None and self._breaker_blocked("gang.gang_run"):
                return outcomes + self._schedule_batch_serial(fwk, batch)
            self.metrics[
                "wave_batches" if wt is not None else "scan_batches"
            ] += 1

            # 1b. host-backed Filter plugins veto (pod, node) pairs the device
            # kernels can't judge (stateful plugins — volumebinding class).
            extra_mask = None
            host_diags = host_plugin_sets = None
            if active_host:
                extra_mask, host_diags, host_plugin_sets = self._host_filter_mask(
                    fwk, state, pods, p_cap, db=db, enabled=enabled
                )

            # 1b'. host-backed Score plugins → pre-weighted additive [P, N]
            # matrix merged into the device selection (the RunScorePlugins
            # weight+sum pass, runtime/framework.go:1177, for kernel-less
            # plugins — e.g. VolumeBinding's VolumeCapacityPriority shape).
            extra_score = None
            if active_scores:
                extra_score = self._host_score_matrix(fwk, state, pods, p_cap)

            # 1c. nominated preemptors (victims still terminating) charge their
            # nominated node for pods of lower priority (runtime:973).
            nom_node = nom_prio = nom_req = None
            if len(self.nominator):
                nom_node, nom_prio, nom_req = self._nominated_arrays(
                    {qp.pod.uid for qp in batch}
                )

        # 2. one fused device dispatch (the whole Filter→Score→Select loop)
        sample_k, tie_key, attempt_base = self._sampling_args(fwk)
        sample_start = (
            jnp.asarray(getattr(self, "_next_start_node_index", 0), I32)
            if sample_k is not None
            else None
        )
        t_gang = time.perf_counter()
        wstats_dev = None
        # kwargs shared VERBATIM by both dispatch kernels — one dict so a
        # future knob cannot reach one path and silently miss the other
        shared_kw = dict(
            has_interpod=has_interpod,
            has_spread=has_spread,
            has_images=has_images,
            enabled=enabled,
            weights=weights,
            extra_mask=extra_mask,
            nom_node=nom_node,
            nom_prio=nom_prio,
            nom_req=nom_req,
            extra_score=extra_score,
            fit_strategy=fwk.fit_strategy(),
            **tables,
        )
        path = "wave" if wt is not None else "scan"
        kroot = "wave.wave_run" if wt is not None else "gang.gang_run"
        n_bound = len(self.mirror.nodes.names)

        def _validate_direct(fetched):
            import numpy as np

            arr = np.asarray(fetched)
            ch, nf = arr[0], arr[1]
            if ((ch < -1) | (ch >= n_bound)).any():
                return "chosen index out of node range"
            if ((nf < 0) | (nf > n_bound)).any():
                return "n_feas out of range"
            return None

        try:
            if wt is not None:
                from kubernetes_tpu.ops import wave as wave_ops

                chosen, n_feas, reason_counts, tallies, wstats_dev = (
                    wave_ops.wave_run(
                        dc,
                        db,
                        hostname_key,
                        v_cap,
                        wt["tid_sp"],
                        wt["rep_sp_p"],
                        wt["rep_sp_c"],
                        wt["tid_ip"],
                        wt["rep_ip_p"],
                        wt["rep_ip_u"],
                        wt["ip_cdv_tab"],
                        d2_cap=wt["d2_cap"],
                        has_ports=wt["has_ports"],
                        tid_pt=wt["tid_pt"],
                        port_conf=wt["port_conf"],
                        sample_k=sample_k,
                        sample_start=sample_start,
                        tie_key=tie_key,
                        attempt_base=attempt_base,
                        **shared_kw,
                    )
                )
            else:
                chosen, n_feas, reason_counts, tallies = gang.gang_run(
                    dc,
                    db,
                    hostname_key,
                    v_cap,
                    has_ports=has_ports,
                    sample_k=sample_k,
                    sample_start=sample_start,
                    tie_key=tie_key,
                    attempt_base=attempt_base,
                    **shared_kw,
                )
            t_d2h = time.perf_counter()
            self.phases.add("device", t_d2h - t_gang)
            both = self._d2h_guarded(
                jnp.stack([chosen, n_feas]),
                kernel=kroot,
                validate=_validate_direct,
            )
            self.phases.add("d2h", time.perf_counter() - t_d2h)
        except kernels_mod.DispatchFailed as e:
            # abandoned dispatch (or unrecoverable readback): nothing was
            # committed — the batch drains on the serial host-oracle path,
            # bit-identically, while the breaker keeps the kernel parked
            self._note_dispatch_failure(e)
            return outcomes + self._schedule_batch_serial(fwk, batch)
        chosen, n_feas = both[0], both[1]
        if sample_k is not None:
            self._next_start_node_index = int(
                self._d2h(tallies["sample_start"], kernel=kroot)
            )
        if tie_key is not None or sample_k is not None:
            self._attempt_counter = (
                getattr(self, "_attempt_counter", 0) + len(batch)
            )
        self.prom.recorder.observe(
            self.prom.gang_dispatch_duration,
            time.perf_counter() - t_gang,
            path=path,
        )
        self._trace_dispatch(path, t_gang, batch)
        trace.step("Gang dispatch done")

        # 3. per-pod commit: assume → reserve → permit → bind.  Wave
        # batches additionally resolve their speculation stats and, when
        # the framework allows lean binds, commit through the bulk path
        # split by interaction group.
        wave_groups = None
        if wstats_dev is not None:
            wave_groups = self._wave_resolve(
                fwk, batch, chosen, wstats_dev, kernel=kroot
            )
        self._process_results(
            fwk,
            state,
            batch,
            chosen,
            n_feas,
            reason_counts,
            outcomes,
            host_diags,
            host_plugin_sets,
            wave_groups=wave_groups,
            kernel=kroot,
        )
        trace.step("Commits done")
        trace.log_if_long()
        return outcomes

    def _process_results(
        self,
        fwk,
        state,
        batch,
        chosen,
        n_feas,
        reason_counts,
        outcomes,
        host_diags=None,
        host_plugin_sets=None,
        wave_groups=None,
        kernel=None,
    ) -> None:
        """The per-pod result walk shared by the direct and chained paths:
        failures → diagnosis + PostFilter, successes → _commit (which hands
        binding to the async workers).  ``wave_groups`` (per-pod
        interaction-group ids from the wave partitioner) routes successes
        through the bulk-commit path instead, one bulk run per group, so
        non-interacting groups' bindings flow concurrently."""
        t_commit = time.perf_counter()
        node_names = self.mirror.nodes.names
        n_nodes = len(self.cache.real_nodes())
        counts = None  # fetched lazily — only failures read it
        if fwk.has_post_filter():
            failed = [
                qp for i, qp in enumerate(batch) if int(chosen[i]) < 0
            ]
            if failed:
                # the dispatch's own committed placements ride into the
                # narrowing dry run (the admission scan's carried state,
                # not yet visible through the cache at this point).
                # Peers travel as node NAMES: the narrow repacks the
                # mirror first, which may compact node slots, so raw
                # dispatch-time indices could charge the wrong rows.
                self._batched_preemption_narrow(
                    fwk,
                    state,
                    failed,
                    batch=batch,
                    chosen=chosen,
                    node_names=node_names,
                )
        # one locked bump for the whole batch: `metrics` is a registered
        # lock-guarded field (binding workers write other keys of it under
        # _mu); uniform write discipline costs one acquisition per batch
        # and stays correct if the interpreter ever drops the GIL's
        # per-op dict atomicity
        with self._mu:
            self.metrics["schedule_attempts"] += len(batch)
        bulk_by_group: Dict[int, list] = {}
        for i, qp in enumerate(batch):
            idx = int(chosen[i])
            if idx < 0:
                if counts is None:
                    counts = self._d2h(reason_counts, kernel=kernel)
                diag = {
                    k: int(c)
                    for k, c in zip(gang.DIAG_KERNELS, counts[i])
                    if c > 0
                }
                plugins = set(diag)
                if "HostFilters" in plugins:
                    # replace the aggregate bucket with the per-plugin
                    # reasons recorded while building the veto mask
                    plugins.discard("HostFilters")
                    diag.pop("HostFilters", None)
                    if host_diags is not None:
                        diag.update(host_diags[i])
                        plugins |= host_plugin_sets[i]
                    else:
                        plugins |= {p.name for p in fwk.host_filter_plugins()}
                status = Status.unschedulable(
                    fit_error_message(n_nodes, diag)
                )
                outcomes.append(
                    self._post_filter_or_fail(
                        fwk, state, qp, status, int(n_feas[i]), diag, plugins
                    )
                )
                continue
            if wave_groups is not None:
                bulk_by_group.setdefault(wave_groups[i], []).append(i)
                continue
            node_name = node_names[idx]
            outcome = self._commit(fwk, state, qp, node_name, int(n_feas[i]))
            outcomes.append(outcome)
        # wave bulk tail: one vectorized assume + one bulk bind task per
        # interaction group (decisions are final; non-interacting groups'
        # binds are independent, so each group rides its own task)
        for gidxs in bulk_by_group.values():
            self._commit_fast_bulk(
                fwk,
                state,
                batch,
                chosen,
                0,
                0,
                node_names,
                outcomes,
                idxs=gidxs,
                n_feas=n_feas,
                nonfast=True,
            )
        self.phases.add("commit", time.perf_counter() - t_commit)

    # ----- the chained (pipelined) dispatch path ---------------------------
    #
    # chain_dispatch (ops/chain.py) appends each batch's placements into the
    # device cluster inside the dispatch itself, so batch k+1 launches
    # against batch k's output WITHOUT waiting for k's results to reach the
    # host — the drain becomes a software pipeline over the device link.
    # Anything the device can't see (informer events, bind failures, fast-
    # path or one-pod commits, vocab growth) changes the chain epoch and
    # forces a fresh host upload.

    def _chain_epoch(self, vocab):
        return (
            self._external_mutations,
            self.metrics["fast_batches"],
            self.mirror._full_packs,
            len(vocab.label_vals),
            len(vocab.label_keys),
        )

    def _chain_quickcheck(self, fwk, batch) -> bool:
        """Spec-only gate: True when the batch can take the chained path
        (no extenders/host-filter/host-score involvement, not a fast-path
        candidate, mirror already initialized)."""
        if self.extenders or self.mirror.nodes is None:
            return False
        # device-fault tier: an open chain breaker routes batches to the
        # direct path (same verdict kernels, no pipeline overlap)
        if self._breaker_blocked("chain.chain_dispatch"):
            return False
        # bit-compat sampling threads a rotation cursor through every
        # attempt — the direct path owns that state
        if self._sampling_active(fwk):
            return False
        # gang members take the direct path's workloads dispatch (all-or-
        # nothing admission with device-side rollback, ops/coscheduling.py)
        if self.config.gang_dispatch and any(
            wlg.group_key_of(qp.pod) is not None for qp in batch
        ):
            return False
        # the device append doesn't splice node port-usage rows, so pods
        # with host ports must take the direct path (which resyncs the
        # snapshot from host state every batch)
        if any(qp.pod.host_ports() for qp in batch):
            return False
        # nominated pods take the single-node fast path via the direct
        # path's split (schedule_one.go:490)
        if any(qp.pod.nominated_node_name for qp in batch):
            return False
        hf = fwk.host_filter_plugins()
        if any(p.maybe_relevant(qp.pod) for p in hf for qp in batch):
            return False
        for p in fwk.host_score_plugins():
            if fwk.score_weights.get(p.name, 0) and any(
                p.score_relevant(qp.pod) for qp in batch
            ):
                return False
        # one-pod-only score plugins (normalize overrides, extended-resource
        # fit strategies) force the direct path's split routing
        for p in self._normalizing_score_plugins(fwk):
            if any(p.score_relevant(qp.pod) for qp in batch):
                return False
        # a batch the signature fast path can commit is cheaper there —
        # the keys computed here are memoized for _try_fast_schedule so the
        # per-pod signature work runs ONCE per batch, not twice
        if (
            self._fast_gate_ok(batch)
            and fwk.fit_strategy() == gang.DEFAULT_FIT_STRATEGY
        ):
            keys = self._batch_signature_keys(batch)
            if keys is not None:
                return False
        return True

    def _repack_mirror(self) -> None:
        """mirror.update + key-width guard: one forced full repack when the
        label-key bucket grew past the packed node-tensor width.  The single
        definition shared by the scan path, the fast-path sync, and the
        chained-dispatch prep.  When a live fast committer proves every
        pending usage delta is its own (same lineage epoch, nothing
        unharvested), its state flushes into the mirror in one vectorized
        pass first, so update()'s per-dirty-node walk sees clean rows."""
        holder = getattr(self, "_fastdev", None)
        if (
            holder is not None
            and not holder["dev_inflight"]
            and getattr(self, "_fc_key", None) is not None
            and self._fc_key[:3]
            == (
                self._external_mutations,
                getattr(self, "_nonfast_commits", 0),
                self.mirror._full_packs,
            )
            and self.mirror.nodes is holder["nt"]
        ):
            self.mirror.apply_fast_usage(holder["fc"], self.cache)
        self.mirror.update(self.cache, self.namespace_labels)
        if bucket_cap(len(self.mirror.vocab.label_keys)) > self.mirror.nodes.k_cap:
            self.mirror._force_full = True
            self.mirror.update(self.cache, self.namespace_labels)
        self._mirror_sync = (
            self._external_mutations,
            getattr(self, "_nonfast_commits", 0),
        )

    def _fast_gate_ok(self, batch) -> bool:
        """Per-batch fast-path eligibility, replacing the old cluster-global
        gates: nominations and placed (anti-)affinity terms only poison the
        pods they can actually touch.

        * nominations count as present only for pods of priority <= the
          nomination's (runtime:973): if every batch pod outranks every
          nomination, the signature committer's capacity view is exact;
        * a placed pod's required anti-affinity (and symmetric term score)
          affects only newcomers its term selectors ADMIT — checked per
          batch label-group against the cache's term-pod registry;
        * placed host-port users never constrain port-FREE pods (and port
          users are already signature-ineligible), so no port gate at all.
        """
        # gang members need the workloads tier's all-or-nothing admission —
        # the signature committer has no rollback
        if self.config.gang_dispatch and any(
            wlg.group_key_of(qp.pod) is not None for qp in batch
        ):
            return False
        if len(self.nominator):
            max_nom = max(p.priority for _, p in self.nominator.entries())
            if any(qp.pod.priority <= max_nom for qp in batch):
                return False
        n_t = self.cache.n_term_pods
        if n_t:
            if n_t > 64:
                # probe checks would cost more than the scan saves
                return False
            from kubernetes_tpu.fastpath import _pod_probes

            key = self.cache.term_version
            cached = getattr(self, "_term_probe_cache", None)
            if cached is None or cached[0] != key:
                probes = []
                for p in self.cache.term_pods.values():
                    probes.extend(_pod_probes(p))
                cached = self._term_probe_cache = (key, probes)
            probes = cached[1]
            seen: Dict[tuple, bool] = {}
            for qp in batch:
                gk = (
                    qp.pod.namespace,
                    tuple(sorted(qp.pod.labels.items())),
                )
                hit = seen.get(gk)
                if hit is None:
                    hit = any(pr.admits(qp.pod) for pr in probes)
                    seen[gk] = hit
                if hit:
                    return False
        return True

    def _fast_pod_predicate(self, fwk, group_name: str, known_rows=None):
        """Per-pod closure mirroring _try_dispatch_fast's batch gates +
        _fast_gate_ok + signature eligibility — the pop_batch_while feed
        for fast-batch extension.  Pods it accepts are exactly the pods a
        fresh batch through those gates would accept; with ``known_rows``
        (the signature row cache) it additionally requires the pod's
        signature to be already established as argmax-neutral, so the
        extension can never force a post-pop bail-out."""
        host_scores = [
            p
            for p in fwk.host_score_plugins()
            if fwk.score_weights.get(p.name, 0)
        ]
        hf = fwk.host_filter_plugins()
        ns_plugins = self._normalizing_score_plugins(fwk)
        extenders = self.extenders
        max_nom = None
        if len(self.nominator):
            max_nom = max(p.priority for _, p in self.nominator.entries())
        probes = ()
        if self.cache.n_term_pods:
            cached = getattr(self, "_term_probe_cache", None)
            # _fast_gate_ok just ran on the seed batch, so the cache is hot;
            # if it somehow isn't, refuse to extend rather than skip probes
            if cached is None or cached[0] != self.cache.term_version:
                return lambda qp: False
            probes = cached[1]
        group_hit: Dict[tuple, bool] = {}
        vocab = self.mirror.vocab
        n_lanes = self.mirror.nodes.allocatable.shape[1]
        params = (n_lanes, len(vocab.resources))
        lanes_box: list = [None]

        # the default registry leaves every gate list empty — guard each
        # any() so the hot steady-state predicate is just the signature
        # memo lookup (pop_batch_while runs this once per extended pod)
        gang_on = self.config.gang_dispatch

        def elig(qp) -> bool:
            p = qp.pod
            if p.scheduler_name != group_name or p.nominated_node_name:
                return False
            if gang_on and wlg.group_key_of(p) is not None:
                return False  # gang members need the workloads dispatch
            if max_nom is not None and p.priority <= max_nom:
                return False
            # explicit loops, not any(genexpr): this predicate runs once
            # per extended pod and the genexpr closure allocation showed
            # up in the drain profile
            for pl in hf:
                if pl.maybe_relevant(p):
                    return False
            for e in extenders:
                if e.is_interested(p):
                    return False
            for pl in ns_plugins:
                if pl.score_relevant(p):
                    return False
            for pl in host_scores:
                if pl.score_relevant(p):
                    return False
            if probes:
                gk = (p.namespace, tuple(sorted(p.labels.items())))
                hit = group_hit.get(gk)
                if hit is None:
                    hit = any(pr.admits(p) for pr in probes)
                    group_hit[gk] = hit
                if hit:
                    return False
            memo = p.__dict__.get("_sigkey_memo")
            if memo is not None and memo[0] == params:
                k = memo[1]
            else:
                k = self._pod_sig_key(p, params, lanes_box)
            if k is None:
                return False
            if known_rows is not None:
                row = known_rows.get(k)
                return row is not None and row["const_ok"]
            return True

        return elig

    def _sync_mirror_external(self) -> None:
        """Repack the host mirror only when state the FAST path reads could
        have moved: external mutations (node/pod informer events, forgets)
        or non-fast commits (scan/extender paths, whose usage the fast
        committer didn't track).  Steady-state fast drains — where the only
        changes are the committer's own commits — skip the repack."""
        sync = (
            self._external_mutations,
            getattr(self, "_nonfast_commits", 0),
        )
        if self.mirror.nodes is None or getattr(self, "_mirror_sync", None) != sync:
            t0 = time.perf_counter()
            self._repack_mirror()
            self.prom.recorder.observe(
                self.prom.snapshot_pack_duration, time.perf_counter() - t0
            )

    def _pod_sig_key(self, pod, params, lanes_box):
        """signature_key for one pod, memoized twice over: ON the pod object
        (spec updates arrive as new Pod objects, the compute_requests memo
        pattern) and CONTENT-ADDRESSED by spec (pods stamped from one
        template — the 100k-pod drain shape — share one computation)."""
        d = pod.__dict__
        memo = d.get("_sigkey_memo")
        if memo is not None and memo[0] == params:
            return memo[1]
        from kubernetes_tpu import fastpath as fp

        cache = getattr(self, "_speckey_cache", None)
        if cache is None:
            cache = self._speckey_cache = {}
        sk = fp.spec_key_memo(pod)
        if sk is not None:
            k = cache.get((params, sk), _MISSING)
            if k is not _MISSING:
                d["_sigkey_memo"] = (params, k)
                return k
        if lanes_box[0] is None:
            from kubernetes_tpu.snapshot.schema import ResourceLanes

            lanes_box[0] = ResourceLanes(self.mirror.vocab)
        k = fp.signature_key(pod, lanes_box[0], params[0])
        d["_sigkey_memo"] = (params, k)
        if sk is not None:
            if len(cache) > 65536:
                cache.clear()
            cache[(params, sk)] = k
        return k

    def _batch_signature_keys(self, batch):
        """signature_key per pod via _pod_sig_key's two-level memo, shared
        by the chain quickcheck, the fast gate, and batch extension.
        Returns the full key list, or None when any pod is ineligible."""
        vocab = self.mirror.vocab
        n_lanes = self.mirror.nodes.allocatable.shape[1]
        params = (n_lanes, len(vocab.resources))
        lanes_box: list = [None]
        keys = []
        append = keys.append
        for qp in batch:
            # inline the per-pod memo hit (the steady-state case: every pod
            # was keyed once by the extension predicate already)
            memo = qp.pod.__dict__.get("_sigkey_memo")
            if memo is not None and memo[0] == params:
                k = memo[1]
            else:
                k = self._pod_sig_key(qp.pod, params, lanes_box)
            if k is None:
                return None
            append(k)
        return keys

    def _try_dispatch_chained(self, fwk, batch, outcomes, can_restart: bool):
        """Dispatch the batch on the chained device cluster.  Returns a
        pending record (dict), "handled" (nothing left to schedule),
        "flush" (pipeline must settle before the chain can restart), or
        None (fall back to the direct path)."""
        from kubernetes_tpu.observability import kernels as kernels_mod
        from kubernetes_tpu.ops import chain as chain_ops

        with self._mu:
            vocab = self.mirror.vocab
            for qp in batch:
                for k, v in qp.pod.labels.items():
                    vocab.intern_label(k, v)
            epoch = self._chain_epoch(vocab)
            ch = getattr(self, "_chain", None)
            if (ch is None or ch["epoch"] != epoch) and not can_restart:
                return "flush"

            # ---- side-effect-free preparation: every bail-out below must
            # happen BEFORE PreFilter runs (its failures mutate outcomes/
            # queue/nominator and must not be replayed by the direct path)
            t_pack = time.perf_counter()
            self._repack_mirror()
            pods = [qp.pod for qp in batch]
            self._p_cap_max = max(self._p_cap_max, self._p_bucket(len(pods)))
            pb = pack_pod_batch(
                pods,
                vocab,
                k_cap=self.mirror.nodes.k_cap,
                p_cap=self._p_cap_max,
                namespace_labels=self.namespace_labels,
            )
            epoch = self._chain_epoch(vocab)  # interning may have grown it
            ch = getattr(self, "_chain", None)
            if ch is None or ch["epoch"] != epoch:
                if not can_restart:
                    # packing interned new vocab (epoch moved) — the
                    # pipeline must settle before a host-state restart
                    return "flush"
                # (re)start: the host mirror is current (pipeline settled —
                # can_restart) so its tensors are the ground truth.  A
                # persistent placement failure (hbm_oom class) bails to
                # the direct path, which owns the serial fallback — this
                # is still the side-effect-free prep, so None is safe.
                try:
                    dc = self._sync_device_cluster(vocab)
                except kernels_mod.DispatchFailed as e:
                    self._note_dispatch_failure(e)
                    return None
                # the chain will donate/diverge these buffers — the delta
                # cache must not touch them again
                self._dc_cache.invalidate()
                ch = {
                    "dc": dc,
                    "e": self.mirror.e_used,
                    "m": self.mirror.m_used,
                    "epoch": epoch,
                }
            # capacity/width checks against the CHAINED cluster's own
            # tensors — the live host mirror may have repacked to different
            # buckets mid-chain
            cdc = ch["dc"]
            dc_shapes = (
                cdc.term_table.req_key.shape[2],
                cdc.term_table.req_vals.shape[3],
                cdc.term_ns_ids.shape[1],
                cdc.epod_labels.shape[1],
            )
            if not chain_ops.caps_compatible(dc_shapes, pb):
                return None
            P = pb.valid.shape[0]
            append_terms = bool((pb.aff_kind != PAD).any())
            AT = pb.aff_kind.shape[1] if append_terms else 0
            E = cdc.epod_node.shape[0]
            M = cdc.term_pod.shape[0]
            if ch["e"] + P > E or ch["m"] + P * AT > M:
                # cursor overflow: compact AND grow the host axes (the
                # append-only host path never enlarges them on its own),
                # then restart the chain once from the repacked state
                self._chain = None
                if not can_restart:
                    return "flush"
                self.mirror._m_cap_max = max(
                    self.mirror._m_cap_max,
                    bucket_cap(max((ch["m"] + P * AT) * 2, 1), 1),
                )
                self.mirror.e_cap_hint = max(
                    self.mirror.e_cap_hint, ch["e"] + 2 * P
                )
                self.mirror._epod_slots = None  # full existing repack
                self.mirror._existing_version = -1
                try:
                    dc = self._sync_device_cluster(vocab)
                except kernels_mod.DispatchFailed as e:
                    self._note_dispatch_failure(e)
                    return None  # direct path owns the serial fallback
                self._dc_cache.invalidate()
                ch = {
                    "dc": dc,
                    "e": self.mirror.e_used,
                    "m": self.mirror.m_used,
                    "epoch": epoch,
                }
                cdc = ch["dc"]
                E = cdc.epod_node.shape[0]
                M = cdc.term_pod.shape[0]
                if ch["e"] + P > E or ch["m"] + P * AT > M:
                    return None  # genuinely beyond capacity — direct path
            self.prom.recorder.observe(
                self.prom.snapshot_pack_duration, time.perf_counter() - t_pack
            )

            # ---- PreFilter (side effects OK now: the dispatch is certain)
            state = CycleState()
            pf_failures = fwk.run_pre_filter(state, [qp.pod for qp in batch])
            if pf_failures:
                live = []
                for qp in batch:
                    s = pf_failures.get(qp.pod.uid)
                    if s is None:
                        live.append(qp)
                        continue
                    self.metrics["schedule_attempts"] += 1
                    outcomes.append(
                        self._post_filter_or_fail(fwk, state, qp, s, 0)
                    )
                batch = live
                if not batch:
                    return "handled"
                # repack without the rejected pods (their rows must not
                # reach the device as schedulable entries)
                pods = [qp.pod for qp in batch]
                pb = pack_pod_batch(
                    pods,
                    vocab,
                    k_cap=self.mirror.nodes.k_cap,
                    p_cap=self._p_cap_max,
                    namespace_labels=self.namespace_labels,
                )
                append_terms = bool((pb.aff_kind != PAD).any())
                AT = pb.aff_kind.shape[1] if append_terms else 0

            db = self._place_db(DeviceBatch.from_host(pb))
            v_cap = bucket_cap(len(vocab.label_vals))
            tables = self._gang_tables(pb, vocab)
            nom_node = nom_prio = nom_req = None
            if len(self.nominator):
                nom_node, nom_prio, nom_req = self._nominated_arrays(
                    {qp.pod.uid for qp in batch}
                )
            # any term row in the chained cluster (host rows OR device-
            # appended ones, which ch["m"] counts past) keeps interpod on
            has_interpod = bool((pb.aff_kind != PAD).any()) or ch["m"] > 0
            has_spread = bool((pb.tsc_topo_key != PAD).any())
            has_images = bool((pb.img_ids >= 0).any())
            has_ports = bool(
                (pb.want_ppk != PAD).any()
                or (self.mirror.nodes.used_ppk != PAD).any()
            )
            enabled = fwk.device_enabled()
            weights = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
            fit_strategy = fwk.fit_strategy()
            # cross-pod-constraint batches ride the speculative wave
            # inside the chained dispatch (same self-append, wave
            # scheduling) — computed from the FINAL pb (post-PreFilter
            # repack).  Port batches never reach here (_chain_quickcheck
            # refuses them: the device append doesn't splice port rows),
            # so the want_ppk arm and the wave_ports pass-through below
            # are inert today — kept so the wave surface stays uniform
            # with the direct path.
            wave_shaped = bool(
                (pb.aff_kind != PAD).any()
                or (pb.tsc_topo_key != PAD).any()
                or (pb.want_ppk != PAD).any()
            )
            wt = None
            if wave_shaped:
                if self.config.wave_dispatch:
                    wt = self._wave_tables(pb)
                    if wt is None:
                        self.prom.wave_fallback.inc(reason="dup_hostname")
                else:
                    self.prom.wave_fallback.inc(reason="kill_switch")
            wave_kw = {}
            if wt is not None:
                wave_kw = dict(
                    wave=True,
                    tid_sp=wt["tid_sp"],
                    rep_sp_p=wt["rep_sp_p"],
                    rep_sp_c=wt["rep_sp_c"],
                    tid_ip=wt["tid_ip"],
                    rep_ip_p=wt["rep_ip_p"],
                    rep_ip_u=wt["rep_ip_u"],
                    ip_cdv_tab=wt["ip_cdv_tab"],
                    d2_cap=wt["d2_cap"],
                    wave_ports=wt["has_ports"],
                    tid_pt=wt["tid_pt"],
                    port_conf=wt["port_conf"],
                )
            t0 = time.perf_counter()
            try:
                out = chain_ops.chain_dispatch(
                    ch["dc"],
                    db,
                    self._hostname_dev(vocab),
                    jnp.asarray(ch["e"], I32),
                    jnp.asarray(ch["m"], I32),
                    v_cap,
                    has_interpod=has_interpod,
                    has_spread=has_spread,
                    has_ports=has_ports,
                    has_images=has_images,
                    enabled=enabled,
                    weights=weights,
                    nom_node=nom_node,
                    nom_prio=nom_prio,
                    nom_req=nom_req,
                    append_terms=append_terms,
                    fit_strategy=fit_strategy,
                    **wave_kw,
                    **tables,
                )
            except kernels_mod.DispatchFailed as e:
                # the chained cluster was donated into the dead dispatch —
                # drop the chain (the next batch rebuilds from the host
                # mirror) and hand the LIVE batch back for the serial
                # host-oracle fallback; nothing was committed, so the
                # fallback is exact.  The serial drain itself runs in
                # the caller OUTSIDE this lock — the snapshot-under-lock
                # / replay-outside-lock discipline every other serial
                # engine follows.
                self._note_dispatch_failure(e)
                self._chain = None
                return ("serial", batch)
            if wt is not None:
                dc2, results, reasons, wstats = out
            else:
                dc2, results, reasons = out
                wstats = None
            self._chain = {
                "dc": dc2,
                "e": ch["e"] + P,
                "m": ch["m"] + P * AT,
                "epoch": epoch,
            }
            if wt is not None:
                self.metrics["wave_batches"] += 1
            else:
                self.metrics["chain_batches"] = (
                    self.metrics.get("chain_batches", 0) + 1
                )
            # start the host copy of the results as soon as the device
            # finishes this batch — by harvest time it's already local
            try:
                results.copy_to_host_async()
                reasons.copy_to_host_async()
                if wstats is not None:
                    wstats.copy_to_host_async()
            except AttributeError:
                pass
            rec = {
                "fwk": fwk,
                "state": state,
                "batch": batch,
                "results": results,
                "reasons": reasons,
                "wave_stats": wstats,
                "t0": t0,
            }
            self._trace_dispatch("wave" if wt is not None else "chain", t0, batch, rec)
            return rec

    def _finish_chained(self, rec) -> List[ScheduleOutcome]:
        """Harvest one pipelined batch: fetch its results and walk the
        commits (the host half that overlapped later dispatches)."""
        outcomes: List[ScheduleOutcome] = []
        tr = self.tracer
        t_h = tr.now() if tr.enabled else None
        t_d2h = time.perf_counter()
        from kubernetes_tpu.observability import kernels as kernels_mod

        n_bound = len(self.mirror.nodes.names)

        def _validate_chain(fetched):
            import numpy as np

            arr = np.asarray(fetched)
            if ((arr[0] < -1) | (arr[0] >= n_bound)).any():
                return "chosen index out of node range"
            return None

        try:
            both = self._d2h_guarded(
                rec["results"],
                kernel="chain.chain_dispatch",
                validate=_validate_chain,
            )
        except kernels_mod.DispatchFailed as e:
            # unrecoverable harvest: the chain's device state already
            # includes these commits, so drop it (the next batch rebuilds
            # from the host mirror) and re-derive the batch serially —
            # bit-identical placements, so host state stays consistent
            self._note_dispatch_failure(e)
            with self._mu:
                self._chain = None
            outcomes.extend(
                self._schedule_batch_serial(rec["fwk"], rec["batch"])
            )
            self._flush_binds()
            return outcomes
        self.phases.add("d2h", time.perf_counter() - t_d2h)
        wstats = rec.get("wave_stats")
        self.prom.recorder.observe(
            self.prom.gang_dispatch_duration,
            time.perf_counter() - rec["t0"],
            path="wave" if wstats is not None else "chain",
        )
        wave_groups = None
        if wstats is not None:
            wave_groups = self._wave_resolve(
                rec["fwk"],
                rec["batch"],
                both[0],
                wstats,
                kernel="chain.chain_dispatch",
            )
        self._process_results(
            rec["fwk"],
            rec["state"],
            rec["batch"],
            both[0],
            both[1],
            rec["reasons"],
            outcomes,
            wave_groups=wave_groups,
            kernel="chain.chain_dispatch",
        )
        self._record_batch_metrics(
            rec["fwk"].profile_name,
            rec["batch"],
            outcomes,
            time.perf_counter() - rec["t0"],
        )
        self._flush_binds()
        if t_h is not None and tr.enabled:
            tr.complete(
                "harvest.wave" if wstats is not None else "harvest.chain",
                t_h,
                cat="batch",
                bid=rec.get("bid"),
                pods=len(rec["batch"]),
            )
        return outcomes

    def _hostname_dev(self, vocab):
        hk_id = vocab.label_keys.lookup(HOSTNAME_LABEL)
        if getattr(self, "_hk_cached", None) != hk_id:
            self._hostname_key_dev = jnp.asarray(hk_id, I32)
            self._hk_cached = hk_id
        return self._hostname_key_dev

    def _place_db(self, db):
        """Mesh placement for a DeviceBatch: pod-major tensors sharded
        over the mesh's pods axis (no-op without meshDispatch).  The
        snapshot half rides DeviceClusterCache(mesh=...)."""
        if self.mesh is None:
            return db
        from kubernetes_tpu.parallel.mesh import place_batch

        return place_batch(self.mesh, db)

    def _p_bucket(self, n: int) -> int:
        """Pod-batch bucket: bucket_cap padded to the mesh's pods-axis
        multiple so sharded batches always split evenly (power-of-two
        buckets already divide power-of-two axes; this covers the rest)."""
        cap = bucket_cap(n, 1)
        if self.mesh is not None:
            from kubernetes_tpu.parallel.mesh import pad_to_multiple

            cap = pad_to_multiple(cap, self.mesh.shape["pods"])
        return cap

    def _gang_tables(self, pb, vocab):
        """batch_tables' device arrays, reused across batches with the same
        key sets + node labels (re-uploading them each batch costs transfer
        round trips on remote device links)."""
        import numpy as np

        hk_id = vocab.label_keys.lookup(HOSTNAME_LABEL)
        tkey = (
            self.mirror.static_generation,
            self.mirror._full_packs,
            len(vocab.label_vals),
            tuple(np.unique(pb.tsc_topo_key).tolist()),
            tuple(np.unique(pb.aff_topo_key).tolist()),
        )
        if getattr(self, "_tables_key", None) != tkey:
            self._tables = gang.batch_tables(
                pb.tsc_topo_key,
                pb.aff_topo_key,
                self.mirror.nodes.label_vals,
                hk_id,
            )
            self._tables_key = tkey
        return self._tables

    def _wave_tables(self, pb):
        """Host half of the wave's interaction partitioner: distinct-term
        tables (spread + inter-pod + port) for the factored admission pass
        (ops/wave.py).  None only when duplicate hostname labels disqualify
        the factored algebra — the caller falls back to the gang scan.

        Memoized like _gang_tables: template-stamped drains repeat the
        same term content batch after batch, so the np.unique row-dedup
        and per-key domain compaction collapse to one digest check."""
        import hashlib

        import numpy as np

        from kubernetes_tpu.ops import wave as wave_ops

        hk_id = self.mirror.vocab.label_keys.lookup(HOSTNAME_LABEL)
        h = hashlib.blake2b(digest_size=16)
        for a in (
            pb.valid,
            pb.ns_id,
            pb.want_ppk,
            pb.want_ip,
            pb.want_wild,
            pb.tsc_topo_key,
            pb.tsc_table.req_key,
            pb.tsc_table.req_op,
            pb.tsc_table.req_vals,
            pb.tsc_table.req_rhs,
            pb.tsc_table.term_valid,
            pb.aff_kind,
            pb.aff_topo_key,
            pb.aff_weight,
            pb.aff_ns_all,
            pb.aff_ns_ids,
            pb.aff_table.req_key,
            pb.aff_table.req_op,
            pb.aff_table.req_vals,
            pb.aff_table.req_rhs,
            pb.aff_table.term_valid,
        ):
            h.update(np.ascontiguousarray(a).tobytes())
        key = (
            self.mirror.static_generation,
            self.mirror._full_packs,
            len(self.mirror.vocab.label_vals),
            hk_id,
            h.digest(),
        )
        cached = getattr(self, "_wave_tables_memo", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        wt = wave_ops.wave_tables(
            pb,
            self.mirror.nodes.label_vals,
            hk_id,
            hostnames_unique=self.mirror.hostnames_unique,
        )
        self._wave_tables_memo = (key, wt)
        return wt

    # ----- the workloads tier: gang/coscheduling + DRA + volume topology ----
    #
    # One fused dispatch (ops/coscheduling.py) schedules batches carrying
    # PodGroup gangs, DRA resource claims, and bound-volume topology —
    # workloads the per-pod reference pipeline (and our one-pod fallback)
    # handles only serially.  Gangs admit all-or-nothing with device-side
    # rollback; claims allocate inside the admission scan so in-batch
    # contention resolves in queue order; volume topology rides a kernel
    # mask.  Behind the gangDispatch kill-switch; bit-identical to the
    # serial gang/DRA oracle (oracle/workloads.py, paritycheck.py).

    def _pull_gang_siblings(self, batch):
        """Queue-level gang sibling-pull: when a popped batch carries gang
        members whose quorum the batch itself cannot cover, pop the gangs'
        remaining ACTIVE members into the same batch (QueueSort order
        preserved among them).  Backoff/unschedulable members stay parked —
        their gates still apply — so an uncoverable gang still takes the
        waiting/timeout barrier, just without burning an attempt per pop
        split.  Caller holds _mu.  Gang-free batches pay one dict probe
        per pod and never scan the queue."""
        present: Dict[str, int] = {}
        for qp in batch:
            key = self._workloads_group_of(qp.pod)
            if key is not None:
                present[key] = present.get(key, 0) + 1
        wanted = set()
        for key, n in present.items():
            pg = self.gangs.get(key)
            if pg is not None and n + self.gangs.bound_count(key) < pg.min_member:
                wanted.add(key)
        if not wanted:
            return []
        return self.queue.pop_siblings(
            lambda qp: self._workloads_group_of(qp.pod) in wanted
        )

    def _workloads_group_of(self, pod):
        """Gang key of a pod, or None when it has no REGISTERED PodGroup
        (pods referencing an unknown group schedule as ordinary pods)."""
        key = wlg.group_key_of(pod)
        if key is None or self.gangs.get(key) is None:
            return None
        return key

    def _vol_kernel_ok(self, pod) -> bool:
        """True when the pod's volume surface is exactly what the kernel
        mask covers: every PVC exists, fully bound, its PV present.  Any
        other shape (WaitForFirstConsumer, immediate unbound, missing PV)
        keeps the serial VolumeBinding path — including its
        unresolvable-status semantics."""
        for name in pod.pvc_names():
            pvc = self.pvc_cache.get(f"{pod.namespace}/{name}")
            if pvc is None or not pvc.is_fully_bound():
                return False
            if self.pv_cache.get(pvc.volume_name) is None:
                return False
        return True

    def _workloads_eligible(self, fwk, batch) -> bool:
        """Spec-only pre-gate: True when the batch MIGHT take the
        workloads dispatch — at least one gang/DRA/volume-kernel pod, and
        none of the spec-level disqualifiers (nominations, extenders, host
        ports, score-relevant host plugins, sampling compat).  The
        host-filter COVERAGE check runs post-PreFilter inside the dispatch
        (_workloads_covered), where the plugins' Skip verdicts are known."""
        if not self.config.gang_dispatch or self._sampling_active(fwk):
            return False
        hf_names = {p.name for p in fwk.host_filter_plugins()}
        dra_on = "DynamicResources" in hf_names
        vol_on = "VolumeBinding" in hf_names
        # cheap O(P) relevance pass FIRST: the common direct-path batch has
        # no gang/claim/volume pod at all and must not pay the plugin /
        # extender disqualifier scan below
        if not any(
            (dra_on and qp.pod.resource_claims)
            or (vol_on and qp.pod.pvc_names())
            or self._workloads_group_of(qp.pod) is not None
            for qp in batch
        ):
            return False
        ns_plugins = self._normalizing_score_plugins(fwk)
        host_scores = [
            p
            for p in fwk.host_score_plugins()
            if fwk.score_weights.get(p.name, 0)
        ]
        for qp in batch:
            pod = qp.pod
            if pod.nominated_node_name or pod.host_ports():
                return False
            for e in self.extenders:
                if e.is_interested(pod):
                    return False
            for pl in ns_plugins:
                if pl.score_relevant(pod):
                    return False
            for pl in host_scores:
                if pl.score_relevant(pod):
                    return False
            if (
                vol_on
                and pod.pvc_names()
                and not self._vol_kernel_ok(pod)
            ):
                return False
        return True

    def _workloads_covered(self, fwk, state, pods) -> bool:
        """Post-PreFilter coverage check: every host Filter plugin still
        ACTIVE for some pod must be one the kernel replaces —
        DynamicResources (the batched allocator), VolumeBinding
        (bound-topology kernel mask; _vol_kernel_ok pre-checked),
        VolumeZone (zone-labeled PV constraints fold into the same mask as
        per-label In-conjunctions — _vol_tables), or NodeVolumeLimits when
        no CSINode advertises limits (its Filter is then a constant
        success).  Anything else falls back to the serial split path."""
        for p in fwk.host_filter_plugins():
            if p.name in ("DynamicResources", "VolumeBinding", "VolumeZone"):
                continue
            if p.name == "NodeVolumeLimits" and not self.csinodes:
                continue
            for pod in pods:
                if not state.is_filter_skipped(pod.uid, p.name):
                    return False
        return True

    def _hostnames_unique(self) -> bool:
        """The wave/workloads factored algebra treats hostname topology as
        node identity — duplicate hostname label values disqualify it.
        The bit is computed once per SNAPSHOT by the mirror (memoized on
        the static lineage), not re-derived per batch."""
        return self.mirror.hostnames_unique

    def _vol_tables(self, pods, p_cap: int, vocab):
        """Pack bound-PV node-affinity DNFs into the volume-topology kernel
        mask's tables: one PV per PV2 slot, ORed selector terms on the
        DTable term axis (ops/coscheduling.volume_topology_mask).  A PV
        carrying zone/region LABELS (the pre-CSI topology convention the
        VolumeZone plugin judges) contributes one extra slot whose single
        conjunction requires ``key In zone-set`` per topology label — the
        AND across slots reproduces volume_zone.go's every-label-must-
        match semantics, so zone-labeled shapes ride the kernel instead of
        falling back to the serial path.  Returns None when no pod
        carries an affinity- or zone-constrained bound PV."""
        import numpy as np

        from kubernetes_tpu.api import labels as k8slabels
        from kubernetes_tpu.api import storage as storage_api
        from kubernetes_tpu.framework.volume_plugins import _zone_value_set
        from kubernetes_tpu.ops.common import DTable
        from kubernetes_tpu.snapshot.schema import pack_conjunction_table
        from kubernetes_tpu.snapshot.selectors import (
            CompiledRequirements,
            compile_node_selector_dnf,
        )

        per_pod: List[list] = []
        bad = np.zeros((p_cap,), bool)
        any_rows = False
        for i, pod in enumerate(pods):
            rows = []
            for name in pod.pvc_names():
                pvc = self.pvc_cache.get(f"{pod.namespace}/{name}")
                if pvc is None or not pvc.is_fully_bound():
                    bad[i] = True  # gate should have routed this away
                    continue
                pv = self.pv_cache.get(pvc.volume_name)
                if pv is None:
                    bad[i] = True
                    continue
                zone_c = CompiledRequirements()
                for key in storage_api.VOLUME_TOPOLOGY_LABELS:
                    if key in pv.labels:
                        zone_c.add(
                            key,
                            k8slabels.IN,
                            sorted(_zone_value_set(pv.labels[key])),
                            vocab,
                        )
                if zone_c.n_reqs:
                    rows.append([zone_c])
                if pv.node_affinity is None:
                    continue  # nil affinity matches everywhere
                rows.append(compile_node_selector_dnf(pv.node_affinity, vocab))
            per_pod.append(rows)
            any_rows = any_rows or bool(rows)
        if not any_rows and not bad.any():
            return None
        pv_cap = bucket_cap(max((len(r) for r in per_pod), default=1) or 1, 1)
        flat: List[list] = []
        valid = np.zeros((p_cap, pv_cap), bool)
        for i in range(p_cap):
            rows = per_pod[i] if i < len(per_pod) else []
            for j in range(pv_cap):
                if j < len(rows):
                    flat.append(rows[j])
                    valid[i, j] = True
                else:
                    flat.append([])
        ct = pack_conjunction_table(flat)
        T, R, V = ct.req_key.shape[1], ct.req_key.shape[2], ct.req_vals.shape[3]

        def rs(a, tail):
            return jnp.asarray(
                np.asarray(a).reshape((p_cap, pv_cap) + tail)
            )

        table = DTable(
            req_key=rs(ct.req_key, (T, R)),
            req_op=rs(ct.req_op, (T, R)),
            req_vals=rs(ct.req_vals, (T, R, V)),
            req_rhs=rs(ct.req_rhs, (T, R)),
            term_valid=rs(ct.term_valid, (T,)),
        )
        return dict(
            vol_table=table,
            vol_valid=jnp.asarray(valid),
            vol_bad=jnp.asarray(bad),
        )

    def _try_dispatch_workloads(self, fwk, batch):
        """The workloads dispatch: gang planning + one fused admission
        kernel + the commit walk.  Returns the outcome list, or None when
        the batch should fall through to the existing machinery (the
        caller treats None as "not handled"; nothing is committed or
        failed before eligibility is certain)."""
        from kubernetes_tpu.ops import coscheduling as cos_ops
        from kubernetes_tpu.ops import dra as dra_ops

        if not self._workloads_eligible(fwk, batch):
            return None
        # device-fault tier: an open workloads breaker refuses the path
        # BEFORE any side effect — the caller falls through to the
        # existing machinery, i.e. the gangDispatch kill-switch fallback
        # (decision-identical for DRA/volume pods; gang pods schedule
        # individually, exactly the documented degraded semantics)
        if self._breaker_blocked("coscheduling.workloads_run"):
            return None
        outcomes: List[ScheduleOutcome] = []
        self._chain = None
        with self._mu:
            state = CycleState()
            vocab = self.mirror.vocab
            for qp in batch:
                for k, v in qp.pod.labels.items():
                    vocab.intern_label(k, v)
            self._sync_mirror_external()
            if not self._hostnames_unique():
                return None  # factored hostname-domain trick invalid
            from kubernetes_tpu.metrics import Trace

            trace = Trace(
                "Scheduling workloads batch",
                clock=time.perf_counter,
                pods=len(batch),
                profile=fwk.profile_name,
            )

            # 0. PreFilter (missing/deleted claims and PVCs reject here).
            # Failures are NOT emitted until the coverage check commits to
            # this path — a fallback must leave no trace.
            pf_failures = (
                fwk.run_pre_filter(state, [qp.pod for qp in batch]) or {}
            )
            live_pods = [
                qp.pod for qp in batch if qp.pod.uid not in pf_failures
            ]
            if not self._workloads_covered(fwk, state, live_pods):
                return None  # an uncovered host filter is active — serial
            if pf_failures:
                live = []
                for qp in batch:
                    s = pf_failures.get(qp.pod.uid)
                    if s is None:
                        live.append(qp)
                        continue
                    self.metrics["schedule_attempts"] += 1
                    outcomes.append(
                        self._post_filter_or_fail_locked(
                            fwk, state, qp, s, 0
                        )
                    )
                batch = live
                if not batch:
                    return outcomes
            trace.step("PreFilter done")

            # 1. gang planning: quorum/timeout barriers reject pre-dispatch
            # (the coscheduling plugin's PreFilter/Permit-timeout verdicts)
            keys = [self._workloads_group_of(qp.pod) for qp in batch]
            present: Dict[str, int] = {}
            for key in keys:
                if key is not None:
                    present[key] = present.get(key, 0) + 1
            needs: Dict[str, int] = {}
            rejected: Dict[str, Status] = {}
            for key, n_present in present.items():
                pg = self.gangs.get(key)
                bound = self.gangs.bound_count(key)
                if self.gangs.timed_out(key):
                    rejected[key] = Status.unresolvable(
                        f'pod group "{key}" scheduling timed out after '
                        f"{pg.schedule_timeout_s:.0f}s",
                        plugin="Coscheduling",
                    )
                    self.gangs.close_window(key)
                elif n_present + bound < pg.min_member:
                    rejected[key] = Status.unschedulable(
                        f'pod group "{key}" has {n_present + bound}/'
                        f"{pg.min_member} members; waiting for the rest",
                        plugin="Coscheduling",
                    )
                    self.gangs.note_attempt(key)
                else:
                    needs[key] = max(0, pg.min_member - bound)
                    self.gangs.note_attempt(key)
            if rejected:
                live = []
                for qp, key in zip(batch, keys):
                    if key in rejected:
                        s = rejected[key]
                        self.metrics["schedule_attempts"] += 1
                        if self.flight.enabled:
                            self.flight.record(
                                qp.pod.uid,
                                "unschedulable",
                                {"plugins": ["Coscheduling"], "reasons": list(s.reasons)[:3]},
                            )
                        self._handle_failure(qp, s)
                        outcomes.append(
                            ScheduleOutcome(qp.pod, None, s, 0)
                        )
                    else:
                        live.append(qp)
                batch = live
                if not batch:
                    return outcomes

            # 2. canonical order: gang members contiguous at first member
            order, gang_positions = wlg.plan_batch(
                [qp.pod for qp in batch], group_of=self._workloads_group_of
            )
            ordered = [batch[i] for i in order]
            pods = [qp.pod for qp in ordered]
            trace.step("Gang plan done")

            # 3. pack (the scan path's prep, workloads tables added)
            enabled = fwk.device_enabled()
            weights = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
            t_pack = time.perf_counter()
            self._repack_mirror()
            self.phases.add("pack", time.perf_counter() - t_pack)
            self._p_cap_max = max(self._p_cap_max, self._p_bucket(len(pods)))
            p_cap = self._p_cap_max
            pb = pack_pod_batch(
                pods,
                vocab,
                k_cap=self.mirror.nodes.k_cap,
                p_cap=p_cap,
                namespace_labels=self.namespace_labels,
            )
            t_sync = time.perf_counter()
            from kubernetes_tpu.observability import kernels as kernels_mod

            try:
                dc = self._sync_device_cluster(vocab)
            except kernels_mod.DispatchFailed as e:
                # persistent snapshot-placement failure (hbm_oom class)
                # PAST the commit point (PreFilter failures and quorum
                # rejections already emitted): finish the live pods on
                # the ordinary machinery — the same move as the
                # wave-tables drift guard below; nothing double-processes
                self._note_dispatch_failure(e)
                return outcomes + self._schedule_batch(
                    ordered, try_workloads=False
                )
            db = self._place_db(DeviceBatch.from_host(pb))
            self.phases.add("h2d", time.perf_counter() - t_sync)
            v_cap = bucket_cap(len(vocab.label_vals))
            hostname_key = self._hostname_dev(vocab)
            tables = self._gang_tables(pb, vocab)
            wt = self._wave_tables(pb)
            if wt is None:
                # The duplicate-hostname pre-check mirrors wave_tables'
                # only remaining refusal condition (in-batch ports ride
                # the factored port carry now), so this is unreachable
                # today — but PreFilter failures and quorum rejections
                # were already emitted above, so if the copies ever drift
                # the only safe move is to finish the REMAINING live pods
                # on the ordinary machinery (gang semantics degrade for
                # one batch; nothing double-processes).  Returning None
                # here instead would hand the caller the ORIGINAL batch,
                # re-processing pods whose failures already landed.
                return outcomes + self._schedule_batch(
                    ordered, try_workloads=False
                )
            has_interpod = bool(
                (pb.aff_kind != PAD).any()
                or (self.mirror.existing.term_kind != PAD).any()
            )
            has_spread = bool((pb.tsc_topo_key != PAD).any())
            has_images = bool((pb.img_ids >= 0).any())

            # 4. workloads tables: gang arrays + DRA pack + volume DNFs
            gid, gfirst, glast, gneed, g_cap, slot_keys = wlg.gang_arrays(
                p_cap, gang_positions, needs
            )
            dt = None
            claim_keys: List[str] = []
            dra_on = any(
                p.name == "DynamicResources"
                for p in fwk.host_filter_plugins()
            )
            claims_by_key = {}
            if dra_on and any(p.resource_claims for p in pods):
                # the WHOLE cache view, not just batch-referenced claims:
                # free0 must exclude devices held by ANY allocated claim
                # (the serial plugin's _allocated_devices walks the full
                # cache too) — a batch-local view would hand out devices
                # earlier drains already granted
                claims_by_key = {
                    c.key: c for c in self.claim_cache.list()
                }
                dt = dra_ops.dra_tables(
                    pods,
                    self.mirror.nodes.name_to_idx,
                    self.mirror.nodes.n_cap,
                    p_cap,
                    list(self.resource_slices.values()),
                    self.device_classes,
                    claims_by_key,
                )
                if dt is not None:
                    claim_keys = dt.pop("claim_keys")
                    dt.pop("has_claims")
            volt = self._vol_tables(pods, p_cap, vocab)
            nom_node = nom_prio = nom_req = None
            if len(self.nominator):
                nom_node, nom_prio, nom_req = self._nominated_arrays(
                    {qp.pod.uid for qp in ordered}
                )
            self.metrics["workload_batches"] += 1

        # 5. one fused dispatch (outside the lock, like every device path)
        from kubernetes_tpu.observability import kernels as kernels_mod

        t_gang = time.perf_counter()
        try:
            chosen_dev, n_feas_dev, reason_counts, tallies, wl_dev = (
                cos_ops.workloads_run(
                dc,
                db,
                hostname_key,
                v_cap,
                g_cap,
                wt["tid_sp"],
                wt["rep_sp_p"],
                wt["rep_sp_c"],
                wt["tid_ip"],
                wt["rep_ip_p"],
                wt["rep_ip_u"],
                wt["ip_cdv_tab"],
                jnp.asarray(gid),
                jnp.asarray(gfirst),
                jnp.asarray(glast),
                jnp.asarray(gneed),
                **(dt or {}),
                **(volt or {}),
                has_interpod=has_interpod,
                has_spread=has_spread,
                has_images=has_images,
                enabled=enabled,
                weights=weights,
                nom_node=nom_node,
                nom_prio=nom_prio,
                nom_req=nom_req,
                    d2_cap=wt["d2_cap"],
                    fit_strategy=fwk.fit_strategy(),
                    **tables,
                )
            )
            t_d2h = time.perf_counter()
            self.phases.add("device", t_d2h - t_gang)
            n_bound = len(self.mirror.nodes.names)

            def _validate_wl(fetched):
                import numpy as np

                ch = np.asarray(fetched[0])
                if ((ch < -1) | (ch >= n_bound)).any():
                    return "chosen index out of node range"
                return None

            fetched = self._d2h_guarded(
                (
                    chosen_dev,
                    n_feas_dev,
                    wl_dev["raw"],
                    wl_dev["spec"],
                    wl_dev["gang_admit"],
                    wl_dev["gang_landed"],
                    wl_dev["claim_node"] if dt is not None else None,
                ),
                kernel="coscheduling.workloads_run",
                validate=_validate_wl,
            )
        except kernels_mod.DispatchFailed as e:
            # abandoned workloads dispatch: nothing committed yet — the
            # live batch degrades to per-pod host-plugin cycles (gang
            # members schedule individually, the documented kill-switch
            # semantics) while the breaker keeps the kernel parked
            self._note_dispatch_failure(e)
            return outcomes + self._schedule_batch_serial(fwk, ordered)
        chosen, n_feas, raw, spec, gang_admit, gang_landed, claim_node = (
            fetched
        )
        self.phases.add("d2h", time.perf_counter() - t_d2h)
        self.prom.recorder.observe(
            self.prom.gang_dispatch_duration,
            time.perf_counter() - t_gang,
            path="workloads",
        )
        self._trace_dispatch("workloads", t_gang, ordered)
        trace.step("Workloads dispatch done")

        self._process_workloads_results(
            fwk,
            state,
            ordered,
            chosen,
            n_feas,
            raw,
            spec,
            reason_counts,
            gang_admit,
            gang_landed,
            gang_positions,
            slot_keys,
            needs,
            claim_keys,
            claims_by_key,
            claim_node,
            outcomes,
        )
        trace.step("Commits done")
        trace.log_if_long()
        return outcomes

    def _wl_host_replay(self, fwk, state, pod, node_name: str) -> Status:
        """Re-run PreFilter (fresh claim/volume ledgers) + the chosen
        node's host Filter walk for a DRA/volume pod, so Reserve/PreBind
        read per-pod decisions consistent with the live cache — the kernel
        proved feasibility; this materializes the concrete device/PV picks
        in cycle state, claim contention resolving in the same batch order
        the kernel replayed."""
        with self._mu:
            pf = fwk.run_pre_filter(state, [pod])
            if pf:
                s = pf.get(pod.uid)
                if s is not None:
                    return s
            st = self.oracle_view()
            ns = st.nodes.get(node_name)
            if ns is None:
                return Status.error(f"node {node_name} vanished", plugin="Workloads")
            return fwk.run_host_filters(state, pod, ns)

    def _process_workloads_results(
        self,
        fwk,
        state,
        ordered,
        chosen,
        n_feas,
        raw,
        spec,
        reason_counts,
        gang_admit,
        gang_landed,
        gang_positions,
        slot_keys,
        needs,
        claim_keys,
        claims_by_key,
        claim_node,
        outcomes,
    ) -> None:
        """The workloads result walk: gang admit/rollback accounting +
        flight events, rolled-back members failed WITHOUT preemption (a
        dry run for a pod its own gang rolled back just churns victims),
        genuine failures through the normal diagnosis path (DRA/volume
        lanes renamed to their plugin reasons), successes through the
        host-replay commit."""
        import numpy as np

        t_commit = time.perf_counter()
        node_names = self.mirror.nodes.names
        n_nodes = len(self.cache.real_nodes())
        counts = None
        fr = self.flight
        chosen_n = np.asarray(chosen)[: len(ordered)]
        spec_n = np.asarray(spec)[: len(ordered)]
        with self._mu:
            self.metrics["schedule_attempts"] += len(ordered)
            # speculation stats: pods whose admitted placement survived
            # the serial admission pass unchanged (the wave's admitted-as-
            # speculated notion, here over gang/DRA-carried state)
            self.metrics["workload_spec_admitted"] += int(
                np.sum((chosen_n == spec_n) & (chosen_n >= 0))
            )
            # claim allocations count ONCE per newly-allocated claim (a
            # shared claim is one allocation however many pods reference
            # it; pre-allocated claims don't count)
            if claim_node is not None:
                new_allocs = sum(
                    1
                    for i, ckey in enumerate(claim_keys)
                    if int(claim_node[i]) >= 0
                    and claims_by_key[ckey].allocation is None
                )
                if new_allocs:
                    self.metrics["dra_claims_allocated"] += new_allocs
                    self.prom.dra_allocations.inc(new_allocs)
        pos_gang: Dict[int, str] = {}
        for key, positions in gang_positions.items():
            for pos in positions:
                pos_gang[pos] = key
        slot_of = {key: i for i, key in enumerate(slot_keys)}

        # gang verdicts: metrics + flight + scheduling-window bookkeeping
        for key, positions in gang_positions.items():
            slot = slot_of[key]
            admit = int(gang_admit[slot])
            landed = int(gang_landed[slot])
            with self._mu:
                if admit == 1:
                    self.gangs.close_window(key)
                    self.metrics["gang_admitted"] += landed
                    self.prom.gang_admitted.inc(landed)
                elif admit == 0:
                    self.metrics["gang_rolled_back"] += 1
                    self.prom.gang_rollbacks.inc()
            if fr.enabled:
                kind = "gang_admit" if admit == 1 else "gang_rollback"
                for pos in positions:
                    fr.record(
                        ordered[pos].pod.uid,
                        kind,
                        {
                            "group": key,
                            "landed": landed,
                            "need": needs.get(key, 0),
                        },
                    )

        for i, qp in enumerate(ordered):
            pod = qp.pod
            idx = int(chosen[i])
            if idx < 0:
                key = pos_gang.get(i)
                if key is not None and int(raw[i]) >= 0:
                    # placed by the admission pass, rolled back with its
                    # gang — not a feasibility failure, no preemption
                    slot = slot_of[key]
                    pg = self.gangs.get(key)
                    s = Status.unschedulable(
                        f'pod group "{key}" admission rolled back: '
                        f"{int(gang_landed[slot])}/"
                        f"{pg.min_member if pg else 0} members schedulable",
                        plugin="Coscheduling",
                    )
                    with self._mu:
                        self._handle_failure(qp, s)
                    outcomes.append(
                        ScheduleOutcome(pod, None, s, int(n_feas[i]))
                    )
                    continue
                if counts is None:
                    counts = self._d2h(
                        reason_counts, kernel="coscheduling.workloads_run"
                    )
                diag = {
                    k: int(c)
                    for k, c in zip(gang.DIAG_KERNELS, counts[i])
                    if c > 0
                }
                plugins = set(diag)
                # workloads batches carry no host ports, so the dynamic
                # hv lane counts exactly the DRA rejections; the extra
                # mask lane is the volume-topology kernel mask
                if "NodePorts" in diag and pod.resource_claims:
                    n = diag.pop("NodePorts")
                    plugins.discard("NodePorts")
                    diag["cannot allocate all devices"] = n
                    plugins.add("DynamicResources")
                if "HostFilters" in diag:
                    n = diag.pop("HostFilters")
                    plugins.discard("HostFilters")
                    diag["node(s) had volume node affinity conflict"] = n
                    plugins.add("VolumeBinding")
                status = Status.unschedulable(
                    fit_error_message(n_nodes, diag)
                )
                outcomes.append(
                    self._post_filter_or_fail(
                        fwk, state, qp, status, int(n_feas[i]), diag, plugins
                    )
                )
                continue
            node_name = node_names[idx]
            if pod.resource_claims or pod.pvc_names():
                s = self._wl_host_replay(fwk, state, pod, node_name)
                if not s.ok:
                    # a race moved the ground truth between dispatch and
                    # commit (informer event, concurrent binder) — fail
                    # the pod; the requeue converges like any lost race
                    outcomes.append(
                        self._post_filter_or_fail(
                            fwk, state, qp, s, int(n_feas[i])
                        )
                    )
                    continue
            outcome = self._commit(fwk, state, qp, node_name, int(n_feas[i]))
            if outcome.node is not None:
                with self._mu:
                    self.gangs.note_placed(pod)
                    if pod.resource_claims:
                        self.metrics["dra_pods"] += 1
                if fr.enabled and pod.resource_claims:
                    fr.record(
                        pod.uid,
                        "dra_alloc",
                        {
                            "node": node_name,
                            "claims": list(pod.resource_claims)[:4],
                        },
                    )
            outcomes.append(outcome)
        self.phases.add("commit", time.perf_counter() - t_commit)

    def _wave_resolve(self, fwk, batch, chosen, wstats_dev, kernel=None):
        """Harvest one wave's speculation stats: admitted/demoted counters,
        a ``wave_demoted`` flight-recorder event (with the conflicting
        term) per corrected pod, and — when the framework permits lean
        binds — the interaction-group split the bulk commit path uses.
        Returns the per-pod group ids, or None when commits must walk the
        per-pod path."""
        import numpy as np

        from kubernetes_tpu.ops import wave as wave_ops

        t0 = time.perf_counter()
        stats = np.asarray(self._d2h(wstats_dev, kernel=kernel))
        n = len(batch)
        spec, kinds, cterms = stats[0][:n], stats[1][:n], stats[2][:n]
        chosen_n = np.asarray(chosen)[:n]
        demoted = np.nonzero(chosen_n != spec)[0]
        # "admitted" = a speculative PLACEMENT survived; pods unschedulable
        # in both passes are neither admitted nor demoted
        admitted = int(np.sum((chosen_n == spec) & (chosen_n >= 0)))
        conflicts: Dict[str, int] = {}
        fr = self.flight
        fr_on = fr.enabled
        names = self.mirror.nodes.names
        for i in demoted:
            code = int(kinds[i])
            upgraded = code == wave_ops.DEMOTE_UPGRADE
            if not upgraded:
                kind = wave_ops.DEMOTE_KINDS.get(code, "score")
                conflicts[kind] = conflicts.get(kind, 0) + 1
            if fr_on:
                c = int(chosen_n[i])
                if upgraded:
                    # infeasible alone, placed once a batch peer committed
                    # (required affinity satisfied) — not a conflict
                    detail = {}
                    if 0 <= c < len(names):
                        detail["node"] = names[c]
                    fr.record(batch[i].pod.uid, "wave_upgraded", detail)
                    continue
                detail = {"kind": kind, "term": int(cterms[i])}
                s = int(spec[i])
                if 0 <= s < len(names):
                    detail["spec_node"] = names[s]
                if 0 <= c < len(names):
                    detail["node"] = names[c]
                fr.record(batch[i].pod.uid, "wave_demoted", detail)
        with self._mu:
            self.metrics["wave_pods"] += n
            self.metrics["wave_admitted"] += admitted
        self.prom.wave_admitted.inc(admitted)
        for kind, cnt in conflicts.items():
            self.prom.wave_conflicts.inc(cnt, kind=kind)
        # Bulk-commit eligibility: lean_bind_ok()'s and the Reserve/Permit
        # "covered by host filters" no-op guarantees are BOTH conditioned
        # on the batch being spec-irrelevant to every host Filter plugin
        # (the fast gate proves this for fast batches) — a wave batch can
        # carry host-filter-relevant pods (the extra_mask route), whose
        # Reserve/PreBind walks must run, so prove irrelevance per pod
        # before routing anything around the per-pod commit path.
        groups = None
        hf = fwk.host_filter_plugins()
        hf_clean = not hf or not any(
            pl.maybe_relevant(qp.pod) for qp in batch for pl in hf
        )
        rp_ok = not fwk.has_reserve_or_permit() or (
            fwk.reserve_permit_covered_by_host_filters() and hf_clean
        )
        if (
            fwk.lean_bind_ok()
            and hf_clean
            and rp_ok
            and not self.extenders
        ):
            groups, n_groups = wave_ops.interaction_groups(
                [qp.pod for qp in batch]
            )
            with self._mu:
                self.metrics["wave_groups"] = (
                    self.metrics.get("wave_groups", 0) + n_groups
                )
        self.phases.add("wave_resolve", time.perf_counter() - t0)
        return groups

    def _static_device_cluster(self) -> DeviceCluster:
        """DeviceCluster cached across batches for STATIC reads only
        (labels/taints/allocatable/images) — usage-only churn (generation)
        does NOT invalidate it, so steady-state batches upload nothing.

        The placed-pod tensors are replaced by an EMPTY pack: every consumer
        of this cluster (fastpath static_eval, preemption narrowing) reads
        node-static fields only, and the placed-pod payload dominates the
        re-upload cost under node churn."""
        from kubernetes_tpu.snapshot.schema import pack_existing_pods

        key = (
            self.mirror.static_generation,
            self.mirror._full_packs,
            len(self.mirror.vocab.label_vals),
        )
        if getattr(self, "_static_dc_key", None) != key:
            empty = pack_existing_pods(
                [],
                self.mirror.nodes.name_to_idx,
                self.mirror.vocab,
                k_cap=self.mirror.nodes.k_cap,
            )
            sdc = DeviceCluster.from_host(
                self.mirror.nodes, empty, self.mirror.vocab
            )
            if self.mesh is not None:
                from kubernetes_tpu.parallel.mesh import place_cluster

                sdc = place_cluster(self.mesh, sdc)
            self._static_dc = sdc
            self._static_dc_key = key
        return self._static_dc

    def _try_fast_schedule(
        self, fwk, state, batch, enabled, weights, outcomes
    ) -> Optional[List[ScheduleOutcome]]:
        """Synchronous signature fast path (the _schedule_batch fallback for
        batches the pipelined loop didn't claim).

        Returns completed outcomes, or None when the batch isn't eligible
        (ineligible pods, or static score raws vary so normalization is
        batch-state-dependent) — the caller falls back to the gang scan.
        """
        keys = self._batch_signature_keys(batch)
        if keys is None:
            return None
        rows = self._fast_sig_rows(fwk, batch, keys, enabled, weights)
        if rows is None:
            return None
        rec = self._fast_dispatch(fwk, state, batch, keys, enabled, weights)
        if rec is None:
            return None
        outcomes.extend(self._finish_fast(rec))
        return outcomes

    def _fast_sig_rows(self, fwk, batch, keys, enabled, weights):
        """Per-signature static rows (masks + raw scores) for this batch,
        cached across batches keyed on the static snapshot: steady-state
        batches reuse them and make ZERO static_eval device calls
        (signatures recur — bench workloads have ~10).  Returns the row
        cache, or None when any signature's static score raws vary over its
        feasible set (normalization would be batch-state-dependent — the
        greedy's argmax-neutrality argument breaks, so the batch must take
        the gang scan)."""
        import numpy as np

        from kubernetes_tpu.ops import fastpath as ops_fp

        vocab = self.mirror.vocab
        dc_key = (
            self.mirror.static_generation,
            self.mirror._full_packs,
            fwk.profile_name,
        )
        cache = getattr(self, "_sig_cache", None)
        if cache is None or self._sig_cache_key != dc_key:
            cache = self._sig_cache = {}
            self._sig_cache_key = dc_key

        order: Dict[object, int] = {}
        reps: List[Pod] = []
        for k, qp in zip(keys, batch):
            if k not in order and k not in cache:
                order[k] = len(reps)
                reps.append(qp.pod)

        w_taint, w_naff = weights[0], weights[1]
        if reps and self._breaker_blocked("fastpath.static_eval"):
            # open static-eval breaker: fail the fast gate — the batch
            # takes the direct scan path, which reads no signature rows
            return None
        if reps:
            has_images = any(p.images for p in reps)
            pb = pack_pod_batch(
                reps,
                vocab,
                k_cap=self.mirror.nodes.k_cap,
                # floor 16: the count of NEW signatures per batch is noisy
                # (1 here, 2 there) and every distinct count would be a
                # fresh static_eval compile — one [16, N] shape covers them
                p_cap=self._p_bucket(max(len(reps), 16)),
            )
            db = self._place_db(DeviceBatch.from_host(pb))
            dc = self._static_device_cluster()
            from kubernetes_tpu.observability import kernels as kernels_mod

            try:
                res = ops_fp.static_eval(
                    dc, db, enabled=enabled, has_images=has_images
                )
                res = {
                    k: np.asarray(v)
                    for k, v in self._d2h_guarded(
                        res, kernel="fastpath.static_eval"
                    ).items()
                }
            except kernels_mod.DispatchFailed as e:
                # abandoned static eval: the fast gate fails and the batch
                # rides the direct scan path (no signature rows needed)
                self._note_dispatch_failure(e)
                return None
            for k, s in order.items():
                row = {name: res[name][s] for name in res}
                # Normalized static scores are argmax-neutral ONLY when
                # their raws are constant over the feasible set (then every
                # feasible node gets the same normalized value).
                m = row["mask"]
                const_ok = True
                for w, raw in (
                    (w_taint, row["taint_raw"]),
                    (w_naff, row["naff_raw"]),
                ):
                    if not w:
                        continue
                    vals = raw[m]
                    if vals.size and int(vals.min()) != int(vals.max()):
                        const_ok = False
                        break
                row["const_ok"] = const_ok
                cache[k] = row
        if any(not cache[k]["const_ok"] for k in keys):
            return None
        return cache

    def _fast_key(self, fwk, enabled, weights):
        return (
            self._external_mutations,
            getattr(self, "_nonfast_commits", 0),
            self.mirror._full_packs,
            enabled,
            weights,
            fwk.profile_name,
        )

    def _fast_dispatch(self, fwk, state, batch, keys, enabled, weights):
        """Run one fast batch and return its pending record.

        Hybrid committer: the persistent source of truth is a host
        FastCommitter (holder["fc"]) that advances at every harvest — small
        batches with an empty pipeline commit directly on it (zero device
        round trips: the interactive/server-loop case), while large or
        pipelined batches dispatch the sig_scan kernel with device-resident
        chained state and START the async result copy (the bulk-drain case;
        the round trip hides behind the next batch's host work).  Both
        paths are bit-identical (property-tested, tests/test_fastpath.py);
        only EXTERNAL mutations or repacks rebuild the lineage."""
        import numpy as np

        from kubernetes_tpu import fastpath as fp

        from kubernetes_tpu.ops import fastpath as ops_fp

        cache = self._sig_cache
        check_fit = "NodeResourcesFit" in enabled
        fc_key = self._fast_key(fwk, enabled, weights)
        holder = getattr(self, "_fastdev", None)
        if holder is None or self._fc_key != fc_key:
            nt = self.mirror.nodes
            holder = self._fastdev = {
                "nt": nt,
                "fc": fp.FastCommitter(nt, weights, check_fit=check_fit),
                "dev": None,  # device state, materialized on demand
                "alloc": None,
                "allowed": None,
                "stack": None,
                "heaps_dirty": False,
                "dev_inflight": 0,  # unharvested device batches — the host
                # committer lags exactly these, so the host path is legal
                # only at zero
                "p_cap": 64,
                # epoch guard (ISSUE 15): lineage epoch (bumped on every
                # device-state rematerialization/resync — a pending record
                # from an older epoch re-derives on the committer) + the
                # host-tracked exact sum of the device usage state
                "epoch": 0,
                "dev_sum": None,
            }
            if getattr(self, "fast_shadow_check", False):
                # invariant-checking mode: a second host FastCommitter
                # replays every batch and must bit-match the chosen path
                holder["shadow"] = fp.FastCommitter(
                    nt, weights, check_fit=check_fit
                )
            self._fc_key = fc_key
            self._sig_objs: Dict[object, fp.Signature] = {}
            self._sig_list: List[fp.Signature] = []

        sigs = self._sig_objs
        for k in keys:
            if k in sigs:
                continue
            row = cache[k]
            req_row, nz, *_ = k
            img_list = None
            if weights[6] and row["img"].any():
                img_list = row["img"].tolist()
            sig = fp.Signature(
                req_row=req_row,
                nz0=nz[0],
                nz1=nz[1],
                all_zero=all(v == 0 for v in req_row),
                static_ok=row["mask"],
                img=img_list,
            )
            sig.sid = len(self._sig_list)
            sigs[k] = sig
            self._sig_list.append(sig)
            holder["stack"] = None  # new signature → restack
        pod_sigs = [sigs[k] for k in keys]
        t0 = time.perf_counter()

        # device-fault tier: an open breaker parks its kernel — resident
        # degrades to sig_scan, sig_scan degrades to the host committer
        # (every rung bit-identical, tests/test_fastpath.py /
        # tests/test_resident.py)
        res_on = getattr(self.config, "resident_drain", False)
        if res_on and self._breaker_blocked("resident.resident_run"):
            res_on = False
        device_ok = res_on or not self._breaker_blocked("fastpath.sig_scan")
        if not device_ok and holder["dev_inflight"] > 0:
            # no device engine available and the host committer lags the
            # unharvested pipeline — the caller flushes and retries or
            # takes the direct path; nothing is committed here
            return None

        # ---- host path: no unharvested device batches + small batch →
        # the greedy answers locally in O(P · log N) with no device link
        # involvement at all (host records already advanced the committer
        # at dispatch, so they may stay pending)
        if holder["dev_inflight"] == 0 and (
            not device_ok
            or len(batch) < getattr(self.config, "fast_device_min", 1024)
        ):
            if holder["heaps_dirty"]:
                # device-batch replays changed scores under the lazy heaps
                holder["fc"].invalidate_heaps()
                holder["heaps_dirty"] = False
            # the host greedy IS the selection step here — attribute it to
            # the device phase it replaces
            t_dev = time.perf_counter()
            choices = holder["fc"].run(pod_sigs)
            self.phases.add("device", time.perf_counter() - t_dev)
            holder["dev"] = None  # device copy (if any) is now stale
            with self._mu:  # metrics is a registered lock-guarded field
                self.metrics["fast_batches"] += 1
            rec = {
                "kind": "fast",
                "fwk": fwk,
                "state": state,
                "batch": batch,
                "keys": keys,
                "pod_sigs": pod_sigs,
                "choices_host": choices,
                "choices_dev": None,
                "rstats_dev": None,
                "rows": cache,
                "weights": weights,
                "check_fit": check_fit,
                "holder": holder,
                "t0": t0,
                "record_metrics": False,
            }
            self._trace_dispatch("fast", t0, batch, rec)
            return rec

        # ---- device path: the greedy commit loop runs as a lax.scan over
        # signature ids with the node-usage state resident in HBM
        # (ops/fastpath.sig_scan) — one dispatch per batch, no [P, N]
        # tensors, bit-identical to the host FastCommitter
        t_h2d = time.perf_counter()
        if holder["stack"] is None:
            holder["stack"] = self._stack_signatures(holder)
        st = holder["stack"]
        # p_cap quantized to three levels so the kernel compiles at most
        # three shapes ever: small drains stay cheap on the test backend,
        # and extended batches all share the fast_batch_max shape (pad
        # steps are masked inner iterations, ~0.2µs each)
        need = len(batch)
        levels = [64, 512, getattr(self.config, "fast_batch_max", 4096)]
        if getattr(self.config, "resident_drain", False):
            levels.append(self.config.resident_run_max)
        for level in levels:
            if need <= level:
                need = level
                break
        else:
            need = bucket_cap(need, 1)
        p_cap = holder["p_cap"] = max(holder["p_cap"], need)
        ids = np.full((p_cap,), -1, np.int32)
        ids[: len(batch)] = [s.sid for s in pod_sigs]
        w_img = weights[6] if st["any_img"] else 0
        try:
            if holder["dev"] is None:
                # (re)materialize device state from the host committer —
                # one upload per host→device transition, folded into this
                # dispatch's async pipeline
                fc = holder["fc"]
                used_np = np.asarray(fc.used_rows, np.int64)
                nz0_np = np.asarray(fc.nz0, np.int64)
                nz1_np = np.asarray(fc.nz1, np.int64)
                npods_np = np.asarray(fc.num_pods, np.int32)
                holder["alloc"] = jnp.asarray(
                    np.asarray(fc.alloc_rows, np.int64)
                )
                holder["allowed"] = jnp.asarray(
                    np.asarray(fc.allowed, np.int32)
                )
                holder["dev"] = (
                    jnp.asarray(used_np),
                    jnp.asarray(nz0_np),
                    jnp.asarray(nz1_np),
                    jnp.asarray(npods_np),
                )
                # epoch guard: a fresh lineage epoch plus the exact host
                # sum of the uploaded state — each harvest advances the
                # sum by its commits and checks it against the device
                # checksum before trusting a round's results
                holder["epoch"] = holder.get("epoch", 0) + 1
                holder["dev_sum"] = int(
                    int(used_np.sum())
                    + int(nz0_np.sum())
                    + int(nz1_np.sum())
                    + int(npods_np.sum())
                )
            used, nz0, nz1, num_pods = holder["dev"]
            t_dev = time.perf_counter()
            self.phases.add("h2d", t_dev - t_h2d)
            rstats_dev = None
            if res_on:
                # resident drain loop (ops/resident.py): the whole run is
                # placed on device through the speculation/admission fixed
                # point — same donated usage state as sig_scan, one d2h
                # readback of packed placements per run
                from kubernetes_tpu.ops import resident as ops_res

                choices_dev, holder["dev"], rstats_dev = ops_res.resident_run(
                    jnp.asarray(ids),
                    st["req"],
                    st["nz"],
                    st["az"],
                    st["ok"],
                    st["img"],
                    holder["alloc"],
                    holder["allowed"],
                    used,
                    nz0,
                    nz1,
                    num_pods,
                    w_fit=weights[4],
                    w_bal=weights[5],
                    w_img=w_img,
                    check_fit=check_fit,
                    # ktpu: allow(retrace) — alloc's leading axis is the
                    # committer's node count, fixed for the holder's whole
                    # lineage (any node change rebuilds the holder): one
                    # compile per lineage, not one per batch
                    window=min(
                        self.config.resident_window,
                        int(holder["alloc"].shape[0]),
                    ),
                    serial_tail=getattr(
                        self.config, "resident_serial_tail", False
                    ),
                )
            else:
                choices_dev, holder["dev"] = ops_fp.sig_scan(
                    jnp.asarray(ids),
                    st["req"],
                    st["nz"],
                    st["az"],
                    st["ok"],
                    st["img"],
                    holder["alloc"],
                    holder["allowed"],
                    used,
                    nz0,
                    nz1,
                    num_pods,
                    w_fit=weights[4],
                    w_bal=weights[5],
                    w_img=w_img,
                    check_fit=check_fit,
                )
            # epoch guard: the device-side checksum of the NEW state rides
            # the same async pipeline; the harvest validates it against
            # the host-tracked sum BEFORE committing the round
            csum_dev = None
            if getattr(self.config, "resident_epoch_guard", True):
                from kubernetes_tpu.ops import resident as ops_res

                csum_dev = ops_res.usage_checksum(*holder["dev"])
                csum_dev.copy_to_host_async()
            # start the device→host result copy NOW; by harvest time the
            # data is local and the blocking fetch is cheap (the same
            # latency-hiding discipline as the chained gang pipeline)
            choices_dev.copy_to_host_async()
            if rstats_dev is not None:
                rstats_dev.copy_to_host_async()
            holder["dev_inflight"] += 1
            self.phases.add("device", time.perf_counter() - t_dev)
        except Exception as e:
            # a dispatch died mid-round: the donated usage buffers are in
            # an unknown state — but the HOST committer is still the
            # committed truth, so the epoch-guarded resync only drops the
            # device lineage (epoch bump invalidates any unharvested
            # record dispatched against it) and answers this batch on the
            # committer, bit-identically.  No torn usage row can commit:
            # nothing reached the cache from the dead dispatch.
            from kubernetes_tpu.observability import kernels as kernels_mod

            if not isinstance(e, kernels_mod.DispatchFailed):
                logger.exception(
                    "fast-path dispatch failed; resyncing device lineage"
                )
            self._note_dispatch_failure(e)
            holder["dev"] = None
            holder["epoch"] = holder.get("epoch", 0) + 1
            holder["dev_sum"] = None
            self.prom.resident_resyncs.inc(reason="dispatch_failed")
            if holder["dev_inflight"] > 0:
                # unharvested records exist: their harvests re-derive on
                # the committer (epoch mismatch); this batch retries via
                # the caller's flush-and-fallback discipline
                return None
            if holder["heaps_dirty"]:
                holder["fc"].invalidate_heaps()
                holder["heaps_dirty"] = False
            t_dev = time.perf_counter()
            choices = holder["fc"].run(pod_sigs)
            self.phases.add("device", time.perf_counter() - t_dev)
            with self._mu:  # metrics is a registered lock-guarded field
                self.metrics["fast_batches"] += 1
            rec = {
                "kind": "fast",
                "fwk": fwk,
                "state": state,
                "batch": batch,
                "keys": keys,
                "pod_sigs": pod_sigs,
                "choices_host": choices,
                "choices_dev": None,
                "rstats_dev": None,
                "rows": cache,
                "weights": weights,
                "check_fit": check_fit,
                "holder": holder,
                "t0": t0,
                "record_metrics": False,
            }
            self._trace_dispatch("fast", t0, batch, rec)
            return rec
        with self._mu:  # metrics is a registered lock-guarded field
            self.metrics["fast_batches"] += 1
        rec = {
            "kind": "fast",
            "fwk": fwk,
            "state": state,
            "batch": batch,
            "keys": keys,
            "pod_sigs": pod_sigs,
            "choices_host": None,
            "choices_dev": choices_dev,
            "rstats_dev": rstats_dev,
            "csum_dev": csum_dev,
            "epoch": holder["epoch"],
            "rows": cache,
            "weights": weights,
            "check_fit": check_fit,
            "holder": holder,
            "t0": t0,
            "record_metrics": False,
        }
        self._trace_dispatch(
            "resident" if rstats_dev is not None else "fast", t0, batch, rec
        )
        return rec

    def _finish_fast(self, rec) -> List[ScheduleOutcome]:
        """Harvest one fast batch: fetch the kernel's choices (device
        records) or take the host greedy's, advance the host committer, and
        walk the commits (assume → reserve/permit → async bind), diagnosing
        unschedulable pods against the committer state."""
        import numpy as np

        tr = self.tracer
        t_h = tr.now() if tr.enabled else None
        fwk = rec["fwk"]
        state = rec["state"]
        batch = rec["batch"]
        cache = rec["rows"]
        weights = rec["weights"]
        pod_sigs = rec["pod_sigs"]
        holder = rec["holder"]
        outcomes: List[ScheduleOutcome] = []
        from kubernetes_tpu.observability import kernels as kernels_mod

        choices = rec["choices_host"]
        torn = None  # epoch-guard verdict: why the device round was discarded
        if choices is None and rec.get("epoch") is not None and rec[
            "epoch"
        ] != rec["holder"].get("epoch"):
            # the lineage was resynced AFTER this dispatch (a later
            # dispatch died, hbm_oom, mesh degrade): its results ride a
            # dead epoch — discard them un-fetched and re-derive on the
            # host committer, bit-identically
            torn = "epoch_stale"
        if choices is None and torn is None:
            rstats_dev = rec.get("rstats_dev")
            csum_dev = rec.get("csum_dev")
            kern = (
                "resident.resident_run"
                if rstats_dev is not None
                else "fastpath.sig_scan"
            )
            n_fc = holder["fc"].n

            def _validate_choices(fetched):
                ch = np.asarray(fetched[0])[: len(batch)]
                if ((ch < -2) | (ch >= n_fc)).any():
                    return "choice index out of node range"
                return None

            t_d2h = time.perf_counter()
            try:
                fetched = self._d2h_guarded(
                    (rec["choices_dev"], rstats_dev, csum_dev),
                    kernel=kern,
                    validate=_validate_choices,
                )
            except kernels_mod.DispatchFailed as e:
                # unrecoverable readback: treat exactly like a torn round
                self._note_dispatch_failure(e)
                torn = "checksum_mismatch"
            else:
                choices_np = np.asarray(fetched[0])[: len(batch)]
                rstats = (
                    np.asarray(fetched[1]) if rstats_dev is not None else None
                )
                csum = int(fetched[2]) if csum_dev is not None else None
                choices = choices_np.tolist()
            self.phases.add("d2h", time.perf_counter() - t_d2h)
        if torn is not None:
            # epoch-guarded resync: nothing from the dead round reaches
            # the cache or the committer — the host committer (still the
            # committed truth) answers the batch instead
            holder["dev"] = None
            holder["dev_sum"] = None
            holder["dev_inflight"] -= 1
            self.prom.resident_resyncs.inc(reason=torn)
            if holder["heaps_dirty"]:
                holder["fc"].invalidate_heaps()
                holder["heaps_dirty"] = False
            t_res = time.perf_counter()
            choices = holder["fc"].run(pod_sigs)
            self.phases.add("resident_rounds", time.perf_counter() - t_res)
            rec["rstats_dev"] = None  # the path label below reads it
        elif rec["choices_host"] is None:
            holder["dev_inflight"] -= 1
            t_res = time.perf_counter()
            if rstats is not None:
                rounds = int(rstats[0])
                # resident_pods counts what the fixed point RESOLVED; the
                # host-committer tail below covers the rest
                resolved = min(int(rstats[1]), len(batch))
                with self._mu:  # metrics is a registered lock-guarded field
                    self.metrics["resident_batches"] += 1
                    self.metrics["resident_pods"] += resolved
                    self.metrics["resident_rounds"] += rounds
                self.prom.resident_rounds.inc(rounds)
            # advance the host committer to the post-batch state by
            # replaying the kernel's commits — VECTORIZED per-node
            # aggregates (scatter-add over the choices) + one python-int
            # update per TOUCHED node; the old per-pod loop was O(P)
            # interpreter work and dominated resident-run harvests
            fc = holder["fc"]
            rn = fc.rn
            sel = choices_np >= 0
            agg = add0 = add1 = cnt = None
            nodes = None
            if sel.any():
                st_np = holder["stack"]
                sids = np.fromiter(
                    (s.sid for s in pod_sigs), np.int64, len(pod_sigs)
                )[sel]
                nodes = choices_np[sel].astype(np.int64)
                agg = np.zeros((fc.n, rn), np.int64)
                np.add.at(agg, nodes, st_np["req_np"][sids][:, :rn])
                add0 = np.zeros(fc.n, np.int64)
                np.add.at(add0, nodes, st_np["nz_np"][sids, 0])
                add1 = np.zeros(fc.n, np.int64)
                np.add.at(add1, nodes, st_np["nz_np"][sids, 1])
                cnt = np.bincount(nodes, minlength=fc.n)
            # epoch guard: the device state's checksum must equal the
            # host-tracked base sum plus EXACTLY this round's commit
            # delta (identical int arithmetic on both sides) — validated
            # BEFORE anything touches the committer, so a dispatch that
            # died mid-round can never commit torn usage rows.  The base
            # is read at HARVEST time (holder["dev_sum"]): harvests are
            # FIFO, so with two batches in flight the earlier harvest has
            # already folded its delta in by the time the later validates.
            if csum is not None and holder.get("dev_sum") is not None:
                delta = 0
                if agg is not None:
                    delta = int(
                        int(agg.sum())
                        + int(add0.sum())
                        + int(add1.sum())
                        + int(cnt.sum())
                    )
                expected = holder["dev_sum"] + delta
                if csum != expected:
                    # torn state: discard the round, resync the lineage,
                    # and answer on the committer (bit-identical)
                    logger.warning(
                        "resident usage checksum mismatch (device %d != "
                        "expected %d) — resyncing from the host committer",
                        csum,
                        expected,
                    )
                    self.kernels.record_breaker_failure(
                        kern, "poisoned_output"
                    )
                    self.prom.resident_resyncs.inc(
                        reason="checksum_mismatch"
                    )
                    self.prom.wave_fallback.inc(reason="breaker")
                    holder["dev"] = None
                    holder["dev_sum"] = None
                    if holder["heaps_dirty"]:
                        fc.invalidate_heaps()
                        holder["heaps_dirty"] = False
                    choices = fc.run(pod_sigs)
                    choices_np = np.asarray(choices)
                    rstats = None
                    sel = np.zeros(0, bool)  # committer already committed
                    agg = None
                else:
                    holder["dev_sum"] = expected
            if agg is not None:
                used_rows = fc.used_rows
                nz0l, nz1l, npods = fc.nz0, fc.nz1, fc.num_pods
                for n in np.unique(nodes).tolist():
                    row = used_rows[n]
                    arow = agg[n]
                    for r in range(rn):
                        row[r] += int(arow[r])
                    nz0l[n] += int(add0[n])
                    nz1l[n] += int(add1[n])
                    npods[n] += int(cnt[n])
                holder["heaps_dirty"] = True
            unresolved = choices_np == -2  # ops/resident.py UNRESOLVED
            if unresolved.any():
                # host-committer tail: the fixed point handed back its
                # conflict tail (adaptive stop / round cap) — finish it
                # with the exact lazy-heap greedy, which beats serial
                # device steps on host-backed runs.  The device state
                # copy now lags these commits, so it re-materializes
                # from the committer at the next dispatch.
                fc.invalidate_heaps()
                tail_idx = np.nonzero(unresolved)[0]
                tail_choices = fc.run([pod_sigs[i] for i in tail_idx])
                for i, c in zip(tail_idx.tolist(), tail_choices):
                    choices[i] = c
                holder["heaps_dirty"] = False
                holder["dev"] = None
                holder["dev_sum"] = None
            if rstats is not None:
                self.phases.add(
                    "resident_rounds", time.perf_counter() - t_res
                )
            shadow = holder.get("shadow")
            if shadow is not None:
                host_choices = shadow.run(pod_sigs)
                if host_choices != choices:
                    diffs = [
                        (i, h, d)
                        for i, (h, d) in enumerate(zip(host_choices, choices))
                        if h != d
                    ][:10]
                    raise AssertionError(
                        f"sig_scan diverged from host FastCommitter: {diffs}"
                    )
        elif holder.get("shadow") is not None:
            shadow_choices = holder["shadow"].run(pod_sigs)
            if shadow_choices != choices:
                raise AssertionError("host fast path diverged from shadow")
        self.prom.recorder.observe(
            self.prom.gang_dispatch_duration,
            time.perf_counter() - rec["t0"],
            path="resident" if rec.get("rstats_dev") is not None else "fast",
        )

        node_names = self.mirror.nodes.names
        diag_cache: Dict[int, Dict[str, int]] = {}
        node_valid = None
        n_nodes = None
        # The fast gate proved every host filter spec-irrelevant to every
        # batch pod; when Reserve/Permit plugins are exactly those plugins
        # (default registry: volumebinding/DRA), their walks are no-ops —
        # skip them for the whole batch.
        has_rp = (
            fwk.has_reserve_or_permit()
            and not fwk.reserve_permit_covered_by_host_filters()
        )
        lean = fwk.lean_bind_ok()
        # the bulk pass needs neither reserve/permit walks nor per-pod bind
        # plugin dispatch — exactly the lean fast-batch conditions
        bulk_ok = lean and not has_rp
        keys = rec["keys"]
        n = len(batch)
        with self._mu:  # metrics is a registered lock-guarded field
            self.metrics["schedule_attempts"] += n
        t_commit = time.perf_counter()
        i = 0
        while i < n:
            if choices[i] >= 0:
                # commit the whole contiguous run of scheduled pods under
                # ONE lock acquisition (in order — runs preserve the
                # sequential-equivalent commit sequence)
                j = i
                while j < n and choices[j] >= 0:
                    j += 1
                if bulk_ok:
                    self._commit_fast_bulk(
                        fwk, state, batch, choices, i, j, node_names, outcomes
                    )
                else:
                    with self._mu:
                        for k_ in range(i, j):
                            outcomes.append(
                                self._commit_under_lock(
                                    fwk,
                                    state,
                                    batch[k_],
                                    node_names[choices[k_]],
                                    -1,
                                    None,
                                    has_rp,
                                    lean,
                                )
                            )
                i = j
                continue
            qp, sig, k = batch[i], pod_sigs[i], keys[i]
            i += 1
            diag = diag_cache.get(id(sig))
            if diag is None:
                if node_valid is None:
                    node_valid = np.asarray(self.mirror.nodes.valid)
                    n_nodes = len(self.cache.real_nodes())
                diag = holder["fc"].diagnose(sig, cache[k], node_valid)
                diag_cache[id(sig)] = diag
            status = Status.unschedulable(fit_error_message(n_nodes, diag))
            outcomes.append(
                self._post_filter_or_fail(
                    fwk, state, qp, status, 0, diag, set(diag)
                )
            )
        self.phases.add("commit", time.perf_counter() - t_commit)
        if rec["record_metrics"]:
            self._record_batch_metrics(
                fwk.profile_name,
                batch,
                outcomes,
                time.perf_counter() - rec["t0"],
            )
            self._flush_binds()
        if t_h is not None and tr.enabled:
            tr.complete(
                "harvest.resident"
                if rec.get("rstats_dev") is not None
                else "harvest.fast",
                t_h,
                cat="batch",
                bid=rec.get("bid"),
                pods=len(batch),
            )
        return outcomes


    def _try_dispatch_fast(
        self, fwk, batch, outcomes, chain_settled: bool, pipeline_empty: bool = True
    ):
        """Pipelined fast-path dispatch from the scheduling loop: run the
        eligibility gates and PreFilter, dispatch the sig_scan kernel, and
        return a pending record the loop harvests later — the fast-path
        analogue of _try_dispatch_chained's ≤2-in-flight discipline, which
        hides the device link's round-trip latency behind the next batch's
        host work.  Returns the record, "handled" (nothing left), "flush"
        (chain records must settle first — their commits move host state the
        fast rebuild reads), or None (not eligible — direct path)."""
        if self._sampling_active(fwk):
            return None
        if fwk.fit_strategy() != gang.DEFAULT_FIT_STRATEGY:
            return None
        if self.mirror.nodes is None:
            # first batch of a fresh scheduler: pack the mirror now so the
            # very first dispatch already takes the pipelined (and batch-
            # extended) path — otherwise the steady-state batch shape only
            # compiles after warm-up
            with self._mu:
                if self.mirror.nodes is None:
                    self._repack_mirror()
            if self.mirror.nodes is None:  # no nodes yet
                return None
        hf = fwk.host_filter_plugins()
        ns_plugins = self._normalizing_score_plugins(fwk)
        for qp in batch:
            p = qp.pod
            if p.nominated_node_name:
                return None
            if any(pl.maybe_relevant(p) for pl in hf):
                return None
            if any(e.is_interested(p) for e in self.extenders):
                return None
            if any(pl.score_relevant(p) for pl in ns_plugins):
                return None
        if not self._fast_gate_ok(batch):
            return None
        keys = self._batch_signature_keys(batch)
        if keys is None:
            return None
        if not chain_settled:
            return "flush"
        # a lineage rebuild (external events moved the ground truth) must
        # not happen under unharvested records: their commits reach the
        # cache only at harvest, and a rebuild reads the mirror — settle
        # the pipeline first, then rebuild on the retry
        if not pipeline_empty:
            enabled_probe = fwk.device_enabled()
            weights_probe = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
            if getattr(self, "_fastdev", None) is None or self._fc_key != self._fast_key(
                fwk, enabled_probe, weights_probe
            ):
                return "flush"
        # spec-level host-score probe on the SEED batch (extension pods are
        # probed inside the predicate) — the pre-PreFilter equivalent of the
        # sync path's Skip-state check: a pod whose spec is irrelevant Skips
        # in PreScore by the stateful-plugin contract
        for p in fwk.host_score_plugins():
            if fwk.score_weights.get(p.name, 0) and any(
                p.score_relevant(qp.pod) for qp in batch
            ):
                return None

        t_pack = time.perf_counter()
        with self._mu:
            vocab = self.mirror.vocab
            for qp in batch:
                for k, v in qp.pod.labels.items():
                    vocab.intern_label(k, v)
            self._sync_mirror_external()
            enabled = fwk.device_enabled()
            weights = tuple(
                fwk.score_weights.get(n, 0) for n in gang.WEIGHT_ORDER
            )
        # Establish the SEED batch's signature rows (and their argmax-
        # neutrality verdicts) BEFORE extending: every bail-out must happen
        # while the seed group is the only thing popped — extension pods
        # would be lost to the direct-path fallback otherwise.
        rows = self._fast_sig_rows(fwk, batch, keys, enabled, weights)
        self.phases.add("pack", time.perf_counter() - t_pack)
        if rows is None:
            return None

        # Extend the batch from the queue head while pods stay eligible AND
        # their signatures are already established as argmax-neutral: per-
        # pod host cost is flat on the sig_scan path, so one big dispatch
        # amortizes the device round trip over many more pods (queue order
        # — and therefore decision sequence — is unchanged; a pod with a
        # NOVEL signature stops the extension and seeds a later batch).
        # resident runs extend further than plain fast batches: the whole
        # run rides ONE dispatch + ONE d2h readback, so per-run host cost
        # amortizes over far more pods (RESIDENT.md)
        cap = (
            self.config.resident_run_max
            if getattr(self.config, "resident_drain", False)
            else getattr(self.config, "fast_batch_max", 4096)
        )
        ext = cap - len(batch)
        if ext > 0:
            elig = self._fast_pod_predicate(
                fwk, batch[0].pod.scheduler_name, known_rows=rows
            )
            t_pop = time.perf_counter()
            with self._mu:
                extra = self.queue.pop_batch_while(ext, elig)
            self.phases.add("queue_pop", time.perf_counter() - t_pop)
            if extra:
                with self._mu:
                    for qp in extra:
                        for k, v in qp.pod.labels.items():
                            vocab.intern_label(k, v)
                batch = batch + extra
                keys = self._batch_signature_keys(batch)
                assert keys is not None  # predicate guarantees eligibility

        state = CycleState()
        pods_all = [qp.pod for qp in batch]
        t_pack = time.perf_counter()
        # ---- point of commitment: PreFilter mutates outcomes/queue state,
        # so every bail-out above happened first (the direct path must not
        # replay it, and extension pods are already part of this batch);
        # after this, the rare dispatch failure error-requeues the batch
        with self._mu:
            fwk.run_pre_score(state, pods_all, self.mirror.nodes.names)
            pf_failures = self._run_pre_filter_fast(fwk, state, batch, keys)
            if pf_failures:
                live = []
                for qp in batch:
                    s = pf_failures.get(qp.pod.uid)
                    if s is None:
                        live.append(qp)
                        continue
                    self.metrics["schedule_attempts"] += 1
                    outcomes.append(
                        self._post_filter_or_fail(fwk, state, qp, s, 0)
                    )
                batch = live
                if not batch:
                    self.phases.add("pack", time.perf_counter() - t_pack)
                    return "handled"
                keys = self._batch_signature_keys(batch)
        self.phases.add("pack", time.perf_counter() - t_pack)
        # fast commits happen outside the chain's device state — drop it
        # (it restarts from the repacked mirror once the pipeline settles)
        self._chain = None
        rec = self._fast_dispatch(fwk, state, batch, keys, enabled, weights)
        if rec is None:
            # dispatch failure after pods (incl. extension) were popped and
            # PreFilter ran: error-requeue the whole batch with backoff —
            # the retry drains through whatever path is healthy then
            s = Status.error("fast-path device dispatch failed; requeued")
            with self._mu:  # one acquisition for the whole error-requeue
                self.metrics["schedule_attempts"] += len(batch)
                for qp in batch:
                    self._handle_failure(qp, s)
                    outcomes.append(ScheduleOutcome(qp.pod, None, s, 0))
            return "handled"
        rec["record_metrics"] = True
        return rec


    def _run_pre_filter_fast(self, fwk, state, batch, keys):
        """RunPreFilterPlugins for a signature-gated batch, ONE walk per
        distinct signature instead of per pod.

        Pods of one signature share the spec fields every in-tree
        PreFilter reads (pre_filter_spec_pure), and the cluster state a
        fast lineage runs against is frozen between external mutations /
        non-fast commits — both are part of the memo key, so a cached
        verdict can never outlive the state it judged.  Signatures whose
        representative FAILED re-run the real per-pod walk (per-pod Status
        objects + CycleState writes feed the PostFilter/preemption path);
        the hot case — every signature passes — costs one dict hit per pod.
        Falls back to the reference-shaped per-pod walk whenever any
        enabled PreFilter plugin doesn't declare spec purity."""
        if not fwk.pre_filter_spec_pure():
            return fwk.run_pre_filter(state, [qp.pod for qp in batch])
        mkey = (
            self._external_mutations,
            getattr(self, "_nonfast_commits", 0),
            self.mirror._full_packs,
            fwk.profile_name,
        )
        memo = getattr(self, "_pf_memo", None)
        if memo is None or memo[0] != mkey:
            memo = self._pf_memo = (mkey, {})
        verdicts = memo[1]
        failures: Dict[str, Status] = {}
        for k, qp in zip(keys, batch):
            hit = verdicts.get(k, _MISSING)
            if hit is _MISSING:
                s = fwk.run_pre_filter(state, [qp.pod]).get(qp.pod.uid)
                verdicts[k] = s
                if s is not None:
                    failures[qp.pod.uid] = s
            elif hit is not None:
                # known-failing signature: real walk for THIS pod so its
                # Status and per-uid state are its own
                s = fwk.run_pre_filter(state, [qp.pod]).get(qp.pod.uid)
                if s is not None:
                    failures[qp.pod.uid] = s
                else:
                    verdicts[k] = None  # plugin state moved — trust the rerun
        return failures

    def _stack_signatures(self, holder):
        """[S_cap, ...] stacked per-signature tensors for sig_scan; S_cap is
        a pow2 bucket so signature-set growth rarely changes the shape."""
        import numpy as np

        sig_list = self._sig_list
        n = holder["fc"].n
        r = holder["fc"].rn
        s_cap = bucket_cap(len(sig_list), 8)
        req = np.zeros((s_cap, r), np.int64)
        nz = np.zeros((s_cap, 2), np.int64)
        az = np.zeros((s_cap,), bool)
        ok = np.zeros((s_cap, n), bool)
        img = np.zeros((s_cap, n), np.int64)
        any_img = False
        for i, sg in enumerate(sig_list):
            row = np.asarray(sg.req_row, np.int64)
            req[i, : row.shape[0]] = row
            nz[i, 0] = sg.nz0
            nz[i, 1] = sg.nz1
            az[i] = sg.all_zero
            ok[i] = sg.static_ok
            if sg.img is not None:
                img[i] = sg.img
                any_img = True
        return {
            "req": jnp.asarray(req),
            "nz": jnp.asarray(nz),
            "az": jnp.asarray(az),
            "ok": jnp.asarray(ok),
            "img": jnp.asarray(img),
            "any_img": any_img,
            # numpy twins for the harvest-side vectorized committer replay
            "req_np": req,
            "nz_np": nz,
        }

    @staticmethod
    def _wave_shaped_pod(pod) -> bool:
        """Pod carries a cross-pod constraint the wave engine owns (spread
        or inter-pod terms, in-batch host ports) — routing it onto a
        one-pod host path is a fallback-ladder event worth counting in
        scheduler_tpu_wave_fallback_total."""
        if pod.host_ports() or pod.topology_spread_constraints:
            return True
        aff = pod.affinity
        return aff is not None and bool(
            aff.pod_affinity or aff.pod_anti_affinity
        )

    def _schedule_one_nominated(self, fwk, qp) -> List[ScheduleOutcome]:
        """The nominated-node fast path (schedule_one.go:490-499): a pod
        whose preemption already nominated a node evaluates feasibility of
        THAT node only — no scoring, no full dispatch — and binds there when
        it passes.  Falls back to the full one-pod cycle otherwise (the
        reference then runs the normal findNodesThatFitPod).  This is what
        keeps preemption retry rounds off the gang pipeline: by the time
        victims finish terminating, each preemptor costs one host-side
        single-node check instead of a device dispatch."""
        from kubernetes_tpu.oracle.pipeline import feasible_nodes

        pod = qp.pod
        nom = pod.nominated_node_name
        if self._wave_shaped_pod(pod):
            self.prom.wave_fallback.inc(reason="nominated")
        with self._mu:
            state = CycleState()
            self.metrics["schedule_attempts"] += 1
            pf_failures = fwk.run_pre_filter(state, [pod])
            if pf_failures:
                return [
                    self._post_filter_or_fail_locked(
                        fwk, state, qp, pf_failures[pod.uid], 0
                    )
                ]
            allowed = state.read(("pre_filter_result", pod.uid))
            st = self.oracle_view()
            ns = st.nodes.get(nom)
            ok = (
                ns is not None
                and (allowed is None or nom in allowed)
            )
            if ok:
                # RunFilterPluginsWithNominatedPods for the single node:
                # OTHER nominated preemptors of >= priority count as present
                # (runtime/framework.go:973), then a second pass without
                # them (a node feasible only via an unbound nomination may
                # never materialize).
                added = [
                    np_
                    for node, np_ in self.nominator.entries()
                    if node == nom
                    and np_.uid != pod.uid
                    and np_.priority >= pod.priority
                ]
                for np_ in added:
                    ns.add_pod(np_)
                    fwk.run_pre_filter_extension_add_pod(state, pod, np_, ns)
                try:
                    fit = feasible_nodes(
                        pod,
                        st,
                        enabled=fwk.device_enabled(),
                        allowed=frozenset({nom}),
                    )
                    ok = bool(fit.feasible)
                    # FIRST pass runs ALL Filter plugins — host-backed ones
                    # included — with the nominated pods counted as present
                    # (RunFilterPluginsWithNominatedPods, runtime:973): an
                    # occupancy-sensitive host plugin must see them
                    if ok and fwk.has_host_filters():
                        ok = fwk.run_host_filters(state, pod, ns).ok
                finally:
                    for np_ in added:
                        ns.remove_pod(np_)
                        fwk.run_pre_filter_extension_remove_pod(
                            state, pod, np_, ns
                        )
                if ok and added:
                    # second pass on the NEUTRAL state (a node feasible
                    # only via an unbound nomination may never materialize)
                    second = feasible_nodes(
                        pod,
                        st,
                        enabled=fwk.device_enabled(),
                        allowed=frozenset({nom}),
                    )
                    ok = bool(second.feasible)
                    if ok and fwk.has_host_filters():
                        ok = fwk.run_host_filters(state, pod, ns).ok
            if ok:
                for ext in self.extenders:
                    if not ext.is_filter() or not ext.is_interested(pod):
                        continue
                    try:
                        kept, _, _ = ext.filter(pod, [nom])
                    except Exception:  # noqa: BLE001 — ignorable or fallback
                        if getattr(ext, "ignorable", False):
                            continue
                        ok = False
                        break
                    if not kept:
                        ok = False
                        break
            if ok:
                # metrics parity: the attempt was already counted above;
                # _commit returns the success outcome (EvaluatedNodes=1)
                return [self._commit(fwk, state, qp, nom, 1)]
        # Nominated node no longer fits — full evaluation (the attempt
        # counter for the fallback cycle is bumped there, so compensate).
        with self._mu:  # metrics is a registered lock-guarded field
            self.metrics["schedule_attempts"] -= 1
        return self._schedule_one_extender(fwk, qp)

    def _schedule_one_extender(self, fwk, qp) -> List[ScheduleOutcome]:
        """One-pod cycle through the host oracle with the extender chain:
        in-tree Filter → extender Filter (serial, schedule_one.go:701-745)
        → in-tree Score → extender Prioritize (:796-854) → select → commit
        (extender Bind replaces in-tree bind plugins when offered).

        Holds the cache lock for the whole cycle: it reads and temporarily
        patches the SHARED oracle view (nominated-pod add/remove), which
        binding workers patch in place concurrently.  One-pod extender
        cycles are the rare path, so stalling binds behind an extender
        round-trip is acceptable (the reference's extender calls sit on the
        scheduling goroutine too)."""
        pod = qp.pod
        if not pod.nominated_node_name and self._wave_shaped_pod(pod):
            # nominated fall-through already counted its own reason
            reason = (
                "extender"
                if any(e.is_interested(pod) for e in self.extenders)
                else "host_scores"
            )
            self.prom.wave_fallback.inc(reason=reason)
        with self._mu:
            return self._schedule_one_extender_locked(fwk, qp)

    def _schedule_one_extender_locked(self, fwk, qp) -> List[ScheduleOutcome]:
        from kubernetes_tpu.extender import ExtenderError
        from kubernetes_tpu.oracle.pipeline import (
            feasible_nodes,
            prioritize,
            select_host,
        )

        pod = qp.pod
        state = CycleState()
        self.metrics["schedule_attempts"] += 1
        # bit-compat tie-break: one hash index per pod ATTEMPT, consumed up
        # front so early failures keep the sequence aligned with the gang
        # path (which advances by batch length, failures included)
        attempt = getattr(self, "_attempt_counter", 0)
        if self.config.tie_break_seed is not None:
            self._attempt_counter = attempt + 1

        pf_failures = fwk.run_pre_filter(state, [pod])
        if pf_failures:
            return [
                self._post_filter_or_fail(fwk, state, qp, pf_failures[pod.uid], 0)
            ]

        st = self.oracle_view()
        n_nodes = len(st.nodes)
        allowed = state.read(("pre_filter_result", pod.uid))
        # sample sizing happens INSIDE feasible_nodes over the
        # PreFilterResult-narrowed list (schedule_one.go narrows first)
        sample_pct = None
        if self._sampling_active(fwk):
            pct = fwk.percentage_of_nodes_to_score
            if pct is None:
                pct = self.config.percentage_of_nodes_to_score
            if pct > 0 or self.config.reference_sampling_compat:
                sample_pct = pct
        # RunFilterPluginsWithNominatedPods (runtime/framework.go:973):
        # nominated preemptors of >= priority count as present on their
        # nominated node during feasibility; PreFilter extensions keep
        # plugin cycle state in step (interface.go:443-520)
        added = []
        for node, np_ in self.nominator.entries():
            if (
                np_.uid != pod.uid
                and np_.priority >= pod.priority
                and node in st.nodes
            ):
                st.nodes[node].add_pod(np_)
                fwk.run_pre_filter_extension_add_pod(
                    state, pod, np_, st.nodes[node]
                )
                added.append((node, np_))
        try:
            fit = feasible_nodes(
                pod,
                st,
                enabled=fwk.device_enabled(),
                allowed=frozenset(allowed) if allowed is not None else None,
                sample_pct=sample_pct,
                start_index=getattr(self, "_next_start_node_index", 0),
            )
        finally:
            for node, np_ in added:
                st.nodes[node].remove_pod(np_)
                fwk.run_pre_filter_extension_remove_pod(
                    state, pod, np_, st.nodes[node]
                )
        if added and fit.feasible:
            # the reference's SECOND pass (runtime/framework.go:973): a node
            # that only passed BECAUSE of a nominated pod (e.g. required
            # affinity to it) must also pass without — the nomination may
            # never materialize there
            nominated_nodes = {n for n, _ in added}
            recheck = [n for n in fit.feasible if n in nominated_nodes]
            if recheck:
                second = feasible_nodes(
                    pod,
                    st,
                    enabled=fwk.device_enabled(),
                    allowed=frozenset(recheck),
                )
                ok2 = set(second.feasible)
                dropped = [n for n in recheck if n not in ok2]
                fit.feasible = [n for n in fit.feasible if n not in dropped]
                for n in dropped:
                    fit.reasons.setdefault(n, []).append(
                        "node(s) only feasible with unbound nominated pods"
                    )
        if sample_pct is not None:
            # advance the rotation modulo the NARROWED list length, like
            # findNodesThatPassFilters (schedule_one.go:625)
            self._next_start_node_index = (
                getattr(self, "_next_start_node_index", 0) + fit.processed
            ) % max(fit.n_considered, 1)
        feasible = fit.feasible
        diag: Dict[str, int] = {}
        for rs in fit.reasons.values():
            for r in rs:
                diag[r] = diag.get(r, 0) + 1
        plugins: set = set()
        if fwk.has_host_filters():
            kept = []
            for n in feasible:
                s = fwk.run_host_filters(state, pod, st.nodes[n])
                if s.ok:
                    kept.append(n)
                else:
                    reason = s.merge_reason() or s.plugin
                    diag[reason] = diag.get(reason, 0) + 1
                    plugins.add(s.plugin)
            feasible = kept

        for ext in self.extenders:
            if not feasible:
                break
            if not ext.is_filter() or not ext.is_interested(pod):
                continue
            try:
                feasible, failed, unresolvable = ext.filter(pod, feasible)
            except ExtenderError as e:
                if ext.ignorable:
                    continue
                status = Status.error(str(e))
                self._handle_failure(qp, status)
                return [ScheduleOutcome(pod, None, status, 0, diag)]
            for reason_map in (failed, unresolvable):
                for _, reason in reason_map.items():
                    key = reason or f"rejected by extender {ext.name}"
                    diag[key] = diag.get(key, 0) + 1

        if not feasible:
            status = Status.unschedulable(fit_error_message(n_nodes, diag))
            return [
                self._post_filter_or_fail(
                    fwk, state, qp, status, 0, diag, plugins or None
                )
            ]

        fit_inst = fwk.plugin_instance("NodeResourcesFit")
        fit_scorer = (
            (lambda pod_, ns_: fit_inst.score(state, pod_, ns_))
            if fit_inst is not None
            else None
        )
        totals = prioritize(
            pod, st, feasible, weights=fwk.score_weights, fit_scorer=fit_scorer
        )
        # host Score plugins contribute here too (the one-pod analogue of
        # the batched extra_score merge)
        fwk.run_pre_score(state, [pod], feasible)
        if fwk.active_host_scores(state, [pod]):
            node_states = [st.nodes.get(n) for n in feasible]
            for name, scores in fwk.run_host_scores(
                state, pod, node_states
            ).items():
                w = fwk.score_weights.get(name, 0)
                for n, s in zip(feasible, scores):
                    totals[n] = totals.get(n, 0) + s * w
        for ext in self.extenders:
            if not ext.is_prioritizer() or not ext.is_interested(pod):
                continue
            try:
                scores = ext.prioritize(pod, feasible)
            except ExtenderError as e:
                if ext.ignorable:
                    continue
                status = Status.error(str(e))
                self._handle_failure(qp, status)
                return [ScheduleOutcome(pod, None, status, len(feasible), diag)]
            for n, s in scores.items():
                if n in totals:
                    totals[n] += s * ext.weight

        if self.config.tie_break_seed is not None and totals:
            # same seeded-hash rule as the device pipeline (gang tie_key):
            # lexicographic (score, hash) max over the oracle's node order
            if getattr(self, "_tie_key", None) is None:
                self._tie_key = jax.random.PRNGKey(self.config.tie_break_seed)
            k_p = jax.random.fold_in(self._tie_key, attempt)
            import numpy as np

            h = np.asarray(
                self._d2h(jax.random.bits(k_p, (n_nodes,), dtype=jnp.uint32))
            )
            idx_of = {n: i for i, n in enumerate(st.nodes)}
            node = max(totals, key=lambda n: (totals[n], int(h[idx_of[n]])))
        else:
            node = select_host(totals) if totals else feasible[0]
        binder = next(
            (
                e
                for e in self.extenders
                if e.is_binder() and e.is_interested(pod)
            ),
            None,
        )
        binder_override = None
        if binder is not None:

            def binder_override(pod, node_name, _ext=binder):
                try:
                    _ext.bind(pod, node_name)
                    # The extender performed the API write itself — against
                    # a real apiserver a second binding POST would conflict.
                    # Only in-proc stores that opt in (the FakeCluster test
                    # pattern, whose "API" IS the sink) get mirrored.
                    sink_self = getattr(self.binding_sink, "__self__", None)
                    if getattr(self.binding_sink, "mirror_extender_binds", False) or getattr(
                        sink_self, "mirror_extender_binds", False
                    ):
                        self.binding_sink(pod, node_name)
                except ExtenderError as e:
                    return Status.error(str(e))
                return Status.success()

        return [
            self._commit(
                fwk, state, qp, node, len(feasible), binder_override=binder_override
            )
        ]

    def _nominated_arrays(self, exclude_uids):
        """Pack nominations (minus this batch's own pods) into the gang
        dispatch's nom_* arrays."""
        import numpy as np

        from kubernetes_tpu.snapshot.schema import ResourceLanes

        lanes = ResourceLanes(self.mirror.vocab)
        R = self.mirror.nodes.allocatable.shape[1]
        rows = []
        for node, pod in self.nominator.entries():
            if pod.uid in exclude_uids:
                continue
            idx = self.mirror.nodes.name_to_idx.get(node)
            if idx is None:
                continue
            rows.append((idx, pod.priority, lanes.request_row(pod.compute_requests(), R)))
        if not rows:
            return None, None, None
        # Sticky bucketed padding: the nomination count changes every
        # preemption round — exact-size arrays would recompile the gang
        # pipeline per distinct count (~8s each).  Pad rows use node=-1,
        # which matches no node in the kernel's one-hot and contributes 0.
        self._nom_cap_max = max(
            getattr(self, "_nom_cap_max", 1), bucket_cap(len(rows), 1)
        )
        G = self._nom_cap_max
        nom_node = np.full(G, -1, dtype=np.int32)
        nom_prio = np.zeros(G, dtype=np.int32)
        nom_req = np.zeros((G, R), dtype=np.int32)
        for i, (idx, prio, req) in enumerate(rows):
            nom_node[i] = idx
            nom_prio[i] = prio
            nom_req[i] = req
        return jnp.asarray(nom_node), jnp.asarray(nom_prio), jnp.asarray(nom_req)

    def _host_filter_mask(self, fwk, state, pods, p_cap: int, db=None, enabled=None):
        """[p_cap, N] bool: True where host Filter plugins allow the pair
        (the post-device-veto path of runtime:861 for host-backed plugins).

        The walk is NARROWED to nodes surviving the device static filters
        (one static_eval dispatch): statically-dead nodes are rejected by
        the device mask regardless, and the reference's per-node filter
        chain early-exits before host plugins there too — so skipping them
        both matches reason attribution and turns the O(pods × all-nodes)
        plugin-call storm into O(pods × surviving-nodes).

        Also returns per-pod failure detail for Diagnosis fidelity
        (types.go:367): ``diags[i]`` maps reason-string → node count and
        ``plugin_sets[i]`` names the rejecting plugins (drives queueing
        hints)."""
        import numpy as np

        nt = self.mirror.nodes
        n_cap = nt.valid.shape[0]
        mask = np.ones((p_cap, n_cap), dtype=bool)
        st = self.oracle_view()
        node_states = [
            st.nodes.get(nt.names[j]) if j < len(nt.names) else None
            for j in range(n_cap)
        ]
        candidates = None
        if db is not None and len(pods) * n_cap >= 4096:
            try:
                from kubernetes_tpu.ops import fastpath as ops_fp

                res = ops_fp.static_eval(
                    self._static_device_cluster(),
                    db,
                    enabled=enabled
                    if enabled is not None
                    else fwk.device_enabled(),
                    has_images=False,
                )
                candidates = np.asarray(
                    self._d2h(res["mask"], kernel="fastpath.static_eval")
                )
            except Exception:  # noqa: BLE001 — narrowing is best-effort
                candidates = None
        diags: List[Dict[str, int]] = [dict() for _ in pods]
        plugin_sets: List[set] = [set() for _ in pods]
        for i, pod in enumerate(pods):
            # RunFilterPluginsWithNominatedPods (runtime:973) for the host
            # veto pass: nominated preemptors of >= priority count as
            # present on their node, with PreFilter AddPod extensions.
            added = []
            if len(self.nominator):
                for node, np_ in self.nominator.entries():
                    if np_.uid != pod.uid and np_.priority >= pod.priority:
                        ns0 = st.nodes.get(node)
                        if ns0 is not None:
                            ns0.add_pod(np_)
                            fwk.run_pre_filter_extension_add_pod(
                                state, pod, np_, ns0
                            )
                            added.append((ns0, np_))
            try:
                for j, ns in enumerate(node_states):
                    if ns is None or not nt.valid[j]:
                        continue
                    if candidates is not None and not candidates[i, j]:
                        continue  # statically dead — device mask rejects it
                    s = fwk.run_host_filters(state, pod, ns)
                    if not s.ok:
                        mask[i, j] = False
                        reason = s.merge_reason() or s.plugin
                        diags[i][reason] = diags[i].get(reason, 0) + 1
                        if s.plugin:
                            plugin_sets[i].add(s.plugin)
            finally:
                for ns0, np_ in added:
                    ns0.remove_pod(np_)
                    fwk.run_pre_filter_extension_remove_pod(
                        state, pod, np_, ns0
                    )
            if added:
                # the reference's SECOND pass (runtime:973): a node that
                # passed only BECAUSE of an unbound nominated pod must also
                # pass without it — re-check passing nodes that carried
                # nominated adds now that the state is back to neutral
                nom_nodes = {ns0.node.name for ns0, _ in added}
                for j, ns in enumerate(node_states):
                    if (
                        ns is None
                        or not nt.valid[j]
                        or not mask[i, j]
                        or ns.node.name not in nom_nodes
                        or (candidates is not None and not candidates[i, j])
                    ):
                        continue
                    s = fwk.run_host_filters(state, pod, ns)
                    if not s.ok:
                        mask[i, j] = False
                        reason = "node(s) only feasible with unbound nominated pods"
                        diags[i][reason] = diags[i].get(reason, 0) + 1
                        if s.plugin:
                            plugin_sets[i].add(s.plugin)
        return jnp.asarray(mask), diags, plugin_sets

    def _sampling_args(self, fwk):
        """(sample_k, tie_key, attempt_base) device args for the bit-compat
        sampling/tie-break mode, or (None, None, None) when full-width
        first-max (the TPU-native default) applies."""
        from kubernetes_tpu.oracle.pipeline import num_feasible_nodes_to_find

        pct = fwk.percentage_of_nodes_to_score
        if pct is None:
            pct = self.config.percentage_of_nodes_to_score
        sample_k = None
        if pct > 0 or self.config.reference_sampling_compat:
            n_valid = len(self.cache.real_nodes())
            k = num_feasible_nodes_to_find(pct, n_valid)
            # k >= n visits every node, but compat mode still needs the
            # kernel's VISIT-ORDER branch: the reference walks (and
            # first-max tie-breaks) in nodeTree zone-round-robin order even
            # when nothing is cut, so pass k = n rather than disabling
            sample_k = jnp.asarray(min(k, n_valid), I32)
        tie_key = None
        if self.config.tie_break_seed is not None:
            if getattr(self, "_tie_key", None) is None:
                self._tie_key = jax.random.PRNGKey(self.config.tie_break_seed)
            tie_key = self._tie_key
        if sample_k is None and tie_key is None:
            return None, None, None
        return sample_k, tie_key, jnp.asarray(
            getattr(self, "_attempt_counter", 0), I32
        )

    def _sampling_active(self, fwk) -> bool:
        pct = fwk.percentage_of_nodes_to_score
        if pct is None:
            pct = self.config.percentage_of_nodes_to_score
        return (
            pct > 0
            or self.config.reference_sampling_compat
            or self.config.tie_break_seed is not None
        )

    @staticmethod
    def _normalizing_score_plugins(fwk):
        """Enabled host Score plugins that OVERRIDE normalize — their
        scores depend on the feasible set, which only the one-pod oracle
        cycle knows (see the routing in _schedule_batch).  Also includes
        NodeResourcesFit when its scoringStrategy weighs resources beyond
        the device kernel's cpu/memory lanes (device_score=False): its
        score evolves with every in-batch commit, so only the one-pod
        cycle (whose fit_scorer recomputes per attempt) is exact."""
        from kubernetes_tpu.framework.interface import ScorePlugin

        out = [
            p
            for p in fwk.host_score_plugins()
            if fwk.score_weights.get(p.name, 0)
            and type(p).normalize is not ScorePlugin.normalize
        ]
        fit = fwk.plugin_instance("NodeResourcesFit")
        if (
            fit is not None
            and not getattr(fit, "device_score", True)
            and fwk.score_weights.get(fit.name, 0)
        ):
            out.append(fit)
        return out

    def _batched_preemption_narrow(
        self, fwk, state, failed, batch=None, chosen=None, node_names=None
    ) -> None:
        """ONE device dispatch shortlisting preemption candidates for every
        failed pod of a batch (ops/preemption.narrow_candidates — the
        batched front of DryRunPreemption, preemption.go:548).  Shortlists
        land in the CycleState under ("preemption_potential", uid);
        DefaultPreemption passes them into the evaluator.  Best-effort: on
        any precondition failure the evaluator's host walk runs unassisted.

        ``batch``/``chosen``/``node_names`` hand over the dispatch's OWN
        committed placements — the admission scan's carried state, which
        the cache cannot show yet (commits happen in the result walk after
        this) — so victim evaluation reuses them instead of re-deriving
        peer state: strictly-higher-priority peers charge the kept plane,
        lower ones count as removable victims (ops/preemption.py
        docstring).  ``node_names`` is the DISPATCH-TIME packing's name
        list: the mirror.update() below may full-repack and compact node
        slots, so peers resolve name→current-index like the victim rows
        do, never by raw dispatch index."""
        import numpy as np

        from kubernetes_tpu.ops import preemption as ops_preemption
        from kubernetes_tpu.snapshot.schema import ResourceLanes

        with self._mu:
            if self.mirror.nodes is None or not failed:
                return
            try:
                vocab = self.mirror.vocab
                self.mirror.update(self.cache, self.namespace_labels)
                nt = self.mirror.nodes
                dc = self._static_device_cluster()
                pods = [qp.pod for qp in failed]
                # sticky bucket: retry rounds with shrinking failure sets
                # must not each compile a new narrow shape
                self._p_cap_max = max(
                    self._p_cap_max, self._p_bucket(len(pods))
                )
                pb = pack_pod_batch(
                    pods,
                    vocab,
                    k_cap=nt.k_cap,
                    p_cap=self._p_cap_max,
                    namespace_labels=self.namespace_labels,
                )
                placed = self.cache.placed_pods()
                lanes = ResourceLanes(vocab)
                R = nt.allocatable.shape[1]
                # sticky: the placed-pod count SHRINKS as victims are
                # evicted — tracking the running max avoids one recompile
                # per crossed bucket boundary on the way down
                self._vic_cap_max = max(
                    getattr(self, "_vic_cap_max", 1),
                    bucket_cap(max(len(placed), 1)),
                )
                E = self._vic_cap_max
                vnode = np.full(E, -1, np.int32)
                vprio = np.zeros(E, np.int32)
                vreq = np.zeros((E, R), np.int32)
                for i, p in enumerate(placed):
                    idx = nt.name_to_idx.get(p.node_name)
                    if idx is None:
                        continue
                    vnode[i] = idx
                    vprio[i] = p.priority
                    vreq[i] = lanes.request_row(p.compute_requests(), R)
                distinct = sorted({p.priority for p in pods})
                G = bucket_cap(len(distinct), 1)
                groups = np.full(G, np.iinfo(np.int32).min, np.int32)
                groups[: len(distinct)] = distinct
                gidx = {pr: i for i, pr in enumerate(distinct)}
                pod_group = np.zeros(pb.valid.shape[0], np.int32)
                for i, p in enumerate(pods):
                    pod_group[i] = gidx[p.priority]
                tree = {
                    "vnode": vnode,
                    "vprio": vprio,
                    "vreq": vreq,
                    "groups": groups,
                    "pg": pod_group,
                }
                if (
                    batch is not None
                    and chosen is not None
                    and node_names is not None
                ):
                    # this dispatch's committed peers (sticky bucket like
                    # the victim plane — retry rounds must not recompile)
                    self._bpeer_cap_max = max(
                        getattr(self, "_bpeer_cap_max", 1),
                        bucket_cap(max(len(batch), 1), 1),
                    )
                    B2 = self._bpeer_cap_max
                    bnode = np.full(B2, -1, np.int32)
                    bprio = np.zeros(B2, np.int32)
                    breq = np.zeros((B2, R), np.int32)
                    for i, qp in enumerate(batch):
                        c = int(chosen[i])
                        if c < 0 or c >= len(node_names):
                            continue
                        # dispatch index → name → CURRENT slot (the
                        # repack above may have moved it)
                        idx = nt.name_to_idx.get(node_names[c])
                        if idx is None:
                            continue
                        bnode[i] = idx
                        bprio[i] = qp.pod.priority
                        breq[i] = lanes.request_row(
                            qp.pod.compute_requests(), R
                        )
                    tree.update(bnode=bnode, bprio=bprio, breq=breq)
                from kubernetes_tpu.ops import wire

                # device-fault tier: narrowing is an optimization — an
                # open breaker (or the best-effort except below, for an
                # abandoned dispatch) leaves the FULL candidate set, which
                # is superset-sound by construction
                if self._breaker_blocked("preemption.narrow_candidates"):
                    return
                t = wire.device_put_packed(tree)
                masks_dev = ops_preemption.narrow_candidates(
                    dc,
                    self._place_db(DeviceBatch.from_host(pb)),
                    t["vnode"],
                    t["vprio"],
                    t["vreq"],
                    t["groups"],
                    t["pg"],
                    batch_node=t.get("bnode"),
                    batch_prio=t.get("bprio"),
                    batch_req=t.get("breq"),
                )
                masks = np.asarray(
                    self._d2h(
                        masks_dev, kernel="preemption.narrow_candidates"
                    )
                )
                names = nt.names
                for i, qp in enumerate(failed):
                    short = {
                        names[j]
                        for j in np.nonzero(masks[i])[0]
                        if j < len(names)
                    }
                    state.write(("preemption_potential", qp.pod.uid), short)
            except Exception:  # noqa: BLE001 — narrowing is best-effort
                return

    def _host_score_matrix(self, fwk, state, pods, p_cap: int):
        """[p_cap, N] i64: Σ weight·normalized host-plugin scores per
        (pod, node) — merged additively into the device total before the
        argmax (RunScorePlugins runtime/framework.go:1101-1207 for plugins
        without kernels).  NormalizeScore runs over the valid node set; a
        kernel-less plugin whose normalize depends on the *dynamic* feasible
        set is not representable here (none in-tree does)."""
        import numpy as np

        nt = self.mirror.nodes
        n_cap = nt.valid.shape[0]
        total = np.zeros((p_cap, n_cap), dtype=np.int64)
        st = self.oracle_view()
        node_states = [
            st.nodes.get(nt.names[j]) if j < len(nt.names) and nt.valid[j] else None
            for j in range(n_cap)
        ]
        relevant = {p.name: p for p in fwk.active_host_scores(state, pods)}
        for i, pod in enumerate(pods):
            if not any(
                p.score_relevant(pod)
                and not state.is_score_skipped(pod.uid, p.name)
                for p in relevant.values()
            ):
                continue
            per_plugin = fwk.run_host_scores(state, pod, node_states)
            for name, scores in per_plugin.items():
                w = fwk.score_weights.get(name, 0)
                if not w:
                    continue
                total[i] += np.asarray(scores, dtype=np.int64) * w
        return jnp.asarray(total)

    def _post_filter_or_fail(
        self,
        fwk,
        state,
        qp,
        status: Status,
        n_feas: int,
        diagnosis: Optional[Dict[str, int]] = None,
        plugins: Optional[set] = None,
    ) -> ScheduleOutcome:
        """Route a filter failure into PostFilter (preemption) when the
        profile has one (schedule_one.go:135-180).  Holds the cache lock:
        the preemption dry-run reads (and temporarily patches) the SHARED
        oracle view, which binding workers now patch in place on
        forget/assume — unsynchronized interleaving would corrupt it."""
        with self._mu:
            return self._post_filter_or_fail_locked(
                fwk, state, qp, status, n_feas, diagnosis, plugins
            )

    def _post_filter_or_fail_locked(
        self,
        fwk,
        state,
        qp,
        status: Status,
        n_feas: int,
        diagnosis: Optional[Dict[str, int]] = None,
        plugins: Optional[set] = None,
    ) -> ScheduleOutcome:
        pod = qp.pod
        fr = self.flight
        if fr.enabled and status.code in (
            Code.UNSCHEDULABLE,
            Code.UNSCHEDULABLE_AND_UNRESOLVABLE,
        ):
            # the diagnosis counts the kernels already fetched ride along
            # for free — /debug/explain is the full per-node drill-down
            fr.record(
                pod.uid,
                "unschedulable",
                {
                    "plugins": sorted(plugins) if plugins else (
                        [status.plugin] if status.plugin else []
                    ),
                    "diagnosis": diagnosis,
                    "reasons": list(status.reasons)[:3],
                },
            )
        if fwk.has_post_filter() and status.code == Code.UNSCHEDULABLE:
            nominated, pf_status = fwk.run_post_filter(state, pod, None)
            if nominated:
                pod.nominated_node_name = nominated
                self.nominator.add(pod, nominated)
                self.status_patcher(pod)  # schedule_one.go:1117 PatchPodStatus
                if fr.enabled:
                    fr.record(pod.uid, "nominated", {"node": nominated})
            elif nominated == "" and pod.nominated_node_name:
                pod.nominated_node_name = ""
                self.nominator.delete(pod)
                self.status_patcher(pod)
        elif (
            status.code == Code.UNSCHEDULABLE_AND_UNRESOLVABLE
            and pod.nominated_node_name
        ):
            # Preemption can't resolve this class of failure — clear the
            # stale nomination so it stops reserving capacity.
            pod.nominated_node_name = ""
            self.nominator.delete(pod)
            self.status_patcher(pod)
        self._handle_failure(qp, status, plugins)
        return ScheduleOutcome(pod, None, status, n_feas, diagnosis)

    def _commit(
        self,
        fwk,
        state,
        qp,
        node_name: str,
        n_feas: int,
        binder_override=None,
        from_fast: bool = False,
    ) -> ScheduleOutcome:
        """The scheduling-cycle tail: assume → reserve → permit, then hand
        the pod to an async binding worker (schedule_one.go:117-129 — the
        goroutine-per-pod bindingCycle).  The returned outcome is
        provisional; a bind failure patches it to unschedulable before
        schedule_pending returns (its end-of-drain barrier).
        ``binder_override`` replaces the in-tree bind plugins when a binder
        extender claims the pod (schedule_one.go extendersBinding)."""
        has_rp = fwk.has_reserve_or_permit()
        with self._mu:
            if not from_fast:
                # scan/extender-path commits advance cache state the fast
                # committer didn't see — its cache key must change
                self._nonfast_commits = getattr(self, "_nonfast_commits", 0) + 1
            return self._commit_under_lock(
                fwk, state, qp, node_name, n_feas, binder_override, has_rp
            )

    def _commit_under_lock(
        self,
        fwk,
        state,
        qp,
        node_name,
        n_feas,
        binder_override,
        has_rp,
        lean: bool = False,
    ) -> ScheduleOutcome:
        """The _commit body with self._mu already held — lets the fast
        harvest commit a whole run of pods under ONE lock acquisition."""
        from kubernetes_tpu.cache.cache import CacheError

        if self._sanitize:
            sanitizer.assert_owned(self._mu, "_commit_under_lock")
        pod = qp.pod
        try:
            self.cache.assume_pod(pod, node_name)
        except CacheError as e:
            # the pod was assumed/added concurrently (an external binding
            # raced our decision — the multi-scheduler window): fail THIS
            # pod and let the event stream settle it; the drain continues
            s = Status.error(f"assume failed: {e}")
            self._handle_failure(qp, s)
            return ScheduleOutcome(pod, None, s, n_feas)
        ps = self.cache.pod_states.get(pod.uid)
        assumed = ps.pod if ps is not None else pod
        self._view_pod_added(assumed)

        waited = False
        if has_rp:
            s = fwk.run_reserve(state, pod, node_name)
            if not s.ok:
                self._external_mutations += 1  # committer state diverges
                self._view_pod_removed(assumed)
                self.cache.forget_pod(pod)
                if self.flight.enabled:
                    self.flight.record(
                        pod.uid,
                        "verdict",
                        {"ext": "Reserve", "plugin": s.plugin, "node": node_name},
                    )
                self._handle_failure(qp, s)
                return ScheduleOutcome(pod, None, s, n_feas)

            s = fwk.run_permit(state, pod, node_name)
            if s.rejected or s.code == Code.ERROR:
                fwk.run_unreserve(state, pod, node_name)
                self._external_mutations += 1  # committer state diverges
                self._view_pod_removed(assumed)
                self.cache.forget_pod(pod)
                if self.flight.enabled:
                    self.flight.record(
                        pod.uid,
                        "verdict",
                        {"ext": "Permit", "plugin": s.plugin, "node": node_name},
                    )
                self._handle_failure(qp, s)
                return ScheduleOutcome(pod, None, s, n_feas)
            waited = s.code == Code.WAIT

        if self.flight.enabled:
            self.flight.record(
                pod.uid, "assumed", {"node": node_name, "waited": waited}
            )
        outcome = ScheduleOutcome(
            pod,
            node_name,
            Status.success(),
            n_feas,
            pod_attempts=qp.attempts,
            first_enqueue_time=qp.timestamp,
            first_enqueue_mono=qp.mono_timestamp or None,
        )
        task = _BindTask(
            fwk, state, qp, node_name, waited, binder_override, outcome, lean
        )
        if waited:
            # A Wait-ed pod's cycle can block on permit for its timeout —
            # it must not serialize behind (or ahead of) other pods' binds;
            # it gets a dedicated worker like the reference's goroutine.
            self._ensure_bind_pool()
            self._inflight_binds.append(
                self._bind_pool.submit(self._binding_cycle, task)
            )
        else:
            # Common case: buffer and submit in chunks at batch end — one
            # future per ~64 pods instead of per pod (submit + wakeup
            # overhead dominates when the bind sink is an in-proc store).
            self._bind_buffer.append(task)
        return outcome

    def _commit_fast_bulk(
        self,
        fwk,
        state,
        batch,
        choices,
        i,
        j,
        node_names,
        outcomes,
        idxs=None,
        n_feas=None,
        nonfast: bool = False,
    ) -> None:
        """Commit batch[i:j] — a contiguous run of fast-scheduled, lean
        pods — as ONE vectorized pass: bulk assume into the cache (per-node
        aggregated accounting), shared success Status, and a single bulk
        binding task instead of per-pod _BindTasks.  Decisions are
        untouched (they were made by the kernel/committer); this collapses
        the per-pod Python of the commit tail, which the config0 phase
        breakdown showed dominating the drain.  Falls back per pod
        (_commit_under_lock) whenever reserve/permit could act or a
        non-default binder is configured — see _finish_fast's bulk_ok.

        ``idxs`` replaces the [i:j) slice with an explicit index list (the
        wave path's per-interaction-group runs); ``n_feas`` supplies
        per-pod feasible counts for the outcomes (-1 otherwise);
        ``nonfast`` marks commits the fast committer didn't make, bumping
        the mirror-sync epoch the way per-pod _commit does."""
        if idxs is None:
            idxs = range(i, j)
        run = [batch[k] for k in idxs]
        names = [node_names[choices[k]] for k in idxs]
        feas = (
            [-1] * len(run)
            if n_feas is None
            else [int(n_feas[k]) for k in idxs]
        )
        # Seed the per-pod request memos from a representative keyed by RAW
        # spec identity (fastpath.spec_key — the exact request strings)
        # before the cache accounting reads them: template-stamped pods
        # share one quantity parse, and the memoized Resources are
        # read-only by contract.  Keying by Signature would be wrong here:
        # signature rows QUANTIZE (ceil-MiB memory lanes), so byte-
        # different pods can share a Signature, and stamping them with the
        # representative's Resources would charge the cache the wrong
        # values for the placement's whole lifetime.
        from kubernetes_tpu import fastpath as fp

        req_by_spec: Dict[object, tuple] = {}
        for qp_ in run:
            pod = qp_.pod
            d = pod.__dict__
            if "_nzreq_memo" in d:
                continue
            sk = fp.spec_key_memo(pod)
            rep = req_by_spec.get(sk) if sk is not None else None
            if rep is None:
                rep = (pod.compute_requests(), pod.non_zero_requests())
                if sk is not None:
                    req_by_spec[sk] = rep
            else:
                d["_req_memo"], d["_nzreq_memo"] = rep
        # one Status shared by the whole run: success statuses are treated
        # as immutable everywhere (failure paths REPLACE outcome.status)
        success = STATUS_SUCCESS
        items = []
        with self._mu:
            if self._sanitize:
                sanitizer.assert_owned(self._mu, "_commit_fast_bulk")
            if nonfast:
                # scan/wave-path commits advance cache state the fast
                # committer didn't see — its cache key must change
                self._nonfast_commits = (
                    getattr(self, "_nonfast_commits", 0) + len(run)
                )
            results = self.cache.assume_pods_bulk(
                list(zip((qp.pod for qp in run), names))
            )
            view_live = self._oracle_cache is not None
            fr = self.flight
            fr_on = fr.enabled
            fr_events = [] if fr_on else None
            for qp, nn, nf, res in zip(run, names, feas, results):
                if isinstance(res, str):
                    # protocol violation (double assume — the multi-
                    # scheduler race): fail the pod AND rebuild the fast
                    # lineage, whose committer already charged this
                    # placement the cache just rejected
                    self._external_mutations += 1
                    s = Status.error(f"assume failed: {res}")
                    self._handle_failure(qp, s)
                    outcomes.append(ScheduleOutcome(qp.pod, None, s, -1))
                    continue
                if view_live:
                    self._view_pod_added(res)
                if fr_on:
                    fr_events.append((qp.pod.uid, "assumed", {"node": nn}))
                outcome = ScheduleOutcome(
                    qp.pod,
                    nn,
                    success,
                    nf,
                    pod_attempts=qp.attempts,
                    first_enqueue_time=qp.timestamp,
                    first_enqueue_mono=qp.mono_timestamp or None,
                )
                outcomes.append(outcome)
                items.append((qp, nn, outcome))
        if fr_events:
            fr.record_many(fr_events)
        if items:
            self._bulk_bind_buffer.append(_BulkBindTask(fwk, state, items))

    def _ensure_bind_pool(self) -> None:
        if self._bind_pool is None:
            self._bind_pool = ThreadPoolExecutor(
                max_workers=max(self.config.parallelism, 1),
                thread_name_prefix="binding-cycle",
            )

    def _flush_binds(self, chunk: int = 64) -> None:
        """Submit buffered binding cycles, chunked — called at batch end so
        bindings still overlap the NEXT batch's device dispatch.  The chunk
        shrinks when the buffer is small relative to the worker pool so a
        single (possibly extended) batch still spreads its binds across all
        workers — one future per ~64 pods is only the ceiling.  Bulk tasks
        (fast-path runs) split into per-worker slices the same way, but
        keep their one-sink-write/one-lock-tail discipline per slice."""
        bulk = self._bulk_bind_buffer
        if bulk:
            self._bulk_bind_buffer = []
            self._ensure_bind_pool()
            workers = max(self.config.parallelism, 1)
            sink_many = self.binding_sink_many is not None
            for t in bulk:
                n = len(t.items)
                if sink_many:
                    # one bulk write + one lock tail per slice: big slices,
                    # or worker threads just fight the GIL with the
                    # scheduling loop over a few dict ops each
                    per = max(1024, -(-n // workers))
                else:
                    # per-pod sink calls may block on I/O (the reference's
                    # binding goroutine shape): small slices spread them
                    # across the pool so latencies overlap
                    per = min(64, max(1, -(-n // workers)))
                for lo in range(0, n, per):
                    part = _BulkBindTask(t.fwk, t.state, t.items[lo : lo + per])
                    self._inflight_binds.append(
                        self._bind_pool.submit(self._binding_bulk, part)
                    )
        buf = self._bind_buffer
        if not buf:
            return
        chunk = min(chunk, max(1, -(-len(buf) // max(self.config.parallelism, 1))))
        self._bind_buffer = []
        self._ensure_bind_pool()
        for i in range(0, len(buf), chunk):
            part = buf[i : i + chunk]
            self._inflight_binds.append(
                self._bind_pool.submit(self._binding_chunk, part)
            )

    def _binding_bulk(self, t: "_BulkBindTask") -> None:
        """One worker's slice of a bulk fast-path binding run.

        The per-pod walk collapses by construction: the fast gate proved
        PreBind irrelevant and DefaultBinder is the only Bind plugin
        (lean), and no Reserve/Permit plugin can act — so the cycle is
        exactly one sink write per pod (or ONE bulk write for the slice
        when the API tier installed binding_sink_many) plus the post-bind
        bookkeeping, settled under a single lock acquisition.  Failures
        unwind per pod through the standard _bind_fail path."""
        from kubernetes_tpu import events as ev

        t0 = time.perf_counter()
        fwk, state, items = t.fwk, t.state, t.items
        fr = self.flight
        if fr.enabled:
            # worker picked the slice up: closes the commit stage (assumed
            # → bind_start) in the SLO tier's attribution join
            fr.record_many(
                (qp.pod.uid, "bind_start", None) for qp, _, _ in items
            )
        ok_items = []
        sink_many = self.binding_sink_many
        if sink_many is not None and len(items) > 1:
            try:
                errs = sink_many([(qp.pod, nn) for qp, nn, _ in items])
            except Exception as e:  # noqa: BLE001 — whole-slice failure
                errs = [str(e)] * len(items)
            if not isinstance(errs, (list, tuple)) or len(errs) != len(items):
                # a misaligned result list would silently drop pods from
                # the zip below, leaking them as assumed-forever — treat
                # it as a whole-slice failure instead
                errs = ["bulk binding sink returned misaligned results"] * len(
                    items
                )
            for (qp, nn, outcome), err in zip(items, errs):
                if err is None:
                    ok_items.append((qp, nn, outcome))
                else:
                    self._bind_fail(fwk, state, qp, nn, outcome, Status.error(err))
        else:
            sink = self.binding_sink
            for qp, nn, outcome in items:
                try:
                    sink(qp.pod, nn)
                except Exception as e:  # noqa: BLE001 — surfaced as Status
                    self._bind_fail(
                        fwk, state, qp, nn, outcome,
                        Status.error(f"binding cycle panicked: {e}"),
                    )
                    continue
                ok_items.append((qp, nn, outcome))
        if ok_items:
            with self._mu:
                queue_done = self.queue.done
                finish = self.cache.finish_binding
                nom = self.nominator if len(self.nominator) else None
                for qp, _, _ in ok_items:
                    pod = qp.pod
                    queue_done(pod.uid)
                    finish(pod)
                    if nom is not None:
                        nom.delete(pod)
                self.metrics["scheduled"] += len(ok_items)
            fr = self.flight
            if fr.enabled:
                fr.record_many(
                    (qp.pod.uid, "bound", {"node": nn})
                    for qp, nn, _ in ok_items
                )
            if fwk.has_post_bind():
                for qp, nn, _ in ok_items:
                    fwk.run_post_bind(state, qp.pod, nn)
            rec = self.recorders.get(ok_items[0][0].pod.scheduler_name)
            if rec is not None and not isinstance(rec, ev.NullRecorder):
                for qp, nn, _ in ok_items:
                    pod = qp.pod
                    rec.eventf(
                        ev.ObjectRef.for_pod(pod),
                        ev.TYPE_NORMAL,
                        "Scheduled",
                        "Binding",
                        f"Successfully assigned {pod.key} to {nn}",
                    )
        dt = time.perf_counter() - t0
        if items:
            # amortized binding latency: the slice shares one wall clock
            self.prom.binding_duration.observe_n(dt / len(items), len(items))
        self.phases.add("bind", dt)

    def _binding_chunk(self, part: List["_BindTask"]) -> None:
        """One worker's buffered binding cycles.  Lean cycles (fast batches
        with the default binder only) run their sink calls first and then
        settle ALL their post-bind tails (queue.done / finish_binding /
        nominator) under ONE lock acquisition — the tail work is pure
        bookkeeping, so batching it shrinks per-pod lock traffic without
        changing what any concurrent reader can observe mid-chunk."""
        from kubernetes_tpu import events as ev

        t_bind = time.perf_counter()
        lean_ok = []
        lean_tasks = [t for t in part if t.lean_eligible()]
        fr = self.flight
        if fr.enabled and lean_tasks:
            # lean tasks bind inline below; non-lean ones route through
            # _binding_cycle, which records its own bind_start
            fr.record_many(
                (t.qp.pod.uid, "bind_start", None) for t in lean_tasks
            )
        sink_many = getattr(self, "binding_sink_many", None)
        if sink_many is not None and len(lean_tasks) > 1:
            # BULK sink (the API tier's /bindings endpoint): the whole
            # chunk's bindings ride one write; per-item errors unwind
            # exactly the pods that failed
            try:
                errs = sink_many([(t.qp.pod, t.node_name) for t in lean_tasks])
            except Exception as e:  # noqa: BLE001 — whole-batch failure
                errs = [str(e)] * len(lean_tasks)
            if not isinstance(errs, (list, tuple)) or len(errs) != len(
                lean_tasks
            ):
                # misaligned results would drop tasks from the zip —
                # whole-batch failure keeps every pod accounted for
                errs = ["bulk binding sink returned misaligned results"] * len(
                    lean_tasks
                )
            for t, err in zip(lean_tasks, errs):
                if err is None:
                    lean_ok.append(t)
                else:
                    self._bind_fail(
                        t.fwk, t.state, t.qp, t.node_name, t.outcome,
                        Status.error(err),
                    )
            lean_handled = set(map(id, lean_tasks))
        else:
            lean_handled = set()
        for t in part:
            if id(t) in lean_handled:
                continue
            if t.lean_eligible():
                try:
                    s = t.fwk.run_bind_direct(t.state, t.qp.pod, t.node_name)
                except Exception as e:  # noqa: BLE001 — surfaced as Status
                    s = Status.error(f"binding cycle panicked: {e}")
                if s.ok:
                    lean_ok.append(t)
                else:
                    self._bind_fail(t.fwk, t.state, t.qp, t.node_name, t.outcome, s)
            else:
                self._binding_cycle(t)
        if not lean_ok:
            self.phases.add("bind", time.perf_counter() - t_bind)
            return
        with self._mu:
            for t in lean_ok:
                pod = t.qp.pod
                self.queue.done(pod.uid)
                self.cache.finish_binding(pod)
                self.nominator.delete(pod)
            self.metrics["scheduled"] += len(lean_ok)
        fr = self.flight
        if fr.enabled:
            fr.record_many(
                (t.qp.pod.uid, "bound", {"node": t.node_name})
                for t in lean_ok
            )
        for t in lean_ok:
            pod = t.qp.pod
            t.fwk.run_post_bind(t.state, pod, t.node_name)
            rec = self.recorders.get(pod.scheduler_name)
            if rec is not None and not isinstance(rec, ev.NullRecorder):
                rec.eventf(
                    ev.ObjectRef.for_pod(pod),
                    ev.TYPE_NORMAL,
                    "Scheduled",
                    "Binding",
                    f"Successfully assigned {pod.key} to {t.node_name}",
                )
        self.phases.add("bind", time.perf_counter() - t_bind)

    def _bind_fail(self, fwk, state, qp, node_name, outcome, s) -> None:
        """Bind-failure unwind: Unreserve + ForgetPod + requeue under the
        cache lock (schedule_one.go:342-374), outcome patched in place."""
        pod = qp.pod
        if self.flight.enabled:
            self.flight.record(
                pod.uid,
                "bind_failed",
                {"node": node_name, "reasons": list(s.reasons)[:3]},
            )
        with self._mu:
            # The in-flight ledger is still intact here, so events that
            # arrived during the attempt replay through add_unschedulable.
            fwk.run_unreserve(state, pod, node_name)
            self._external_mutations += 1  # committer state diverges
            ps = self.cache.pod_states.get(pod.uid)
            if ps is not None:
                self._view_pod_removed(ps.pod)
            self.cache.forget_pod(pod)
            self.gangs.note_removed(pod)  # quorum bookkeeping unwinds too
            self._handle_failure(qp, s)
        outcome.node = None
        outcome.status = s

    def _binding_cycle(self, t: "_BindTask") -> None:
        """WaitOnPermit → PreBind → Bind → PostBind on a worker thread
        (schedule_one.go:263-340); failure unwinds via Unreserve + ForgetPod
        + requeue under the cache lock (:342-374).  A lean task (fast
        batches whose gate proved PreBind irrelevant and whose only binder
        is the default) collapses the walk to the direct sink call."""
        fwk, state, qp, node_name = t.fwk, t.state, t.qp, t.node_name
        waited, binder_override, outcome = t.waited, t.binder_override, t.outcome
        pod = qp.pod
        if self.flight.enabled:
            self.flight.record(pod.uid, "bind_start", None)
        try:
            if t.lean_eligible():
                s = fwk.run_bind_direct(state, pod, node_name)
            else:
                s = fwk.wait_on_permit(pod) if waited else Status.success()
                if s.ok:
                    s = fwk.run_pre_bind(state, pod, node_name)
                if s.ok:
                    if binder_override is not None:
                        s = binder_override(pod, node_name)
                    else:
                        s = fwk.run_bind(state, pod, node_name)
        except Exception as e:  # noqa: BLE001 — surfaced as Status
            s = Status.error(f"binding cycle panicked: {e}")
        if not s.ok:
            self._bind_fail(fwk, state, qp, node_name, outcome, s)
            return
        with self._mu:
            self.queue.done(pod.uid)
            self.cache.finish_binding(pod)
            self.nominator.delete(pod)
            self.metrics["scheduled"] += 1
        if self.flight.enabled:
            self.flight.record(pod.uid, "bound", {"node": node_name})
        fwk.run_post_bind(state, pod, node_name)
        from kubernetes_tpu import events as ev

        self.recorders.get(pod.scheduler_name, ev.NullRecorder()).eventf(
            ev.ObjectRef.for_pod(pod),
            ev.TYPE_NORMAL,
            "Scheduled",
            "Binding",
            f"Successfully assigned {pod.key} to {node_name}",
        )

    def wait_for_bindings(self) -> None:
        """Barrier: block until every in-flight binding cycle completed and
        its outcome is final (the analogue of draining the reference's
        binding goroutines)."""
        self._flush_binds()
        while self._inflight_binds:
            futs, self._inflight_binds = self._inflight_binds, []
            for f in futs:
                f.result()

    def _handle_failure(self, qp, status: Status, plugins: Optional[set] = None) -> None:
        """handleSchedulingFailure (schedule_one.go:1020).  ``plugins`` is
        the rejecting-plugin set driving queueing-hint requeue; it defaults
        to the status's single plugin.  Takes the cache lock itself: called
        from both the scheduling loop and binding workers."""
        with self._mu:
            if status.code == Code.ERROR:
                self.metrics["errors"] += 1
                # Errors (API failures etc.) carry no rejector plugin —
                # the queue retries them after plain backoff instead of
                # waiting for a queueing hint (scheduling_queue.go:642).
                plugins = set()
            else:
                self.metrics["unschedulable"] += 1
            if plugins is None:
                plugins = {status.plugin} if status.plugin else set()
            self.queue.add_unschedulable(qp, plugins)
        from kubernetes_tpu import events as ev

        pod = qp.pod
        self.recorders.get(pod.scheduler_name, ev.NullRecorder()).eventf(
            ev.ObjectRef.for_pod(pod),
            ev.TYPE_WARNING,
            "FailedScheduling",
            "Scheduling",
            "; ".join(status.reasons) or "scheduling failed",
        )
