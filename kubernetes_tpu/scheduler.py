"""The scheduler: cache + queue + device pipeline + binding, wired.

The batched counterpart of pkg/scheduler/scheduler.go + schedule_one.go:
``Scheduler.schedule_pending()`` pops a whole batch in queue order, brings
the device mirror up to date (incremental, generation-gated), runs ONE
fused gang dispatch (sequential-equivalent — decisions identical to the
reference's one-pod-at-a-time loop), then walks the per-pod results through
assume → reserve → permit → bind exactly like schedulingCycle/bindingCycle
(schedule_one.go:135-340).

API access is abstracted behind ``ClusterSource`` (list/watch events in) and
the handle's ``bind`` (writes out) — a fake in-process implementation lives
in kubernetes_tpu.testing; a real client would speak the same interface.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from kubernetes_tpu.api.types import Node, Pod
from kubernetes_tpu.cache import Cache, SnapshotMirror
from kubernetes_tpu.framework import config as cfg
from kubernetes_tpu.framework.interface import (
    ActionType,
    ClusterEvent,
    Code,
    CycleState,
    EventResource,
    Status,
)
from kubernetes_tpu.framework.registry import Registry, default_registry
from kubernetes_tpu.framework.runtime import Framework
from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL
from kubernetes_tpu.oracle.state import NodeState, OracleState
from kubernetes_tpu.ops import gang
from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, I32
from kubernetes_tpu.queue import SchedulingQueue
from kubernetes_tpu.queue.nominator import Nominator
from kubernetes_tpu.snapshot.interner import PAD
from kubernetes_tpu.snapshot.schema import bucket_cap, pack_pod_batch


@dataclass
class ScheduleOutcome:
    pod: Pod
    node: Optional[str]
    status: Status
    n_feasible: int = 0


class Handle:
    """framework.Handle analogue — what plugins see of the scheduler."""

    def __init__(self, scheduler: "Scheduler"):
        self._s = scheduler

    def bind(self, pod: Pod, node_name: str) -> None:
        self._s.binding_sink(pod, node_name)

    def oracle_state(self) -> OracleState:
        return self._s.oracle_view()

    @property
    def nominator(self) -> Nominator:
        return self._s.nominator


class Scheduler:
    def __init__(
        self,
        configuration: Optional[cfg.SchedulerConfiguration] = None,
        registry: Optional[Registry] = None,
        binding_sink=None,
        namespace_labels: Optional[Dict[str, Dict[str, str]]] = None,
        clock=time.monotonic,
    ):
        self.config = configuration or cfg.SchedulerConfiguration()
        self.config.validate()
        self.binding_sink = binding_sink or (lambda pod, node: None)
        self.namespace_labels = namespace_labels or {}
        self.clock = clock

        self.cache = Cache()
        self.mirror = SnapshotMirror()
        self.nominator = Nominator()
        handle = Handle(self)
        reg = registry or default_registry()
        self.profiles: Dict[str, Framework] = {
            p.scheduler_name: Framework(p, reg, handle)
            for p in self.config.profiles
        }

        # queueing hints: union over profiles (eventhandlers.go:431)
        hints: Dict[str, list] = {}
        for fwk in self.profiles.values():
            for name, evs in fwk.events_to_register().items():
                hints.setdefault(name, []).extend(evs)

        default_fwk = next(iter(self.profiles.values()))
        self.queue = SchedulingQueue(
            queueing_hints=hints,
            pre_enqueue_check=default_fwk.run_pre_enqueue,
            initial_backoff_s=self.config.pod_initial_backoff_seconds,
            max_backoff_s=self.config.pod_max_backoff_seconds,
            clock=clock,
        )
        self._dirty_pending = False
        self.metrics: Dict[str, float] = {
            "schedule_attempts": 0,
            "scheduled": 0,
            "unschedulable": 0,
            "errors": 0,
        }

    # ----- event handlers (eventhandlers.go:345-428) ------------------------

    def on_node_add(self, node: Node) -> None:
        self.cache.add_node(node)
        self.queue.move_all_on_event(
            ClusterEvent(EventResource.NODE, ActionType.ADD), None, node
        )

    def on_node_update(self, old: Node, new: Node) -> None:
        self.cache.update_node(new)
        action = ActionType(0)
        if old.labels != new.labels:
            action |= ActionType.UPDATE_NODE_LABEL
        if old.taints != new.taints or old.unschedulable != new.unschedulable:
            action |= ActionType.UPDATE_NODE_TAINT
        if (
            old.allocatable.milli_cpu != new.allocatable.milli_cpu
            or old.allocatable.memory != new.allocatable.memory
            or old.allocatable.scalars != new.allocatable.scalars
        ):
            action |= ActionType.UPDATE_NODE_ALLOCATABLE
        if action:
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.NODE, action), old, new
            )

    def on_node_delete(self, node: Node) -> None:
        self.cache.remove_node(node.name)
        self.queue.move_all_on_event(
            ClusterEvent(EventResource.NODE, ActionType.DELETE), node, None
        )

    def on_pod_add(self, pod: Pod) -> None:
        if pod.node_name:
            self.cache.add_pod(pod)
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.ADD),
                None,
                pod,
            )
        elif self._responsible_for(pod):
            self.queue.add(pod)

    def on_pod_update(self, old: Pod, new: Pod) -> None:
        if new.node_name:
            if old.node_name:
                self.cache.update_pod(old, new)
            else:
                self.cache.add_pod(new)
            action = ActionType(0)
            if old.labels != new.labels:
                action |= ActionType.UPDATE_POD_LABEL
            if action:
                self.queue.move_all_on_event(
                    ClusterEvent(EventResource.ASSIGNED_POD, action), old, new
                )
        else:
            self.queue.update(old, new)

    def on_pod_delete(self, pod: Pod) -> None:
        if pod.node_name:
            self.cache.remove_pod(pod)
            self.queue.move_all_on_event(
                ClusterEvent(EventResource.ASSIGNED_POD, ActionType.DELETE),
                pod,
                None,
            )
        else:
            self.queue.delete(pod)
        self.nominator.delete(pod)

    def _responsible_for(self, pod: Pod) -> bool:
        return pod.scheduler_name in self.profiles

    # ----- views ------------------------------------------------------------

    def oracle_view(self) -> OracleState:
        """Host-object view of the cache for host-backed plugins/oracle."""
        st = OracleState(namespace_labels=self.namespace_labels)
        for cn in self.cache.real_nodes():
            ns = NodeState(node=cn.node)
            for p in cn.pods.values():
                ns.add_pod(p)
            st.nodes[cn.node.name] = ns
        return st

    # ----- the scheduling loop ---------------------------------------------

    def schedule_pending(self, max_batches: Optional[int] = None) -> List[ScheduleOutcome]:
        """Drain the active queue in gang batches; returns all outcomes."""
        outcomes: List[ScheduleOutcome] = []
        batches = 0
        while True:
            batch = self.queue.pop_batch(self.config.batch_size)
            if not batch:
                break
            outcomes.extend(self._schedule_batch(batch))
            batches += 1
            if max_batches is not None and batches >= max_batches:
                break
        return outcomes

    def _schedule_batch(self, batch) -> List[ScheduleOutcome]:
        pods = [qp.pod for qp in batch]
        fwk = self.profiles.get(
            pods[0].scheduler_name, next(iter(self.profiles.values()))
        )

        # 1. snapshot: incremental host-side pack + device upload
        self.mirror.update(self.cache, self.namespace_labels)
        vocab = self.mirror.vocab
        for pod in pods:
            for k, v in pod.labels.items():
                vocab.intern_label(k, v)
        if bucket_cap(len(vocab.label_keys)) > self.mirror.nodes.k_cap:
            self.mirror._force_full = True
            self.mirror.update(self.cache, self.namespace_labels)

        p_cap = bucket_cap(len(pods), 1)
        pb = pack_pod_batch(
            pods,
            vocab,
            k_cap=self.mirror.nodes.k_cap,
            p_cap=p_cap,
            namespace_labels=self.namespace_labels,
        )
        dc = DeviceCluster.from_host(self.mirror.nodes, self.mirror.existing, vocab)
        db = DeviceBatch.from_host(pb)
        v_cap = bucket_cap(len(vocab.label_vals))
        hostname_key = jnp.asarray(vocab.label_keys.lookup(HOSTNAME_LABEL), I32)

        has_interpod = bool(
            (pb.aff_kind != PAD).any()
            or (self.mirror.existing.term_kind != PAD).any()
        )
        has_spread = bool((pb.tsc_topo_key != PAD).any())
        has_images = bool((pb.img_ids >= 0).any())
        has_ports = bool(
            (pb.want_ppk != PAD).any() or (self.mirror.nodes.used_ppk != PAD).any()
        )
        enabled = fwk.device_enabled()
        weights = tuple(
            fwk.score_weights.get(n, 0)
            for n in (
                "TaintToleration",
                "NodeAffinity",
                "PodTopologySpread",
                "InterPodAffinity",
                "NodeResourcesFit",
                "NodeResourcesBalancedAllocation",
                "ImageLocality",
            )
        )

        # 2. one fused device dispatch (the whole Filter→Score→Select loop)
        chosen, n_feas, _ = gang.gang_run(
            dc,
            db,
            hostname_key,
            v_cap,
            has_interpod=has_interpod,
            has_spread=has_spread,
            has_ports=has_ports,
            has_images=has_images,
            enabled=enabled,
            weights=weights,
        )
        chosen = jax.device_get(chosen)
        n_feas = jax.device_get(n_feas)

        # 3. per-pod commit: assume → reserve → permit → bind
        node_names = self.mirror.nodes.names
        outcomes = []
        state = CycleState()
        for i, qp in enumerate(batch):
            pod = qp.pod
            self.metrics["schedule_attempts"] += 1
            idx = int(chosen[i])
            if idx < 0:
                status = Status.unschedulable(
                    "no nodes available" if int(n_feas[i]) == 0 else "filtered out"
                )
                self._handle_failure(qp, status)
                outcomes.append(
                    ScheduleOutcome(pod, None, status, int(n_feas[i]))
                )
                continue
            node_name = node_names[idx]
            outcome = self._commit(fwk, state, qp, node_name, int(n_feas[i]))
            outcomes.append(outcome)
        return outcomes

    def _commit(self, fwk, state, qp, node_name: str, n_feas: int) -> ScheduleOutcome:
        """assume → reserve → permit → bind (schedulingCycle/bindingCycle)."""
        pod = qp.pod
        self.cache.assume_pod(pod, node_name)

        s = fwk.run_reserve(state, pod, node_name)
        if not s.ok:
            self.cache.forget_pod(pod)
            self._handle_failure(qp, s)
            return ScheduleOutcome(pod, None, s, n_feas)

        s = fwk.run_permit(state, pod, node_name)
        if s.rejected or s.code == Code.ERROR:
            fwk.run_unreserve(state, pod, node_name)
            self.cache.forget_pod(pod)
            self._handle_failure(qp, s)
            return ScheduleOutcome(pod, None, s, n_feas)
        if s.code == Code.WAIT:
            s = fwk.wait_on_permit(pod)
            if not s.ok:
                fwk.run_unreserve(state, pod, node_name)
                self.cache.forget_pod(pod)
                self._handle_failure(qp, s)
                return ScheduleOutcome(pod, None, s, n_feas)

        s = fwk.run_pre_bind(state, pod, node_name)
        if not s.ok:
            fwk.run_unreserve(state, pod, node_name)
            self.cache.forget_pod(pod)
            self._handle_failure(qp, s)
            return ScheduleOutcome(pod, None, s, n_feas)

        s = fwk.run_bind(state, pod, node_name)
        if not s.ok:
            # The in-flight ledger is still intact here, so events that
            # arrived during the attempt replay through add_unschedulable.
            fwk.run_unreserve(state, pod, node_name)
            self.cache.forget_pod(pod)
            self._handle_failure(qp, s)
            return ScheduleOutcome(pod, None, s, n_feas)
        self.queue.done(pod.uid)
        fwk.run_post_bind(state, pod, node_name)
        self.cache.finish_binding(pod)
        self.nominator.delete(pod)
        self.metrics["scheduled"] += 1
        return ScheduleOutcome(pod, node_name, Status.success(), n_feas)

    def _handle_failure(self, qp, status: Status) -> None:
        """handleSchedulingFailure (schedule_one.go:1020)."""
        if status.code == Code.ERROR:
            self.metrics["errors"] += 1
        else:
            self.metrics["unschedulable"] += 1
        plugins = {status.plugin} if status.plugin else set()
        self.queue.add_unschedulable(qp, plugins)
