"""CLI: ``python -m kubernetes_tpu.chaos``.

    --list                      show the scenario catalogue
    --scenario NAME [--seed N]  run one seeded scenario (repeatable)
    --all                       run every catalogued scenario
    --journal PATH              record the run's journal to PATH
    --replay PATH               replay a recorded journal; exit 1 on any
                                placement mismatch
    --soak [--pods N --nodes N --rate R --seed N]
                                fixed-rate mixed-fault soak (the bench's
                                config7 shape), JSON result on stdout

Exit status: 0 when every oracle/replay check passes, 1 otherwise.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from typing import List, Optional


def main(argv: Optional[List[str]] = None) -> int:
    from kubernetes_tpu.chaos import SCENARIOS, replay, run_chaos_soak, run_scenario

    ap = argparse.ArgumentParser(prog="python -m kubernetes_tpu.chaos")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    ap.add_argument("--scenario", action="append", help="scenario name (repeatable)")
    ap.add_argument("--all", action="store_true", help="run every scenario")
    ap.add_argument("--seed", type=int, help="override the scenario seed")
    ap.add_argument("--journal", help="record the journal to this path")
    ap.add_argument("--replay", help="replay a recorded journal")
    ap.add_argument("--soak", action="store_true", help="fixed-rate mixed soak")
    ap.add_argument("--pods", type=int, default=600)
    ap.add_argument("--nodes", type=int, default=24)
    ap.add_argument("--rate", type=float, default=0.15)
    args = ap.parse_args(argv)

    if args.list:
        # one line per scenario, description included — the catalogue is
        # the single source of truth, so a new scenario shows up here
        # (and under --all) the day it lands, no hand-maintained list
        for name, scn in sorted(SCENARIOS.items()):
            print(
                f"{name:20s} {scn.desc or '(no description)'}\n"
                f"{'':20s}   seed={scn.seed} kind={scn.kind} "
                f"mode={scn.mode} pods={scn.n_pods} "
                f"faults={sorted(scn.rates) or ['none']}"
            )
        return 0

    if args.replay:
        res = replay(args.replay)
        print(
            f"replayed {res.drains} drains / {res.deliveries} deliveries: "
            f"{len(res.placements)} placements, "
            f"{len(res.mismatches)} mismatches"
        )
        for m in res.mismatches:
            print(f"  MISMATCH {m}")
        return 0 if res.ok else 1

    if args.soak:
        out = run_chaos_soak(
            n_nodes=args.nodes,
            n_pods=args.pods,
            fault_rate=args.rate,
            seed=args.seed if args.seed is not None else 2026,
            progress=lambda m: print(f"# {m}", file=sys.stderr),
        )
        print(json.dumps(out, sort_keys=True))
        return 0 if not out["problems"] else 1

    names = list(SCENARIOS) if args.all else (args.scenario or [])
    if not names:
        ap.print_help()
        return 2
    rc = 0
    for name in names:
        scn = SCENARIOS[name]
        if args.seed is not None:
            scn = dataclasses.replace(scn, seed=args.seed)
        journal_path = args.journal
        if journal_path and len(names) > 1:
            # one file per scenario — a shared path would silently keep
            # only the last recording
            root, ext = os.path.splitext(journal_path)
            journal_path = f"{root}.{name}{ext or '.jsonl'}"
        res = run_scenario(
            scn,
            journal_path=journal_path,
            progress=lambda m: print(f"# {m}", file=sys.stderr),
        )
        status = "ok" if res.ok else "FAIL"
        print(
            f"{name}: {status} bound={len(res.placements)} "
            f"faults={res.injected} wall={res.wall_s:.2f}s"
            + (
                f" failover_stall={res.failover_stall_s:.1f}s"
                if res.failover_stall_s is not None
                else ""
            )
        )
        for p in res.problems:
            print(f"  PROBLEM {p}")
        if not res.ok:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
