"""Journal: logical-timestamped record/replay for the control-plane loop.

The journal is a JSONL stream of logically-timestamped entries — no wall
clocks anywhere, so two runs of the same deterministic scenario produce
byte-identical files:

  {"t": 1, "kind": "header", "scenario": ..., "seed": ..., "rates": ...}
  {"t": 2, "kind": "clock", "now": 1000.0}
  {"t": 3, "kind": "delivery", "res": "nodes", "action": "add", "obj": ...}
  {"t": 7, "kind": "fault", "fault": "bind_conflict", "seam": "bind", ...}
  {"t": 9, "kind": "drain_start", "n": 0}
  {"t": 12, "kind": "drain_end", "n": 0, "decisions": [{"pod": uid,
      "node": "node-3", "code": "SUCCESS"}, ...]}

``JournalRecorder.attach`` wraps a Scheduler's six informer-facing
handlers so every delivery is journaled in the exact order the scheduler
processed it (the wrapper records INSIDE the scheduler lock — ``_mu`` is
reentrant — so journal order can never contradict apply order).

``replay`` feeds a recorded stream to a fresh ``Scheduler`` and asserts
its placement decisions match the journal bit-for-bit: deliveries
recorded between a drain's start/end markers are applied *after* the
replayed drain (they raced the live dispatch — bind confirmations,
relist echoes — and must not be visible to the batch that preceded them).
Bind faults are re-derived from the header's seed via ``FaultPlan``, so
the replayed scheduler suffers the same 409s the live one did.

Journals checked into ``tests/fixtures/journals/`` are regression
corpora: a behavior change in the scheduler that alters any recorded
placement fails the replay test and must be acknowledged by re-recording.
"""

from __future__ import annotations

import json
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.api.codec import decode, encode

JOURNAL_VERSION = 1

# Lock-discipline registry (kubernetes_tpu.analysis reads this literal):
# reflector threads, binding workers, and the scenario driver all append.
_KTPU_GUARDED = {
    "Journal": {
        "lock": "_mu",
        "guards": {"_entries": None, "_t": None},
    },
}


class LogicalClock:
    """Manually-advanced clock injected into the scheduler (and electors)
    so backoff expiry and lease timing are scenario state, not wall time.
    Reads are a single attribute load — safe from any thread."""

    def __init__(self, start: float = 1000.0):
        self.now = start

    def __call__(self) -> float:
        return self.now

    def advance(self, dt: float) -> float:
        self.now += dt
        return self.now


class Journal:
    """Append-only entry log with process-logical timestamps."""

    def __init__(self, path: Optional[str] = None):
        self._mu = threading.Lock()
        self._entries: List[dict] = []
        self._t = 0
        self.path = path

    def append(self, kind: str, **fields) -> dict:
        with self._mu:
            self._t += 1
            entry = {"t": self._t, "kind": kind, **fields}
            self._entries.append(entry)
            return entry

    def now(self) -> int:
        """Current logical timestamp (the last appended entry's ``t``) —
        the correlation key observability spans carry so a wall-clock
        trace can be located in the replayable journal stream."""
        with self._mu:
            return self._t

    def entries(self) -> List[dict]:
        with self._mu:
            return list(self._entries)

    def serialize(self) -> str:
        return "".join(
            json.dumps(e, sort_keys=True) + "\n" for e in self.entries()
        )

    def dump(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("journal has no path")
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.serialize())
        return path

    @staticmethod
    def load_entries(path: str) -> List[dict]:
        out = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if line:
                    out.append(json.loads(line))
        return out


class JournalRecorder:
    """Wraps a Scheduler's informer-facing handlers with journaling.

    Must run BEFORE the cluster source reads the handlers off the
    scheduler (``FakeCluster.connect`` / ``RemoteClusterSource.connect``
    capture bound methods).  The wrapper takes the scheduler's reentrant
    lock around {record + apply} so the journal order is exactly the
    order the scheduler observed.
    """

    def __init__(self, journal: Journal):
        self.journal = journal
        self._originals = None  # (sched, {handler name: original}) once attached

    def attach(self, sched) -> None:
        journal = self.journal
        mu = sched._mu
        # wall-clock ↔ logical-time correlation: trace spans recorded while
        # this journal is attached carry its logical timestamp as args.lt
        tracer = getattr(sched, "tracer", None)
        if tracer is not None:
            tracer.logical_time = journal.now
        # the control-plane monitor's chain breadcrumbs carry the same
        # logical stamps, so a replayed journal reconstructs chains
        # byte-identically (kind, rv, lt)
        cp = getattr(sched, "controlplane", None)
        if cp is not None:
            cp.logical_time = journal.now
        self._originals = (
            sched,
            {
                name: getattr(sched, name)
                for name in (
                    "on_node_add",
                    "on_node_update",
                    "on_node_delete",
                    "on_pod_add",
                    "on_pod_update",
                    "on_pod_delete",
                )
            },
        )

        def wrap1(action: str, res: str, orig):
            def handler(obj):
                with mu:
                    journal.append(
                        "delivery", res=res, action=action, obj=encode(obj)
                    )
                    orig(obj)

            return handler

        def wrap2(res: str, orig):
            def handler(old, new):
                with mu:
                    journal.append(
                        "delivery",
                        res=res,
                        action="update",
                        obj=encode(new),
                        old=encode(old),
                    )
                    orig(old, new)

            return handler

        sched.on_node_add = wrap1("add", "nodes", sched.on_node_add)
        sched.on_node_update = wrap2("nodes", sched.on_node_update)
        sched.on_node_delete = wrap1("delete", "nodes", sched.on_node_delete)
        sched.on_pod_add = wrap1("add", "pods", sched.on_pod_add)
        sched.on_pod_update = wrap2("pods", sched.on_pod_update)
        sched.on_pod_delete = wrap1("delete", "pods", sched.on_pod_delete)

    def detach(self) -> None:
        """Restore the scheduler's original handlers and stop stamping this
        journal's logical time into trace spans — for schedulers that
        outlive the recorded scenario."""
        if self._originals is None:
            return
        sched, originals = self._originals
        self._originals = None
        with sched._mu:
            for name, orig in originals.items():
                setattr(sched, name, orig)
        tracer = getattr(sched, "tracer", None)
        if tracer is not None and tracer.logical_time == self.journal.now:
            tracer.logical_time = None
        cp = getattr(sched, "controlplane", None)
        if cp is not None and cp.logical_time == self.journal.now:
            cp.logical_time = None


def decisions_of(outcomes) -> List[dict]:
    """ScheduleOutcomes → canonical decision records, sorted by pod uid so
    journal bytes don't depend on batch-internal ordering."""
    return sorted(
        (
            {
                "pod": o.pod.uid,
                "node": o.node,
                "code": o.status.code.name,
            }
            for o in outcomes
        ),
        key=lambda d: d["pod"],
    )


# ---------------------------------------------------------------------------
# replay
# ---------------------------------------------------------------------------


@dataclass
class ReplayResult:
    drains: int = 0
    deliveries: int = 0
    mismatches: List[str] = field(default_factory=list)
    placements: Dict[str, Optional[str]] = field(default_factory=dict)
    expected: Dict[str, Optional[str]] = field(default_factory=dict)

    @property
    def ok(self) -> bool:
        return not self.mismatches


def _apply_delivery(sched, entry: dict) -> None:
    res, action = entry["res"], entry["action"]
    obj = decode(entry["obj"])
    if action == "update":
        old = decode(entry["old"])
        if res == "nodes":
            sched.on_node_update(old, obj)
        else:
            sched.on_pod_update(old, obj)
    elif action == "add":
        (sched.on_node_add if res == "nodes" else sched.on_pod_add)(obj)
    else:
        (sched.on_node_delete if res == "nodes" else sched.on_pod_delete)(obj)


def replay(source, scheduler_factory=None) -> ReplayResult:
    """Feed a recorded journal to a fresh Scheduler; compare decisions.

    ``source`` is a path, a list of entries, or a Journal.  The replayed
    scheduler binds into a local dict through a chaos-wrapped sink rebuilt
    from the header's seed, so every recorded 409 recurs on schedule.
    """
    from kubernetes_tpu.chaos.faults import FaultPlan
    from kubernetes_tpu.chaos.proxy import chaos_binding_sink, chaos_binding_sink_many

    if isinstance(source, Journal):
        entries = source.entries()
    elif isinstance(source, str):
        entries = Journal.load_entries(source)
    else:
        entries = list(source)
    if not entries or entries[0].get("kind") != "header":
        raise ValueError("journal has no header entry")
    header = entries[0]
    if header.get("version") != JOURNAL_VERSION:
        raise ValueError(f"unsupported journal version {header.get('version')}")

    plan = FaultPlan(
        seed=header["seed"],
        rates=header.get("rates", {}),
        bind_delay_s=0.0,  # latency faults are not semantic — skip sleeps
        lease_blackout=tuple(header["lease_blackout"])
        if header.get("lease_blackout")
        else None,
    )
    clock = LogicalClock(header.get("clock0", 1000.0))
    if scheduler_factory is None:
        from kubernetes_tpu.scheduler import Scheduler

        sched = Scheduler(clock=clock)
    else:
        sched = scheduler_factory(clock)

    # device-fault seams (ISSUE 15): rebuilt from the same header seed,
    # so the replayed scheduler suffers the identical dispatch-boundary
    # faults — breaker routing may differ in timing, but every fallback
    # engine is bit-identical, so the decision comparison still gates.
    # hang_s=0: stalls are not semantic, like the bind-delay sleeps.
    device_injector = None
    from kubernetes_tpu.chaos import faults as _faults

    if any(k in _faults.DEVICE_KINDS for k in plan.rates):
        from kubernetes_tpu.chaos.device import DeviceFaultInjector, install

        device_injector = DeviceFaultInjector(plan, hang_s=0.0)
        install(device_injector)

    # control-plane chain replay: when the factory installed a monitor,
    # drive its logical clock from the entry stream's own ``t`` stamps —
    # exactly the values Journal.now() returned live (the delivery entry
    # is appended before its handler runs; drain-time breadcrumbs see the
    # drain_start entry's t), so reconstructed chains compare byte-for-
    # byte on (kind, rv, lt) against the recording run's.
    cp = getattr(sched, "controlplane", None)
    lt_cursor = [0]
    if cp is not None and cp.logical_time is None:
        cp.logical_time = lambda: lt_cursor[0]

    result = ReplayResult()
    bound: Dict[str, str] = {}
    sink = chaos_binding_sink(
        lambda pod, node: bound.__setitem__(pod.uid, node), plan, sleep=lambda s: None
    )
    sched.binding_sink = sink
    if header.get("sink_many"):

        def sink_many_raw(pairs):
            for pod, node in pairs:
                bound[pod.uid] = node
            return [None] * len(pairs)

        sched.binding_sink_many = chaos_binding_sink_many(
            sink_many_raw, plan, sleep=lambda s: None
        )

    in_drain = False
    buffered: List[dict] = []
    try:
        for entry in entries[1:]:
            kind = entry["kind"]
            if kind == "clock":
                clock.now = entry["now"]
            elif kind == "delivery":
                result.deliveries += 1
                if in_drain:
                    # raced the live dispatch (bind confirmations, relist
                    # echoes): invisible to the drain that was running
                    buffered.append(entry)
                else:
                    lt_cursor[0] = entry["t"]
                    _apply_delivery(sched, entry)
            elif kind == "drain_start":
                lt_cursor[0] = entry["t"]
                in_drain = True
            elif kind == "drain_end":
                outs = sched.schedule_pending()
                got = decisions_of(outs)
                want = entry.get("decisions", [])
                if got != want:
                    result.mismatches.append(
                        f"drain {entry.get('n')}: got {got} want {want}"
                    )
                for d in want:
                    if d["code"] == "SUCCESS" and d["node"]:
                        result.expected[d["pod"]] = d["node"]
                for d in got:
                    if d["code"] == "SUCCESS" and d["node"]:
                        result.placements[d["pod"]] = d["node"]
                result.drains += 1
                in_drain = False
                for pending in buffered:
                    lt_cursor[0] = pending["t"]
                    _apply_delivery(sched, pending)
                buffered.clear()
            # "fault" / "note" entries are informational
        for pending in buffered:
            lt_cursor[0] = pending["t"]
            _apply_delivery(sched, pending)
    finally:
        if device_injector is not None:
            from kubernetes_tpu.chaos.device import install

            install(None)
    return result
