"""Scenario soak runner + post-run invariant oracle.

A scenario composes a workload with a seeded ``FaultPlan`` and drives the
REAL control-plane loop through it:

  * ``inproc`` scenarios connect the scheduler straight to a FakeCluster
    (synchronous deliveries, ``parallelism=1`` bind pool) — fully
    deterministic: same seed → byte-identical journal → identical binds;
  * ``http`` scenarios run FakeCluster ← ApiServer ← ChaosClient-backed
    RemoteClusterSource (real reflectors, watch caches, relists) with the
    NodeLifecycleController / LeaseElector in the loop where the scenario
    demands — deliveries race threads, so the journal records the order
    the scheduler actually observed and replay reproduces the recorded
    placements bit-for-bit.

After the drive, the INVARIANT ORACLE must come back empty:

  1. scheduler cache == API ground truth (CacheDebugger.compare);
  2. no leaked assumed pods;
  3. mirror usage rows == fresh recomputation from the cache (the
     KTPU_SANITIZE drift probe, run explicitly);
  4. every created pod is bound, deleted (evicted/churned), or carries a
     FailedScheduling event;
  5. no pod ever successfully bound to two different nodes (bind ledger);
  6. nothing left in active/backoff queues (the drain converged);
  7. failover scenarios: leader-handoff stall within the lease budget.

``python -m kubernetes_tpu.chaos`` drives scenarios, soaks, and replays.
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from kubernetes_tpu.analysis import sanitizer
from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Container, Node, Pod
from kubernetes_tpu.chaos import faults
from kubernetes_tpu.chaos.journal import (
    JOURNAL_VERSION,
    Journal,
    JournalRecorder,
    LogicalClock,
    decisions_of,
)
from kubernetes_tpu.chaos.proxy import (
    ChaosClient,
    ChaosLeaseStore,
    chaos_binding_sink,
    chaos_binding_sink_many,
)

CLOCK0 = 1000.0


# ---------------------------------------------------------------------------
# scenario catalogue
# ---------------------------------------------------------------------------


@dataclass
class Scenario:
    name: str
    seed: int
    kind: str = "basic"  # basic | flap | failover
    mode: str = "inproc"  # inproc | http
    n_nodes: int = 6
    n_pods: int = 36
    rounds: int = 3
    rates: Dict[str, float] = field(default_factory=dict)
    unschedulable: int = 0  # pods that can never fit (FailedScheduling path)
    bind_delay_s: float = 0.01
    lease_duration_s: float = 8.0
    flap_grace_s: float = 6.0
    synthetic: bool = False  # draw pods from workloads.synthetic instead
    # spread-constrained pods force every batch onto a device dispatch
    # (wave/gang engine) — the device-fault scenarios need a dispatch
    # stream for their seams to draw on; plain pods ride the host greedy
    spread: bool = False
    # wire codec for http-mode clients ("binary" | "json"); inproc
    # scenarios have no wire, so the field is inert there.  Faults are
    # injected above the codec seam (ChaosClient wraps decoded events),
    # so journals replay identically under either value.
    codec: str = "binary"
    # one-line catalogue description (``--list``); every scenario must
    # carry one (tested) so the CLI is self-documenting
    desc: str = ""


SCENARIOS: Dict[str, Scenario] = {
    s.name: s
    for s in (
        # deterministic in-proc scenarios (same seed → byte-identical journal)
        Scenario(
            "bind-conflict",
            seed=101,
            rates={faults.BIND_CONFLICT: 0.25},
            desc="binding sink 409s → unreserve/forget/requeue unwind",
        ),
        Scenario(
            "bind-slow",
            seed=102,
            rates={faults.BIND_SLOW: 0.4},
            bind_delay_s=0.005,
            desc="stalled binds overlap later dispatches, then confirm",
        ),
        Scenario(
            "unschedulable-burst",
            seed=103,
            rates={faults.BIND_CONFLICT: 0.15},
            unschedulable=3,
            desc="hopeless pods + bind 409s: FailedScheduling path under churn",
        ),
        Scenario(
            "leader-failover",
            seed=104,
            kind="failover",
            rates={faults.LEASE_CONTENTION: 0.1},
            n_pods=24,
            rounds=2,
            desc="scripted lease blackout deposes A; B takes over in budget",
        ),
        # full-stack HTTP scenarios (reflector/relist/watch-cache in the loop)
        Scenario(
            "watch-cut",
            seed=105,
            mode="http",
            rates={faults.WATCH_CUT: 0.06},
            desc="mid-stream watch EOFs → re-watch at current rv, no relist",
        ),
        Scenario(
            "compaction",
            seed=106,
            mode="http",
            rates={faults.COMPACT: 0.06},
            desc="forced 410 compactions → relist + exact diff resync",
        ),
        Scenario(
            "api-errors",
            seed=107,
            mode="http",
            # watch cuts force relists, so the list/patch request stream is
            # busy enough for the transport faults to actually land
            rates={
                faults.API_ERROR: 0.25,
                faults.API_TIMEOUT: 0.2,
                faults.WATCH_CUT: 0.04,
            },
            desc="REST transport errors/timeouts on a busy list/patch stream",
        ),
        Scenario(
            "node-flap",
            seed=108,
            kind="flap",
            mode="http",
            n_pods=24,
            rounds=2,
            desc="heartbeat loss → NotReady taint, evictions, recovery",
        ),
        # device-fault scenarios (ISSUE 15): dispatch-boundary seams —
        # spread pods force every batch onto a device dispatch so the
        # fault draws have a kernel stream to land on; recovery rides the
        # per-kernel breakers + serial fallbacks, bit-identically
        Scenario(
            "device-errors",
            seed=110,
            spread=True,
            rates={faults.DISPATCH_ERROR: 0.4},
            desc="backend RuntimeErrors from jit roots → retry/breaker/serial",
        ),
        Scenario(
            "device-hang",
            seed=111,
            spread=True,
            rates={faults.DISPATCH_HANG: 0.5},
            desc="dispatches stall past the watchdog → breaker parks kernel",
        ),
        Scenario(
            "device-poison",
            seed=112,
            spread=True,
            rates={faults.POISONED_OUTPUT: 0.6},
            desc="NaN/out-of-range readbacks → guarded re-fetch heals",
        ),
        Scenario(
            "mesh-loss",
            seed=113,
            spread=True,
            rates={
                faults.MESH_DEVICE_LOSS: 0.3,
                faults.DISPATCH_ERROR: 0.15,
            },
            desc="device drops from the mesh → degrade to smaller/single-chip",
        ),
        Scenario(
            "mixed-soak",
            seed=109,
            mode="http",
            n_pods=48,
            rounds=3,
            unschedulable=2,
            # NOTE deliberately not spread=True: over the racing HTTP
            # tier, equal-scored node pairs make live-vs-replay tie
            # order delivery-race-sensitive (a latent property of
            # spread workloads over HTTP, independent of device faults
            # — the four inproc device scenarios carry the
            # dispatch-heavy spread coverage with byte-identical
            # journals).  Device faults still ride the fast path's
            # static_eval dispatches and the snapshot-sync seam here.
            rates={
                faults.WATCH_CUT: 0.02,
                faults.COMPACT: 0.02,
                faults.API_ERROR: 0.08,
                faults.BIND_CONFLICT: 0.15,
                faults.BIND_SLOW: 0.15,
                # device seams folded in (ISSUE 15)
                faults.DISPATCH_ERROR: 0.12,
                faults.POISONED_OUTPUT: 0.1,
                faults.HBM_OOM: 0.08,
            },
            desc="every control-plane seam + device faults, one soak",
        ),
    )
}


# ---------------------------------------------------------------------------
# workload factories (uids are EXPLICIT — the process-global uid counter
# would break journal byte-determinism across runs)
# ---------------------------------------------------------------------------


def _mk_nodes(n: int) -> List[Node]:
    return [
        Node(
            name=f"chaos-node-{i}",
            labels={
                "topology.kubernetes.io/zone": f"zone-{i % 3}",
                "kubernetes.io/hostname": f"chaos-node-{i}",
            },
            capacity=Resource.from_map({"cpu": "8", "memory": "32Gi", "pods": 110}),
        )
        for i in range(n)
    ]


def _mk_pod(i: int, rng, unschedulable: bool = False, spread: bool = False) -> Pod:
    if unschedulable:
        requests = {"cpu": "64", "memory": "1Ti"}
    else:
        requests = {
            "cpu": f"{rng.choice([100, 250, 500])}m",
            "memory": f"{rng.choice([128, 256, 512])}Mi",
        }
    tsc = ()
    if spread and not unschedulable:
        # a zone-spread constraint makes the batch wave-shaped: every
        # drain rides a device dispatch (the device-fault seams' stream)
        from kubernetes_tpu.api.types import LabelSelector, TopologySpreadConstraint

        tsc = (
            TopologySpreadConstraint(
                max_skew=2,
                topology_key="topology.kubernetes.io/zone",
                when_unsatisfiable="ScheduleAnyway",
                label_selector=LabelSelector(
                    match_labels={"app": f"app-{i % 5}"}
                ),
            ),
        )
    return Pod(
        name=f"chaos-{i}",
        uid=f"default/chaos-{i}",
        labels={"app": f"app-{i % 5}"},
        topology_spread_constraints=tsc,
        containers=[Container(name="c", requests=requests)],
    )


def _mk_synthetic_pod(i: int, rng) -> Pod:
    from kubernetes_tpu.workloads.synthetic import make_pod

    p = make_pod(rng, f"chaos-{i}")
    p.uid = f"{p.namespace}/chaos-{i}"
    return p


def _wait(predicate, timeout: float = 20.0, interval: float = 0.01) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(interval)
    return bool(predicate())


# ---------------------------------------------------------------------------
# bind ledger (oracle input: no pod ever bound to two nodes)
# ---------------------------------------------------------------------------


class _BindLedger:
    def __init__(self):
        self._mu = threading.Lock()
        self.nodes_by_uid: Dict[str, set] = {}

    def record(self, uid: str, node: str) -> None:
        with self._mu:
            self.nodes_by_uid.setdefault(uid, set()).add(node)

    def wrap(self, sink):
        def bind(pod, node_name):
            out = sink(pod, node_name)
            self.record(pod.uid, node_name)
            return out

        return bind

    def wrap_many(self, sink_many):
        def bind_many(pairs):
            errs = sink_many(pairs)
            for (pod, node_name), err in zip(pairs, errs):
                if err is None:
                    self.record(pod.uid, node_name)
            return errs

        return bind_many

    def double_bound(self) -> List[str]:
        with self._mu:
            return sorted(
                uid for uid, nodes in self.nodes_by_uid.items() if len(nodes) > 1
            )


# ---------------------------------------------------------------------------
# fault → queue-drained recovery tracking (feeds the chaos histogram)
# ---------------------------------------------------------------------------


class _RecoveryTracker:
    """Opens a window at the first injection after quiescence; the runner
    closes it when the queue next fully drains — the observed value is the
    fault→recovered latency per kind."""

    def __init__(self, hist):
        self.hist = hist
        self._mu = threading.Lock()
        self._open: Dict[str, float] = {}  # kind → wall start

    def mark(self, kind: str) -> None:
        with self._mu:
            self._open.setdefault(kind, time.perf_counter())

    def drained(self) -> None:
        now = time.perf_counter()
        with self._mu:
            windows, self._open = self._open, {}
        for kind, t0 in windows.items():
            if self.hist is not None:
                self.hist.observe(now - t0, kind=kind)


# ---------------------------------------------------------------------------
# scenario context + drive
# ---------------------------------------------------------------------------


@dataclass
class ScenarioResult:
    scenario: str
    seed: int
    problems: List[str]
    placements: Dict[str, str]
    injected: Dict[str, int]
    journal: Journal
    created: int
    wall_s: float
    failover_stall_s: Optional[float] = None
    evicted: int = 0

    @property
    def ok(self) -> bool:
        return not self.problems


class _Ctx:
    def __init__(self, scn: Scenario, journal_path: Optional[str]):
        import random

        from kubernetes_tpu.events import EventBroadcaster
        from kubernetes_tpu.framework.config import SchedulerConfiguration
        from kubernetes_tpu.scheduler import Scheduler
        from kubernetes_tpu.testing.fake_cluster import FakeCluster

        self.scn = scn
        self.rng = random.Random(scn.seed)
        self.plan = faults.FaultPlan(
            seed=scn.seed,
            rates=scn.rates,
            bind_delay_s=scn.bind_delay_s,
            # failover scenarios script the incumbent's blackout HERE so the
            # journal header (written at connect time) records it and replay
            # reconstructs the identical plan
            lease_blackout=("A", CLOCK0 + 6.0, 1e18)
            if scn.kind == "failover"
            else None,
        )
        self.journal = Journal(journal_path)
        self.clock = LogicalClock(CLOCK0)
        self.drain_no = 0
        self.created_uids: List[str] = []
        self.ledger = _BindLedger()
        self.api = FakeCluster(pv_controller=False)
        self.apiserver = None
        self.source = None
        self.client = None
        self.controller = None
        self.endpoint = None

        # deterministic mode pins the bind pool to one worker so delivery
        # order (bind confirmations) is a pure function of the seed
        conf = (
            SchedulerConfiguration(parallelism=1)
            if scn.mode == "inproc"
            else None
        )
        self.sched = Scheduler(
            configuration=conf,
            clock=self.clock,
            event_broadcaster=EventBroadcaster(),
        )
        self.recovery = _RecoveryTracker(self.sched.prom.chaos_recovery)
        journal = self.journal
        prom = self.sched.prom
        recovery = self.recovery

        def on_inject(kind, seam, key):
            prom.chaos_injected.inc(kind=kind)
            recovery.mark(kind)
            journal.append("fault", fault=kind, seam=seam, key=key)

        self.plan.on_inject = on_inject
        self.recorder = JournalRecorder(self.journal)

        # device-fault tier (ISSUE 15): when the plan carries device
        # kinds, install the injector into the DispatchLedger's chaos
        # hook for the scenario's duration (close() uninstalls) — the
        # same plan, so journal replay re-derives the schedule from the
        # header's seed alone
        self.device_injector = None
        if any(k in faults.DEVICE_KINDS for k in scn.rates):
            from kubernetes_tpu.chaos.device import DeviceFaultInjector, install

            self.device_injector = DeviceFaultInjector(self.plan)
            install(self.device_injector)

    # -- wiring --------------------------------------------------------------

    def connect(self) -> None:
        scn = self.scn
        self.journal.append(
            "header",
            version=JOURNAL_VERSION,
            scenario=scn.name,
            seed=scn.seed,
            rates=scn.rates,
            clock0=CLOCK0,
            sink_many=scn.mode == "http",
            lease_blackout=list(self.plan.lease_blackout)
            if self.plan.lease_blackout
            else None,
        )
        self.journal.append("clock", now=self.clock.now)
        self.recorder.attach(self.sched)
        # the scheduler's events land in the FakeCluster's events store
        # whichever tier is in between (process-local broadcaster)
        self.sched.event_broadcaster.start_recording_to_sink(self.api.record_event)
        if scn.mode == "http":
            from kubernetes_tpu.client import ApiClient, ApiServer, RemoteClusterSource

            self.apiserver = ApiServer(self.api).start()
            endpoint = f"http://127.0.0.1:{self.apiserver.port}"
            self.endpoint = endpoint
            self.client = ApiClient(endpoint, codec=scn.codec)  # clean driver
            chaos_client = ChaosClient(endpoint, self.plan, codec=scn.codec)
            self.source = RemoteClusterSource(endpoint, client=chaos_client)
            self.source.connect(self.sched)
            self.source.start()
        else:
            self.api.connect(self.sched)
        # chaos + ledger wrap whatever sink the tier installed
        self.sched.binding_sink = chaos_binding_sink(
            self.ledger.wrap(self.sched.binding_sink), self.plan
        )
        if self.sched.binding_sink_many is not None:
            self.sched.binding_sink_many = chaos_binding_sink_many(
                self.ledger.wrap_many(self.sched.binding_sink_many), self.plan
            )

    def close(self) -> None:
        if self.device_injector is not None:
            from kubernetes_tpu.chaos.device import install

            install(None)
            self.device_injector = None
        if self.controller is not None:
            self.controller.stop()
        if self.source is not None:
            self.source.stop()
        if self.apiserver is not None:
            self.apiserver.stop()

    # -- drive primitives ----------------------------------------------------

    def create_nodes(self, nodes: List[Node]) -> None:
        if self.client is not None:
            self.client.create_nodes(nodes)
        else:
            for n in nodes:
                self.api.create_node(n)

    def create_pods(self, pods: List[Pod]) -> None:
        self.created_uids.extend(p.uid for p in pods)
        if self.client is not None:
            self.client.create_pods(pods)
        else:
            for p in pods:
                self.api.create_pod(p)

    def advance(self, dt: float) -> None:
        self.clock.advance(dt)
        self.journal.append("clock", now=self.clock.now)

    def queue_counts(self) -> Dict[str, int]:
        with self.sched._mu:
            return self.sched.queue.stats()

    def wait_enqueued(self, timeout: float = 20.0) -> bool:
        """Quiesce: every created pod is visible to the scheduler — queued,
        assumed/bound in its cache, or gone from the API (evicted)."""

        def visible():
            with self.sched._mu:
                known = len(self.sched.cache.pod_states) + len(self.sched.queue)
            alive = sum(1 for uid in self.created_uids if uid in self.api.pods)
            return known >= alive

        return _wait(visible, timeout=timeout)

    def drain(self, sched=None, journaled: bool = True):
        """One journaled drain.  Correctness of the drain markers leans on
        the drive discipline around them: every drive QUIESCES first
        (wait_enqueued / explicit waits), so the only deliveries that can
        land between drain_start and drain_end are echoes of this drain's
        own binds — which never change placements and which replay
        correctly defers past the replayed drain.  drain_end needs no
        bind-thread synchronization: schedule_pending ends with
        wait_for_bindings, so all worker-side journal appends (fault
        fires, confirmations) happen-before the marker."""
        s = sched or self.sched
        if journaled:
            with s._mu:
                self.journal.append("drain_start", n=self.drain_no)
        outs = s.schedule_pending()
        if journaled:
            self.journal.append(
                "drain_end", n=self.drain_no, decisions=decisions_of(outs)
            )
            self.drain_no += 1
            counts = self.queue_counts()
            if counts.get("active", 0) == 0 and counts.get("backoff", 0) == 0:
                # the queue fully recovered from every open fault window
                self.recovery.drained()
        return outs

    def settle(self, rounds: int = 4) -> None:
        """Drain until nothing actionable remains: retried pods (bind
        faults, relist churn) re-pop after a clock advance, confirmations
        land, and the active/backoff queues go empty."""
        for _ in range(rounds):
            _wait(lambda: not self.sched.cache.assumed, timeout=10.0)
            counts = self.queue_counts()
            if counts.get("active", 0) == 0 and counts.get("backoff", 0) == 0:
                break
            self.advance(30.0)
            self.drain()
        _wait(lambda: not self.sched.cache.assumed, timeout=10.0)
        if self.scn.mode == "http":
            from kubernetes_tpu.server import CacheDebugger

            dbg = CacheDebugger(self.sched, ground_truth=self.api.ground_truth)
            _wait(lambda: not dbg.compare(), timeout=10.0)
        self.recovery.drained()


# ---------------------------------------------------------------------------
# invariant oracle
# ---------------------------------------------------------------------------


def check_invariants(ctx: _Ctx) -> List[str]:
    problems: List[str] = []
    sched, api = ctx.sched, ctx.api
    from kubernetes_tpu.server import CacheDebugger

    problems += CacheDebugger(sched, ground_truth=api.ground_truth).compare()
    with sched._mu:
        assumed = sorted(sched.cache.assumed)
    if assumed:
        problems.append(f"leaked assumed pods ({len(assumed)}): {assumed[:5]}")
    try:
        with sched._mu:
            sanitizer.check_mirror_consistency(sched.cache, sched.mirror)
    except AssertionError as e:
        problems.append(str(e))
    doubles = ctx.ledger.double_bound()
    if doubles:
        problems.append(f"pods bound to multiple nodes: {doubles[:5]}")
    failed = {
        e.regarding.uid for e in api.list_events("FailedScheduling")
    }
    for uid in ctx.created_uids:
        if uid in api.bindings:
            continue
        if uid not in api.pods:
            continue  # deleted (evicted / churned away)
        if uid in failed:
            continue
        problems.append(
            f"pod {uid} neither bound, deleted, nor FailedScheduling-evented"
        )
    counts = ctx.queue_counts()
    stuck = counts.get("active", 0) + counts.get("backoff", 0)
    if stuck:
        problems.append(f"drain did not converge: {counts}")
    return problems


# ---------------------------------------------------------------------------
# drives
# ---------------------------------------------------------------------------


def _drive_basic(ctx: _Ctx) -> None:
    scn = ctx.scn
    ctx.create_nodes(_mk_nodes(scn.n_nodes))
    if ctx.source is not None:
        ctx.source.wait_for_sync()
    per_round = max(1, scn.n_pods // scn.rounds)
    made = 0
    for r in range(scn.rounds):
        n = per_round if r < scn.rounds - 1 else scn.n_pods - made
        pods = []
        for i in range(made, made + n):
            hopeless = i < scn.unschedulable
            pods.append(
                _mk_synthetic_pod(i, ctx.rng)
                if scn.synthetic and not hopeless
                else _mk_pod(
                    i, ctx.rng, unschedulable=hopeless, spread=scn.spread
                )
            )
        made += n
        ctx.create_pods(pods)
        ctx.wait_enqueued()
        ctx.advance(1.0)
        ctx.drain()
    ctx.settle()


def _drive_flap(ctx: _Ctx) -> None:
    """Heartbeat suppression: the NodeLifecycleController (own client +
    reflectors against the same API server) marks the victim NotReady,
    taints it NoExecute, and evicts its pods; replacements reschedule on
    healthy nodes; the heartbeat returns and the taint lifts."""
    from kubernetes_tpu.controller.node_lifecycle import NodeLifecycleController

    scn = ctx.scn
    nodes = _mk_nodes(scn.n_nodes)
    ctx.create_nodes(nodes)
    ctx.source.wait_for_sync()
    names = [n.name for n in nodes]
    victim = ctx.plan.flap_targets(names, k=1)[0]
    ctrl = ctx.controller = NodeLifecycleController(
        ctx.endpoint,
        grace_s=scn.flap_grace_s,
        clock=ctx.clock,
        chaos_client=ChaosClient(ctx.endpoint, ctx.plan),
    )
    ctrl.start(run_loop=False)  # runner ticks it deterministically
    ctrl.wait_for_sync()

    def heartbeat(skip=()):
        for name in names:
            if name not in skip:
                ctx.client.patch_node_status(name, True, ctx.clock.now)

    heartbeat()
    pods = [_mk_pod(i, ctx.rng) for i in range(scn.n_pods)]
    ctx.create_pods(pods)
    ctx.wait_enqueued()
    ctx.advance(1.0)
    ctx.drain()
    _wait(lambda: not ctx.sched.cache.assumed, timeout=10.0)

    # --- flap: suppress the victim's heartbeat past the grace period ------
    ctx.plan.fire(faults.NODE_FLAP, "heartbeat", victim)
    ctx.advance(scn.flap_grace_s + 2.0)
    heartbeat(skip=(victim,))
    evicted_before = ctrl.evicted
    _wait(lambda: (ctrl.tick() or True) and victim in ctrl.tainted, timeout=15.0)
    # eviction storms through the controller's client; wait for the watch
    # to carry the deletes back to the scheduler
    _wait(
        lambda: all(
            uid not in ctx.api.pods
            for uid, node in list(ctx.api.bindings.items())
            if node == victim
        ),
        timeout=15.0,
    )
    evicted = ctrl.evicted - evicted_before

    # a workload controller recreates evicted pods as pending replacements
    gone = [uid for uid in ctx.created_uids if uid not in ctx.api.pods]
    replacements = []
    for j, uid in enumerate(sorted(gone)):
        p = _mk_pod(scn.n_pods + j, ctx.rng)
        replacements.append(p)
    if replacements:
        ctx.create_pods(replacements)
        ctx.wait_enqueued()
    ctx.advance(1.0)
    ctx.drain()

    # --- recovery: the kubelet comes back, the taint lifts -----------------
    heartbeat()
    _wait(
        lambda: (ctrl.tick() or True) and victim not in ctrl.tainted, timeout=15.0
    )
    ctx.settle()
    ctx.evicted = evicted


def _drive_failover(ctx: _Ctx) -> None:
    """Two electors over one chaos lease store: A leads and schedules;
    a scripted blackout (plus seeded contention) lapses A's lease; B —
    whose clock the plan skews — takes over within the lease budget.  The
    journal tracks scheduler B, the takeover side."""
    from kubernetes_tpu.events import EventBroadcaster
    from kubernetes_tpu.framework.config import SchedulerConfiguration
    from kubernetes_tpu.scheduler import Scheduler
    from kubernetes_tpu.server import LeaseElector

    scn = ctx.scn
    ctx.create_nodes(_mk_nodes(scn.n_nodes))

    # scheduler A: the incumbent (not journaled; its binds reach B's
    # journal as deliveries through the shared store)
    clock_a = LogicalClock(CLOCK0)
    sched_a = Scheduler(
        configuration=SchedulerConfiguration(parallelism=1),
        clock=clock_a,
        event_broadcaster=EventBroadcaster(),
    )
    sched_a.event_broadcaster.start_recording_to_sink(ctx.api.record_event)
    ctx.api.connect(sched_a)
    sched_a.binding_sink = chaos_binding_sink(
        ctx.ledger.wrap(sched_a.binding_sink), ctx.plan
    )

    skew = ctx.plan.clock_skew_s("B")
    ctx.plan.fire(faults.CLOCK_SKEW, "elector", f"B:{skew:+.3f}")
    clock_b = ctx.clock  # B is the journaled scheduler — shares ctx clock
    clock_b.now = CLOCK0 + skew
    ctx.journal.append("clock", now=clock_b.now)

    assert ctx.plan.lease_blackout is not None  # scripted at plan build
    el_a = LeaseElector(
        ChaosLeaseStore(ctx.api.lease_store, ctx.plan, clock=clock_a),
        "A",
        lease_duration_s=scn.lease_duration_s,
        retry_period_s=1.0,
        clock=clock_a,
    )
    el_b = LeaseElector(
        ChaosLeaseStore(ctx.api.lease_store, ctx.plan, clock=clock_b),
        "B",
        lease_duration_s=scn.lease_duration_s,
        retry_period_s=1.0,
        clock=clock_b,
    )

    def tick(dt: float = 1.0):
        clock_a.advance(dt)
        ctx.advance(dt)
        a = el_a.try_acquire_or_renew()
        b = el_b.try_acquire_or_renew()
        return a, b

    assert el_a.try_acquire_or_renew(), "A failed to acquire an empty lease"
    assert not el_b.try_acquire_or_renew(), "standby stole a held lease"

    # phase 1: A leads and drains — TO COMPLETION, so a pod whose bind
    # chaos-conflicted under A retries and lands before the handoff (the
    # one-shot bind-fault ledger would otherwise desync replay, which
    # re-draws B's faults from a fresh plan)
    half = scn.n_pods // 2
    pods = [_mk_pod(i, ctx.rng) for i in range(half)]
    ctx.create_pods(pods)
    ctx.advance(1.0)
    for _ in range(4):
        ctx.drain(sched=sched_a, journaled=False)
        sched_a.wait_for_bindings()
        if all(p.uid in ctx.api.bindings for p in pods):
            break
        clock_a.advance(30.0)
    assert all(p.uid in ctx.api.bindings for p in pods), (
        "incumbent failed to settle its half before the handoff"
    )

    # phase 2: blackout — A's renewals lose until its lease lapses for B.
    # The STALL is the leaderless window: from the tick A's lease expired
    # (it stops scheduling) to B's acquisition, on B's clock.
    deposed_at: Optional[float] = None
    took_over = False
    for _ in range(int(scn.lease_duration_s + 8)):
        a, b = tick(1.0)
        assert not (el_a.is_leader() and el_b.is_leader()), "two leaders"
        if deposed_at is None and not el_a.is_leader():
            deposed_at = clock_b.now
        if b and el_b.is_leader():
            took_over = True
            break
    assert took_over, "standby never took over after the lease blackout"
    stall = clock_b.now - (deposed_at if deposed_at is not None else clock_b.now)
    ctx.failover_stall_s = stall

    # phase 3: B schedules the rest; A must schedule nothing more
    pods = [_mk_pod(half + i, ctx.rng) for i in range(scn.n_pods - half)]
    ctx.create_pods(pods)
    ctx.advance(1.0)
    assert not el_a.is_leader(), "deposed leader still claims the lease"
    ctx.drain()
    ctx.settle()


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def run_scenario(
    scn, journal_path: Optional[str] = None, progress=None
) -> ScenarioResult:
    if isinstance(scn, str):
        scn = SCENARIOS[scn]
    ctx = _Ctx(scn, journal_path)
    ctx.evicted = 0
    ctx.failover_stall_s = None
    t0 = time.perf_counter()
    try:
        ctx.connect()
        if scn.kind == "flap":
            _drive_flap(ctx)
        elif scn.kind == "failover":
            _drive_failover(ctx)
        else:
            _drive_basic(ctx)
        problems = check_invariants(ctx)
        if scn.kind == "failover":
            budget = scn.lease_duration_s + 3.0
            if ctx.failover_stall_s is None or ctx.failover_stall_s > budget:
                problems.append(
                    f"leader failover stall {ctx.failover_stall_s} exceeds "
                    f"budget {budget}"
                )
    finally:
        ctx.close()
    wall = time.perf_counter() - t0
    if journal_path:
        ctx.journal.dump()
    if progress:
        progress(
            f"{scn.name}: {len(ctx.api.bindings)} bound, "
            f"{sum(ctx.plan.injected_counts().values())} faults, "
            f"{len(problems)} problems, {wall:.2f}s"
        )
    return ScenarioResult(
        scenario=scn.name,
        seed=scn.seed,
        problems=problems,
        placements=dict(ctx.api.bindings),
        injected=ctx.plan.injected_counts(),
        journal=ctx.journal,
        created=len(ctx.created_uids),
        wall_s=wall,
        failover_stall_s=ctx.failover_stall_s,
        evicted=ctx.evicted,
    )


def run_chaos_soak(
    n_nodes: int = 24,
    n_pods: int = 600,
    rounds: int = 4,
    seed: int = 2026,
    fault_rate: float = 0.15,
    device_fault_rate: float = 0.0,
    codec: str = "binary",
    hollow_nodes: int = 0,
    progress=None,
):
    """The bench's config7 shape: a fixed-rate mixed-fault soak over the
    HTTP tier; reports throughput under chaos + recovery latency.  A
    nonzero ``device_fault_rate`` folds the device seams in (the bench's
    config15 shape: degraded-mode throughput with per-kernel breakers and
    epoch-guarded resync absorbing dispatch faults) — spread pods force
    every batch onto a device dispatch so the seams have a stream.

    ``codec`` selects the wire format for every http-tier client in the
    soak, and a nonzero ``hollow_nodes`` runs a kubemark HollowFleet
    against the same apiserver (extra heartbeat + pods-watch load riding
    the frames under fault injection — the config17 wire-soak shape)."""
    rates = {
        faults.WATCH_CUT: fault_rate / 10,
        faults.COMPACT: fault_rate / 10,
        faults.API_ERROR: fault_rate / 2,
        faults.API_TIMEOUT: fault_rate / 2,
        faults.BIND_CONFLICT: fault_rate / 2,
        faults.BIND_SLOW: fault_rate / 2,
    }
    if device_fault_rate > 0:
        rates.update(
            {
                faults.DISPATCH_ERROR: device_fault_rate / 2,
                faults.DISPATCH_HANG: device_fault_rate / 4,
                faults.POISONED_OUTPUT: device_fault_rate / 2,
                faults.HBM_OOM: device_fault_rate / 4,
                faults.MESH_DEVICE_LOSS: device_fault_rate / 10,
            }
        )
    scn = Scenario(
        name="bench-soak",
        seed=seed,
        mode="http",
        n_nodes=n_nodes,
        n_pods=n_pods,
        rounds=rounds,
        unschedulable=0,
        spread=device_fault_rate > 0,
        rates=rates,
        codec=codec,
    )
    ctx = _Ctx(scn, None)
    ctx.evicted = 0
    ctx.failover_stall_s = None
    fleet = None
    t0 = time.perf_counter()
    try:
        ctx.connect()
        if hollow_nodes > 0:
            from kubernetes_tpu.kubemark import HollowFleet

            # adopt (don't register) — _drive_basic registers the same
            # node names through the driver client; the fleet's agents
            # just heartbeat them and report bound pods Running, adding
            # kubelet-shaped wire load on top of the fault stream
            fleet = HollowFleet(ctx.endpoint, heartbeat_interval_s=1.0, codec=codec)
            fleet.adopt(_mk_nodes(min(hollow_nodes, n_nodes)))
            fleet.start()
        _drive_basic(ctx)
        problems = check_invariants(ctx)
    finally:
        if fleet is not None:
            fleet.stop()
        ctx.close()
    wall = time.perf_counter() - t0
    bound = len(ctx.api.bindings)
    hist = ctx.sched.prom.chaos_recovery
    # percentile returns +Inf when the rank lands in the overflow bucket;
    # the soak JSON wants a finite number, so clamp EXPLICITLY to the top
    # bound here (a recovery slower than the last bucket is reported as
    # "at least that slow" — the sentinel made the choice visible)
    p99 = hist.percentile(0.99)
    if math.isinf(p99):
        p99 = hist.buckets[-1]
    kstats = ctx.sched.kernels.stats()
    out = {
        "pods_per_s": bound / max(wall, 1e-9),
        "bound": bound,
        "wall_s": wall,
        "injected_total": sum(ctx.plan.injected_counts().values()),
        "injected": ctx.plan.injected_counts(),
        "recovery_p99_s": p99,
        "breaker_trips": kstats["breaker_trips"],
        "problems": problems,
        "codec": codec,
        "hollow_nodes": hollow_nodes,
    }
    if progress:
        progress(
            f"chaos soak: {bound} bound in {wall:.2f}s "
            f"({out['pods_per_s']:.1f} pods/s, "
            f"{out['injected_total']} faults, recovery p99 "
            f"{out['recovery_p99_s'] * 1000:.1f}ms, {len(problems)} problems)"
        )
    return out
