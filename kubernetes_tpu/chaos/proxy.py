"""Injection wrappers at the transport seams.

The chaos subsystem never mocks the scheduler's machinery — it wraps the
real seams so the real reflector, informer-diff, relist, bind-unwind, and
election code paths absorb the faults:

  * ``ChaosClient`` — an ``ApiClient`` whose REST calls and watch streams
    consult a ``FaultPlan``: transport errors/timeouts on requests, EOF
    cuts and forced 410 compactions mid-watch-stream;
  * ``chaos_binding_sink`` / ``chaos_binding_sink_many`` — binding-sink
    wrappers injecting 409 conflicts and slow binds keyed by pod uid
    (one-shot, so the post-unwind retry converges);
  * ``ChaosLeaseStore`` — a LeaseStore proxy whose CAS loses on plan
    demand (lease contention / scripted blackouts driving failover).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from kubernetes_tpu.chaos import faults
from kubernetes_tpu.client.client import ApiClient, ApiError

# Lock-discipline registry (kubernetes_tpu.analysis reads this literal):
# the per-seam ordinal counters are bumped from reflector threads and
# binding workers concurrently.
_KTPU_GUARDED = {
    "ChaosClient": {
        "lock": "_chaos_mu",
        "guards": {"_chaos_seq": None},
    },
    "ChaosLeaseStore": {
        "lock": "_attempts_mu",
        "guards": {"_attempts": None},
    },
}


class ChaosClient(ApiClient):
    """ApiClient with plan-driven transport faults.

    Faults raised here surface exactly like real infrastructure failures:
    a ``ConnectionResetError``/``TimeoutError`` from ``_req`` reaches the
    reflector's reconnect-with-backoff loop (or the caller's error path),
    and a mid-stream cut/410 reaches the reflector's EOF/relist handling.
    """

    def __init__(
        self,
        endpoint: str,
        plan: faults.FaultPlan,
        timeout: float = 10.0,
        watch_timeout: Optional[float] = None,
        codec: str = "binary",
    ):
        super().__init__(
            endpoint, timeout=timeout, watch_timeout=watch_timeout, codec=codec
        )
        self.plan = plan
        self._chaos_mu = threading.Lock()
        self._chaos_seq = {}

    def _seq(self, key: str) -> int:
        with self._chaos_mu:
            n = self._chaos_seq.get(key, 0)
            self._chaos_seq[key] = n + 1
            return n

    def _req(self, method: str, path: str, payload=None):
        family = path.split("?", 1)[0]
        fault = self.plan.req_fault(method, family, self._seq(f"{method} {family}"))
        if fault == faults.API_ERROR:
            self.plan.fire(fault, f"req:{method}:{family}", family)
            raise ConnectionResetError(
                f"chaos: injected transport error on {method} {family}"
            )
        if fault == faults.API_TIMEOUT:
            self.plan.fire(fault, f"req:{method}:{family}", family)
            raise TimeoutError(f"chaos: injected timeout on {method} {family}")
        return super()._req(method, path, payload)

    def watch_stream(self, resource: str, rv: int):
        stream_no = self._seq(f"watch {resource}")
        n = 0
        for evt in super().watch_stream(resource, rv):
            if evt.get("type") == "BOOKMARK":
                # a timing artifact (idle-interval keepalive), not a
                # delivery — never burns a fault ordinal, so the fault
                # sequence is a function of the event stream alone
                # (identical across wire codecs and idle-gap jitter)
                yield evt
                continue
            kind = self.plan.watch_event_fault(resource, stream_no, n)
            if kind is not None:
                self.plan.fire(kind, f"watch:{resource}", f"{stream_no}:{n}")
                if kind == faults.COMPACT:
                    # the server's own compaction shape: the reflector
                    # must relist and diff
                    raise ApiError(410, "chaos: forced compaction")
                return  # WATCH_CUT: EOF mid-stream → re-list/watch
            yield evt
            n += 1


def chaos_binding_sink(sink, plan: faults.FaultPlan, sleep=time.sleep):
    """Wrap a per-pod binding sink with plan-driven 409s / stalls."""

    def bind(pod, node_name):
        kind = plan.bind_fault(pod.uid)
        if kind == faults.BIND_CONFLICT:
            plan.fire(kind, "bind", pod.uid)
            raise ApiError(409, f"chaos: conflicting bind for {pod.uid}")
        if kind == faults.BIND_SLOW:
            plan.fire(kind, "bind", pod.uid)
            sleep(plan.bind_delay_s)
        return sink(pod, node_name)

    return bind


def chaos_binding_sink_many(sink_many, plan: faults.FaultPlan, sleep=time.sleep):
    """Wrap a bulk binding sink; injected conflicts surface as the per-item
    error strings the API tier's /bindings endpoint produces, so the
    scheduler unwinds exactly the faulted pods and commits the rest."""

    def bind_many(pairs) -> List[Optional[str]]:
        results: List[Optional[str]] = [None] * len(pairs)
        todo, idxs = [], []
        stalled = False
        for i, (pod, node_name) in enumerate(pairs):
            kind = plan.bind_fault(pod.uid)
            if kind == faults.BIND_CONFLICT:
                plan.fire(kind, "bind", pod.uid)
                results[i] = f"HTTP 409: chaos: conflicting bind for {pod.uid}"
                continue
            if kind == faults.BIND_SLOW:
                plan.fire(kind, "bind", pod.uid)
                stalled = True
            todo.append((pod, node_name))
            idxs.append(i)
        if stalled:
            sleep(plan.bind_delay_s)
        if todo:
            errs = sink_many(todo)
            for i, err in zip(idxs, errs):
                results[i] = err
        return results

    return bind_many


class ChaosLeaseStore:
    """LeaseStore proxy whose updates lose the CAS on plan demand —
    contention from a phantom competitor, or a scripted blackout window
    that forces the holder to lapse (leader failover)."""

    def __init__(self, store, plan: faults.FaultPlan, clock=time.monotonic):
        self.store = store
        self.plan = plan
        self.clock = clock
        self._attempts = {}
        self._attempts_mu = threading.Lock()

    def get(self, name: str):
        return self.store.get(name)

    def update(self, name: str, rec) -> bool:
        with self._attempts_mu:
            attempt = self._attempts.get(rec.holder, 0)
            self._attempts[rec.holder] = attempt + 1
        if self.plan.lease_fault(rec.holder, attempt, self.clock()):
            self.plan.fire(
                faults.LEASE_CONTENTION, f"lease:{rec.holder}", attempt
            )
            return False
        return self.store.update(name, rec)
