"""Device-tier fault injection (ISSUE 15: dispatch-boundary chaos).

The control-plane seams (proxy.py) fault transports, sinks, and leases;
this module faults the DEVICE tier — the jit roots themselves — at the
choke points the DispatchLedger already owns (observability/kernels.py
wraps every registered root) plus the two host↔device edges the ledger
doesn't call through: ``Scheduler._d2h`` readbacks and the
``DeviceClusterCache.sync`` snapshot placement.

  * ``dispatch_error`` — a backend ``RuntimeError`` raised from a chosen
    jit root before the kernel runs (the jaxlib INTERNAL-error shape);
  * ``dispatch_hang``  — the dispatch stalls past the ledger's watchdog
    deadline (the hung-collective shape: the result still arrives, but
    the breaker books the stall as a failure — you cannot preempt an XLA
    dispatch, so detection-on-return is the honest model);
  * ``poisoned_output`` — a guarded readback's host copy is overwritten
    with NaN (floats) / out-of-range sentinels (ints); the harvest-side
    validator rejects it and re-fetches (the device array was never
    corrupted, so the retry heals — and a REAL non-finite kernel output
    keeps failing and routes to the fallback engine);
  * ``hbm_oom``       — the resident-snapshot donation/placement fails
    (RESOURCE_EXHAUSTED), forcing the rebuild-from-mirror path;
  * ``mesh_device_loss`` — a device drops from the mesh: the next
    multichip dispatch fails and ``Scheduler._degrade_mesh`` re-forms a
    smaller mesh (or single-chip) with the same parity guarantee.

Draw discipline matches faults.py exactly: stateless
``(seed, kind, seam, key)`` hashing with per-seam ATTEMPT ordinals as
keys — the scheduling loop sequences dispatches, so the ordinals (and
therefore the entire fault schedule) are a pure function of the seed,
and journal replay re-derives it from the header alone.  The injector
installs into the ledger's module-global hook
(``kernels.set_fault_injector``): the hot path never imports chaos.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple

import numpy as np

from kubernetes_tpu.chaos import faults

# Lock-discipline registry (kubernetes_tpu.analysis reads this literal):
# per-seam attempt ordinals are bumped from the scheduling loop, HTTP
# planner handlers, and harvest paths concurrently.
_KTPU_GUARDED = {
    "DeviceFaultInjector": {
        "lock": "_mu",
        "guards": {"_ordinals": None},
    },
}

# the int sentinel poisoned readbacks write — far outside any legal
# node/choice/count range, so range validators always catch it
POISON_I32 = np.iinfo(np.int32).min


class DeviceFaultError(RuntimeError):
    """An injected device fault (shaped like the jaxlib failure class it
    models).  ``kind`` is the faults.py vocabulary entry; the ledger's
    breaker reads it to pick retry semantics (an error injected BEFORE
    the kernel ran retries in place — the args are intact; a mesh loss
    does not — the mesh must re-form first)."""

    def __init__(self, kind: str, kernel: str, msg: str):
        super().__init__(msg)
        self.kind = kind
        self.kernel = kernel


class DeviceFaultInjector:
    """Seeded device-fault schedule over a FaultPlan.

    ``hang_s`` is the stall an injected ``dispatch_hang`` sleeps — kept
    tiny (the breaker verdict is what matters, not the wall time; the
    chaos contract DEFINES the stall as past the watchdog deadline, so
    the ledger books the failure without racing a real clock).  Replay
    passes ``hang_s=0`` the same way it skips bind-delay sleeps.
    """

    def __init__(self, plan: faults.FaultPlan, hang_s: float = 0.02):
        self.plan = plan
        self.hang_s = hang_s
        self._mu = threading.Lock()
        self._ordinals: Dict[str, int] = {}

    def _next(self, seam: str) -> int:
        with self._mu:
            n = self._ordinals.get(seam, 0)
            self._ordinals[seam] = n + 1
            return n

    # -- seam: jit-root dispatch (the _LedgerRoot wrapper) -------------------

    def dispatch_fault(self, kernel: str) -> Optional[str]:
        """Draw for the next dispatch attempt of ``kernel``; fires the
        plan's injection record when a fault is delivered.  Returns the
        kind (the ledger raises/stalls accordingly) or None."""
        attempt = self._next(f"dispatch:{kernel}")
        kind = self.plan.dispatch_fault(kernel, attempt)
        if kind is not None:
            self.plan.fire(kind, f"dispatch:{kernel}", attempt)
        return kind

    def raise_for(self, kind: str, kernel: str) -> None:
        """Materialize a drawn dispatch fault as the backend error it
        models (hangs don't raise — the ledger stalls and books them)."""
        if kind == faults.MESH_DEVICE_LOSS:
            raise DeviceFaultError(
                kind,
                kernel,
                f"INTERNAL: device lost from mesh during {kernel} "
                "(chaos mesh_device_loss)",
            )
        raise DeviceFaultError(
            kind,
            kernel,
            f"INTERNAL: Failed to execute XLA computation {kernel} "
            "(chaos dispatch_error)",
        )

    # -- seam: guarded readback (Scheduler._d2h) -----------------------------

    def poison(self, kernel: str, fetched) -> Tuple[object, bool]:
        """Maybe corrupt one guarded fetch's HOST copy: floats → NaN,
        signed ints → POISON_I32 (out of every legal range).  The device
        array is untouched — a re-fetch reads clean data, which is
        exactly the one-shot-per-attempt healing the breaker's bounded
        retry leans on."""
        attempt = self._next(f"d2h:{kernel}")
        kind = self.plan.readback_fault(kernel, attempt)
        if kind is None:
            return fetched, False
        self.plan.fire(kind, f"d2h:{kernel}", attempt)
        import jax

        def corrupt(leaf):
            if not isinstance(leaf, np.ndarray) or leaf.size == 0:
                return leaf
            out = np.array(leaf)  # writable copy; the original may be a view
            if np.issubdtype(out.dtype, np.floating):
                out.flat[0] = np.nan
            elif np.issubdtype(out.dtype, np.signedinteger):
                out.flat[0] = np.asarray(POISON_I32, out.dtype)
            return out

        return jax.tree_util.tree_map(corrupt, fetched), True

    # -- seam: resident snapshot placement (DeviceClusterCache.sync) ---------

    def sync_fault(self) -> Optional[str]:
        """Draw for the next snapshot donation/placement; raises inside
        the caller as RESOURCE_EXHAUSTED when it fires."""
        attempt = self._next("hbm:sync")
        kind = self.plan.hbm_fault(attempt)
        if kind is not None:
            self.plan.fire(kind, "hbm:sync", attempt)
        return kind


def install(injector: Optional[DeviceFaultInjector]) -> None:
    """Route the ledger's (and _d2h's / sync's) chaos hook through
    ``injector`` — None uninstalls.  Process-global, like the ledger's
    root wrappers; the chaos runner installs for the scenario's duration
    and uninstalls in a finally."""
    from kubernetes_tpu.observability import kernels as kernels_mod

    kernels_mod.set_fault_injector(injector)
