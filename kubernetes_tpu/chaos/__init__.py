"""Chaos & replay: deterministic fault injection + journal replay.

The subsystem that drives the control plane's failure machinery instead of
waiting for production to: seeded fault plans (``faults``), injection
wrappers at the real transport seams (``proxy``), a logical-time journal
with a bit-for-bit replayer (``journal``), and a scenario soak runner with
a post-run invariant oracle (``runner``).  See CHAOS.md for the fault
vocabulary and the record/replay workflow.

    python -m kubernetes_tpu.chaos --scenario mixed-soak --journal /tmp/j.jsonl
    python -m kubernetes_tpu.chaos --replay /tmp/j.jsonl
"""

from kubernetes_tpu.chaos.device import (
    DeviceFaultError,
    DeviceFaultInjector,
    install as install_device_faults,
)
from kubernetes_tpu.chaos.faults import (
    ALL_KINDS,
    DEVICE_KINDS,
    FaultPlan,
    Injection,
)
from kubernetes_tpu.chaos.journal import (
    Journal,
    JournalRecorder,
    LogicalClock,
    ReplayResult,
    replay,
)
from kubernetes_tpu.chaos.proxy import (
    ChaosClient,
    ChaosLeaseStore,
    chaos_binding_sink,
    chaos_binding_sink_many,
)
from kubernetes_tpu.chaos.runner import (
    SCENARIOS,
    Scenario,
    ScenarioResult,
    check_invariants,
    run_chaos_soak,
    run_scenario,
)

__all__ = [
    "ALL_KINDS",
    "DEVICE_KINDS",
    "DeviceFaultError",
    "DeviceFaultInjector",
    "install_device_faults",
    "FaultPlan",
    "Injection",
    "Journal",
    "JournalRecorder",
    "LogicalClock",
    "ReplayResult",
    "replay",
    "ChaosClient",
    "ChaosLeaseStore",
    "chaos_binding_sink",
    "chaos_binding_sink_many",
    "SCENARIOS",
    "Scenario",
    "ScenarioResult",
    "check_invariants",
    "run_chaos_soak",
    "run_scenario",
]
