"""Seeded, fully deterministic fault plans for the chaos subsystem.

A ``FaultPlan`` answers one question at every injection seam: *does this
operation fail, and how?*  Decisions are pure functions of
``(seed, kind, seam, key)`` via a keyed blake2b hash — NOT a shared PRNG
stream — so two threads racing through the same seam draw the same
verdict for the same key regardless of interleaving, and a replay run
re-derives the exact fault sequence from the journal header's seed alone.

Keys are chosen for stability under concurrency: bind faults key on the
pod UID (worker threads race, UIDs don't), watch faults on the per-stream
reconnect ordinal, request faults on a per-(method, path-family) counter.

Semantics that keep chaotic runs convergent:

  * bind faults are ONE-SHOT per pod — the retry after the unwind/requeue
    succeeds, exactly like a real 409 whose conflicting writer went away;
  * request/watch faults re-draw per attempt, so a seam with rate r heals
    with probability (1 - r) on every retry;
  * a scripted ``lease_blackout`` window suppresses one holder's lease
    CAS between two logical times (the deterministic way to force a
    leader failover mid-scenario).

Every fault that actually fires is appended to ``injections`` (the
journal/metrics feed) under the plan lock.
"""

from __future__ import annotations

import hashlib
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

# ----- fault vocabulary ------------------------------------------------------

WATCH_CUT = "watch_cut"  # watch stream EOF mid-stream
COMPACT = "compact"  # forced compaction: 410 Gone → relist
API_ERROR = "api_error"  # transport error on a REST call
API_TIMEOUT = "api_timeout"  # request timeout on a REST call
BIND_CONFLICT = "bind_conflict"  # binding sink 409 conflict
BIND_SLOW = "bind_slow"  # slow bind (sink stalls before writing)
NODE_FLAP = "node_flap"  # heartbeat suppression → NotReady → evict
LEASE_CONTENTION = "lease_contention"  # lease CAS loses → leader failover
CLOCK_SKEW = "clock_skew"  # elector clock offset (failover scenarios)

# ----- device-tier seams (ISSUE 15; injected at the DispatchLedger's
# choke points — observability/kernels.py — and Scheduler._d2h) ----------
DISPATCH_ERROR = "dispatch_error"  # backend RuntimeError from a jit root
DISPATCH_HANG = "dispatch_hang"  # dispatch stalls past the watchdog
POISONED_OUTPUT = "poisoned_output"  # NaN/out-of-range on readback
HBM_OOM = "hbm_oom"  # resident-state donation/placement fails
MESH_DEVICE_LOSS = "mesh_device_loss"  # device drops from the mesh

DEVICE_KINDS = (
    DISPATCH_ERROR,
    DISPATCH_HANG,
    POISONED_OUTPUT,
    HBM_OOM,
    MESH_DEVICE_LOSS,
)

ALL_KINDS = (
    WATCH_CUT,
    COMPACT,
    API_ERROR,
    API_TIMEOUT,
    BIND_CONFLICT,
    BIND_SLOW,
    NODE_FLAP,
    LEASE_CONTENTION,
    CLOCK_SKEW,
) + DEVICE_KINDS

# Lock-discipline registry (kubernetes_tpu.analysis reads this literal):
# the injection log and one-shot ledger are appended from binding workers,
# reflector threads, and the scenario driver concurrently.
_KTPU_GUARDED = {
    "FaultPlan": {
        "lock": "_mu",
        "guards": {"injections": None, "_fired": None},
    },
}


def _draw(seed: int, kind: str, seam: str, key) -> float:
    """Deterministic uniform [0, 1) from (seed, kind, seam, key)."""
    h = hashlib.blake2b(
        f"{seed}|{kind}|{seam}|{key}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(h, "big") / 2.0**64


@dataclass
class Injection:
    """One fault that actually fired (the journal/metrics record)."""

    kind: str
    seam: str
    key: str


class FaultPlan:
    """Deterministic fault schedule over the vocabulary above.

    ``rates`` maps fault kind → probability per draw; kinds absent from the
    map never fire.  ``on_inject(kind, seam, key)`` is the observer hook the
    runner wires to the chaos metrics counter and the journal.
    ``lease_blackout`` is a scripted (holder, t_from, t_to) window during
    which that holder's lease CAS always loses; ``watch_fault_after`` is
    how many events a doomed watch stream delivers before its fault (a cut
    at event 0 would just look like a failed connect).
    """

    def __init__(
        self,
        seed: int,
        rates: Optional[Dict[str, float]] = None,
        bind_delay_s: float = 0.01,
        watch_fault_after: int = 4,
        lease_blackout: Optional[Tuple[str, float, float]] = None,
        on_inject=None,
    ):
        self.seed = seed
        self.rates = dict(rates or {})
        unknown = set(self.rates) - set(ALL_KINDS)
        if unknown:
            raise ValueError(f"unknown fault kinds: {sorted(unknown)}")
        self.bind_delay_s = bind_delay_s
        self.watch_fault_after = watch_fault_after
        self.lease_blackout = lease_blackout
        self.on_inject = on_inject
        self.injections: List[Injection] = []
        self._mu = threading.Lock()
        self._fired: set = set()

    # ----- core draws -------------------------------------------------------

    def _roll(self, kind: str, seam: str, key) -> bool:
        rate = self.rates.get(kind, 0.0)
        return rate > 0.0 and _draw(self.seed, kind, seam, key) < rate

    def fire(self, kind: str, seam: str, key) -> None:
        """Record a fault that is actually being delivered."""
        hook = self.on_inject
        with self._mu:
            self.injections.append(Injection(kind, seam, str(key)))
        if hook is not None:
            hook(kind, seam, str(key))

    def injected_counts(self) -> Dict[str, int]:
        with self._mu:
            out: Dict[str, int] = {}
            for inj in self.injections:
                out[inj.kind] = out.get(inj.kind, 0) + 1
            return out

    # ----- seam: binding sink (key = pod uid, one-shot) ---------------------

    def bind_fault(self, uid: str) -> Optional[str]:
        """Conflict beats slow; fires at most once per pod so the requeued
        retry converges (returns the kind WITHOUT recording — callers fire()
        at the moment the fault is delivered)."""
        for kind in (BIND_CONFLICT, BIND_SLOW):
            if self._roll(kind, "bind", uid):
                with self._mu:
                    if ("bind", uid) in self._fired:
                        return None
                    self._fired.add(("bind", uid))
                return kind
        return None

    # ----- seam: REST requests (key = per-family attempt ordinal) -----------

    def req_fault(self, method: str, family: str, attempt: int) -> Optional[str]:
        """Transport fault for REST attempt #attempt on (method, family).
        Binding endpoints are exempt — bind failures are injected at the
        sink seam (keyed by pod uid) so journal replay, which has no REST
        tier, reproduces the identical bind-failure sequence."""
        if "binding" in family or family.endswith("/bindings"):
            return None
        seam = f"req:{method}:{family}"
        for kind in (API_ERROR, API_TIMEOUT):
            if self._roll(kind, seam, attempt):
                return kind
        return None

    # ----- seam: watch streams (key = per-resource stream ordinal) ----------

    def watch_event_fault(
        self, resource: str, stream_no: int, event_no: int
    ) -> Optional[str]:
        """Per-delivered-event draw on stream #stream_no of a resource:
        the configured rate is a PER-EVENT hazard, so every active stream
        eventually faults at rate-proportional intervals (a per-stream
        draw could leave a lucky stream — and therefore the whole run —
        fault-free).  The first ``watch_fault_after`` events of each
        stream are exempt; sync itself is never at risk because the
        reflector relists BEFORE each watch opens."""
        if event_no < self.watch_fault_after:
            return None
        seam = f"watch:{resource}:{stream_no}"
        for kind in (COMPACT, WATCH_CUT):
            if self._roll(kind, seam, event_no):
                return kind
        return None

    # ----- seam: lease CAS (key = holder + attempt, plus blackout) ----------

    def lease_fault(self, holder: str, attempt: int, now: float) -> bool:
        blackout = self.lease_blackout
        if (
            blackout is not None
            and holder == blackout[0]
            and blackout[1] <= now < blackout[2]
        ):
            return True
        return self._roll(LEASE_CONTENTION, f"lease:{holder}", attempt)

    # ----- seam: device dispatches (key = per-kernel attempt ordinal) -------

    def dispatch_fault(self, kernel: str, attempt: int) -> Optional[str]:
        """Device fault for dispatch attempt #attempt of jit root
        ``kernel`` — the DispatchLedger wrapper's pre-call draw.  Re-draws
        per ATTEMPT, so a breaker retry of an injected error heals with
        probability (1 - r) exactly like the REST seams; the key is the
        injector's per-kernel attempt ordinal (dispatches are sequenced by
        the scheduling loop, so the ordinal — and therefore the whole
        schedule — is a pure function of the seed).  Mesh loss outranks an
        error outranks a hang: the rarest, most structural fault wins a
        multi-way draw."""
        seam = f"dispatch:{kernel}"
        for kind in (MESH_DEVICE_LOSS, DISPATCH_ERROR, DISPATCH_HANG):
            if self._roll(kind, seam, attempt):
                return kind
        return None

    def readback_fault(self, kernel: str, attempt: int) -> Optional[str]:
        """Poisoned-output draw for readback attempt #attempt of a
        GUARDED fetch (Scheduler._d2h with a validating harvest).  Per
        attempt: a poisoned fetch re-fetches and heals, like a transport
        retry — the device array itself was never corrupted."""
        if self._roll(POISONED_OUTPUT, f"d2h:{kernel}", attempt):
            return POISONED_OUTPUT
        return None

    def hbm_fault(self, attempt: int) -> Optional[str]:
        """Resident-state donation/placement failure draw for sync
        attempt #attempt (the DeviceClusterCache.sync seam)."""
        if self._roll(HBM_OOM, "hbm:sync", attempt):
            return HBM_OOM
        return None

    # ----- seam: node heartbeats -------------------------------------------

    def flap_targets(self, node_names: Sequence[str], k: int = 1) -> List[str]:
        """The k nodes whose heartbeats this plan suppresses — a stable
        hash order over the names, so any caller with the same node set
        picks the same victims."""
        ranked = sorted(
            node_names, key=lambda n: _draw(self.seed, NODE_FLAP, "flap", n)
        )
        return ranked[: max(0, k)]

    def clock_skew_s(self, identity: str, max_skew_s: float = 2.0) -> float:
        """Deterministic per-identity clock offset in [-max, +max)."""
        return (_draw(self.seed, CLOCK_SKEW, "skew", identity) * 2 - 1) * max_skew_s
