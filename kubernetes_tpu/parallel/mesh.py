"""Device mesh + sharding specs for the scheduling pipeline.

Sharding layout (SURVEY.md §2.4 "TPU-native equivalent"):

  * every ``[P, ...]`` pod-batch tensor is sharded over the ``pods`` axis;
  * node-major snapshot tensors (``[N, ...]``) are replicated by default —
    the snapshot is the shared working set, and the per-pod pipeline reduces
    over all nodes; with a ``nodes`` axis >1 they are sharded on dim 0 and
    XLA all-gathers where a full-width reduction (normalize, argmax) needs
    them;
  * interned vocab side-tables are replicated.

This mirrors how the reference shares one Snapshot across its 16 worker
goroutines while splitting the pod stream — except both axes here scale
across chips over ICI instead of OS threads.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, DTable


def make_mesh(
    n_devices: Optional[int] = None, pods_axis: Optional[int] = None
) -> Mesh:
    """Mesh over available devices: ('pods', 'nodes').

    Default: all devices on the pods axis (batch parallel), nodes axis 1 —
    the layout that needs zero collectives in the hot path.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    # Default pods axis: the largest power of two dividing n, so bucketed
    # (power-of-two) batch dims always shard evenly.
    pa = pods_axis or (n & -n)
    na = n // pa
    arr = np.array(devs).reshape(pa, na)
    return Mesh(arr, ("pods", "nodes"))


def _shard(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def batch_shardings(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    """Sharding pytree for a DeviceBatch: dim 0 (pods) sharded."""

    def spec_for(x):
        return _shard(mesh, P("pods", *([None] * (x.ndim - 1))))

    return jax.tree_util.tree_map(spec_for, db)


# DeviceCluster fields whose leading axis is the NODE axis — these shard
# over the mesh's 'nodes' dimension.  Placed-pod ([E]), term ([M]) and vocab
# ([V]) tensors replicate: they are the quadratic operands every node shard
# reads in full (the all-gather-free layout; sharding THEM would turn every
# selector evaluation into a collective).
_NODE_MAJOR_FIELDS = frozenset(
    {
        "allocatable",
        "requested",
        "nonzero_req",
        "num_pods",
        "allowed_pods",
        "node_labels",
        "taint_key",
        "taint_val",
        "taint_effect",
        "unschedulable",
        "node_valid",
        "used_ppk",
        "used_ip",
        "used_wild",
        "img_sizes",
    }
)


def cluster_shardings(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    """Sharding pytree for a DeviceCluster: node-major tensors are
    partitioned over the mesh's 'nodes' axis (dim 0); everything else
    (placed pods, terms, vocab side-tables, scalars) replicates.  XLA's
    partitioner inserts the all-gathers/reductions where full-width
    normalize/argmax passes need them (SURVEY §2.4)."""
    n_nodes_axis = mesh.shape["nodes"]
    from dataclasses import fields, replace

    specs = {}
    for f in fields(DeviceCluster):
        x = getattr(dc, f.name)
        if (
            n_nodes_axis > 1
            and f.name in _NODE_MAJOR_FIELDS
            and getattr(x, "ndim", 0) >= 1
            and x.shape[0] % n_nodes_axis == 0
        ):
            spec = _shard(mesh, P("nodes", *([None] * (x.ndim - 1))))
        elif f.name == "term_table":
            spec = jax.tree_util.tree_map(lambda _: _shard(mesh, P()), x)
        else:
            spec = _shard(mesh, P())
        specs[f.name] = spec
    return replace(dc, **specs)


def place_batch(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    shardings = batch_shardings(mesh, db)
    return jax.tree_util.tree_map(jax.device_put, db, shardings)


def place_cluster(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    shardings = cluster_shardings(mesh, dc)
    return jax.tree_util.tree_map(jax.device_put, dc, shardings)
