"""Device mesh + sharding specs for the scheduling pipeline.

Sharding layout (SURVEY.md §2.4 "TPU-native equivalent"):

  * every ``[P, ...]`` pod-batch tensor is sharded over the ``pods`` axis;
  * node-major snapshot tensors (``[N, ...]``) are replicated by default —
    the snapshot is the shared working set, and the per-pod pipeline reduces
    over all nodes; with a ``nodes`` axis >1 they are sharded on dim 0 and
    XLA all-gathers where a full-width reduction (normalize, argmax) needs
    them;
  * interned vocab side-tables are replicated.

This mirrors how the reference shares one Snapshot across its 16 worker
goroutines while splitting the pod stream — except both axes here scale
across chips over ICI instead of OS threads.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, DTable


def make_mesh(
    n_devices: Optional[int] = None, pods_axis: Optional[int] = None
) -> Mesh:
    """Mesh over available devices: ('pods', 'nodes').

    Default: all devices on the pods axis (batch parallel), nodes axis 1 —
    the layout that needs zero collectives in the hot path.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    # Default pods axis: the largest power of two dividing n, so bucketed
    # (power-of-two) batch dims always shard evenly.
    pa = pods_axis or (n & -n)
    na = n // pa
    arr = np.array(devs).reshape(pa, na)
    return Mesh(arr, ("pods", "nodes"))


def _shard(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def batch_shardings(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    """Sharding pytree for a DeviceBatch: dim 0 (pods) sharded."""

    def spec_for(x):
        return _shard(mesh, P("pods", *([None] * (x.ndim - 1))))

    return jax.tree_util.tree_map(spec_for, db)


def cluster_shardings(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    """Sharding pytree for a DeviceCluster: replicated (nodes axis of the
    mesh shards node-major tensors when sized >1)."""
    n_nodes_axis = mesh.shape["nodes"]

    def spec_for(x):
        if n_nodes_axis > 1 and getattr(x, "ndim", 0) >= 1:
            return _shard(mesh, P(None))
        return _shard(mesh, P())

    return jax.tree_util.tree_map(spec_for, dc)


def place_batch(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    shardings = batch_shardings(mesh, db)
    return jax.tree_util.tree_map(jax.device_put, db, shardings)


def place_cluster(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    shardings = cluster_shardings(mesh, dc)
    return jax.tree_util.tree_map(jax.device_put, dc, shardings)
