"""Device mesh + sharding specs for the scheduling pipeline.

Sharding layout (SURVEY.md §2.4 "TPU-native equivalent"):

  * every ``[P, ...]`` pod-batch tensor is sharded over the ``pods`` axis;
  * node-major snapshot tensors (``[N, ...]``) are replicated by default —
    the snapshot is the shared working set, and the per-pod pipeline reduces
    over all nodes; with a ``nodes`` axis >1 they are sharded on dim 0 and
    XLA all-gathers where a full-width reduction (normalize, argmax) needs
    them;
  * interned vocab side-tables are replicated.

This mirrors how the reference shares one Snapshot across its 16 worker
goroutines while splitting the pod stream — except both axes here scale
across chips over ICI instead of OS threads.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster, DTable


def auto_enabled() -> bool:
    """The meshDispatch auto rule: partition the admission engine whenever
    the backend exposes more than one device (real multichip, or the
    forced-host virtual-device emulation used by the parity/test tier)."""
    return len(jax.devices()) > 1


def make_mesh(
    n_devices: Optional[int] = None, pods_axis: Optional[int] = None
) -> Mesh:
    """Mesh over available devices: ('pods', 'nodes').

    Default: all devices on the pods axis (batch parallel), nodes axis 1 —
    the layout that needs zero collectives in the hot path.
    """
    devs = jax.devices()
    n = n_devices or len(devs)
    devs = devs[:n]
    # Default pods axis: the largest power of two dividing n, so bucketed
    # (power-of-two) batch dims always shard evenly.
    pa = pods_axis or (n & -n)
    if n % pa:
        raise ValueError(f"pods_axis {pa} does not divide {n} devices")
    na = n // pa
    arr = np.array(devs).reshape(pa, na)
    return Mesh(arr, ("pods", "nodes"))


def parse_mesh_shape(spec: str) -> tuple:
    """'PAxNA' (e.g. '1x8', '8x1', '4x2') → (pods_axis, nodes_axis)."""
    try:
        pa, na = (int(x) for x in spec.lower().split("x"))
    except ValueError:
        raise ValueError(f"mesh spec {spec!r} is not PAxNA (e.g. '4x2')")
    if pa <= 0 or na <= 0:
        raise ValueError(f"mesh spec {spec!r} axes must be positive")
    return pa, na


def pad_to_multiple(n: int, multiple: int) -> int:
    """Round ``n`` up to a multiple of ``multiple`` (≥1)."""
    m = max(int(multiple), 1)
    return -(-int(n) // m) * m


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully-replicated placement on ``mesh`` — for wire buffers and side
    tables that every shard reads in full (mixing mesh-committed kernel
    operands with single-device-committed ones is a jit error)."""
    return NamedSharding(mesh, P())


def _shard(mesh: Mesh, spec: P) -> NamedSharding:
    return NamedSharding(mesh, spec)


def batch_shardings(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    """Sharding pytree for a DeviceBatch: dim 0 (pods) sharded.

    Every DeviceBatch leaf is pod-major, so the invariant is global:
    P % pods_axis == 0.  The scheduler guarantees it by seeding its sticky
    batch bucket with the mesh's pods axis (p_cap buckets are powers of
    two ≥ 8); standalone packers must pass a compatible ``p_cap``.
    """
    pa = mesh.shape["pods"]

    def spec_for(x):
        if pa > 1:
            assert x.shape[0] % pa == 0, (
                f"pod-major tensor {x.shape} not divisible by the mesh's "
                f"pods axis {pa} — pad p_cap to the mesh multiple "
                "(pad_to_multiple) instead of silently replicating"
            )
        return _shard(mesh, P("pods", *([None] * (x.ndim - 1))))

    return jax.tree_util.tree_map(spec_for, db)


# DeviceCluster fields whose leading axis is the NODE axis — these shard
# over the mesh's 'nodes' dimension.  Placed-pod ([E]), term ([M]) and vocab
# ([V]) tensors replicate: they are the quadratic operands every node shard
# reads in full (the all-gather-free layout; sharding THEM would turn every
# selector evaluation into a collective).
_NODE_MAJOR_FIELDS = frozenset(
    {
        "allocatable",
        "requested",
        "nonzero_req",
        "num_pods",
        "allowed_pods",
        "node_labels",
        "taint_key",
        "taint_val",
        "taint_effect",
        "unschedulable",
        "node_valid",
        "used_ppk",
        "used_ip",
        "used_wild",
        "img_sizes",
    }
)


def cluster_shardings(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    """Sharding pytree for a DeviceCluster: node-major tensors are
    partitioned over the mesh's 'nodes' axis (dim 0); everything else
    (placed pods, terms, vocab side-tables, scalars) replicates.  XLA's
    partitioner inserts the all-gathers/reductions where full-width
    normalize/argmax passes need them (SURVEY §2.4).

    N-divisibility is an INVARIANT, not a fallback: the packer pads the
    node bucket to the mesh multiple (pack_nodes ``n_multiple`` /
    SnapshotMirror.node_pad_multiple), so a non-divisible node-major
    tensor here means the padding discipline broke — assert instead of
    silently replicating (a replicated snapshot "works" but quietly
    abandons the node-axis scale-out this layout exists for)."""
    n_nodes_axis = mesh.shape["nodes"]
    from dataclasses import fields, replace

    specs = {}
    for f in fields(DeviceCluster):
        x = getattr(dc, f.name)
        if (
            n_nodes_axis > 1
            and f.name in _NODE_MAJOR_FIELDS
            and getattr(x, "ndim", 0) >= 1
        ):
            assert x.shape[0] % n_nodes_axis == 0, (
                f"node-major tensor {f.name}{x.shape} not divisible by the "
                f"mesh's nodes axis {n_nodes_axis} — the packer must pad N "
                "to the mesh multiple (pack_nodes n_multiple / "
                "mirror.node_pad_multiple), not replicate"
            )
            spec = _shard(mesh, P("nodes", *([None] * (x.ndim - 1))))
        elif f.name == "term_table":
            spec = jax.tree_util.tree_map(lambda _: _shard(mesh, P()), x)
        else:
            spec = _shard(mesh, P())
        specs[f.name] = spec
    return replace(dc, **specs)


def place_batch(mesh: Mesh, db: DeviceBatch) -> DeviceBatch:
    shardings = batch_shardings(mesh, db)
    return jax.tree_util.tree_map(jax.device_put, db, shardings)


def place_cluster(mesh: Mesh, dc: DeviceCluster) -> DeviceCluster:
    shardings = cluster_shardings(mesh, dc)
    return jax.tree_util.tree_map(jax.device_put, dc, shardings)
