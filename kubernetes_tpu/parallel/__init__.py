"""Multi-chip parallelism: mesh construction + sharding specs.

The reference scales per-cycle work with a 16-way chunked parallel-for over
nodes (pkg/scheduler/framework/parallelize/parallelism.go:28) and runs
replicas active/passive behind leader election.  Here the same two axes
become a 2-D ``jax.sharding.Mesh``:

  * ``pods``  — the batch axis (the reference's strictly-serial pod loop,
    SURVEY.md §2.2 item 1, turned into data parallelism);
  * ``nodes`` — the cluster axis (the reference's Parallelizer.Until axis,
    turned into sharded tensor columns).

XLA inserts the collectives (all-gathers for cross-node reductions like
normalize/argmax) — there is no hand-written NCCL/MPI equivalent, by design.
"""

from kubernetes_tpu.parallel.mesh import (  # noqa: F401
    auto_enabled,
    batch_shardings,
    cluster_shardings,
    make_mesh,
    pad_to_multiple,
    parse_mesh_shape,
    place_batch,
    place_cluster,
    replicated,
)
