"""Use-after-donation checker (rule: ``donation``).

The device pipelines donate their big HBM buffers back to XLA
(``donate_argnums``/``donate_argnames`` on ``chain_dispatch``,
``sig_scan``, ``resident_run``, the device-mirror delta applier): the
callee may write its outputs into the donated storage, so the caller's
reference is DEAD the moment the call is issued.  Reading it afterwards
is use-after-free that jax only sometimes catches (a deleted-buffer
error on some backends, silently stale data on others).

Two checks:

  * caller-side liveness — for every intra-package call site of a
    donating root (resolved through import aliases), any argument bound
    to a donated parameter that is a plain local NAME kills that name
    (and every alias of it, tracked like the lock checker's alias
    tainting: ``b = a`` then donate ``a`` kills ``b`` too).  A later
    read of a dead name — before a rebinding revives it — is a finding.
    If/else branches are walked independently and merged, so the
    resident/sig_scan either-or dispatch does not cross-contaminate.

  * contract documentation — every donating root must be named in the
    donation/aliasing contract (RESIDENT.md §"Donation / aliasing
    contract"): the text is the API contract callers code against, and
    an undocumented donation is a contract change that shipped silently.
    (Checked only on shipped-tree runs, where the doc is present.)

Limits (by design): donated arguments reached through attributes or
subscripts (``ch["dc"]``) are not tracked — the chain holder's dict
handoff rebinds atomically; and loop bodies are walked once, so a
donate-then-read across iterations of the same loop is caught only when
the name is not rebound first.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubernetes_tpu.analysis.core import (
    RULE_DONATION,
    Checker,
    ImportRefs,
    SourceModule,
    dotted_name,
    resolve_root,
)

from kubernetes_tpu.analysis.d2h import _module_base


def _donation_spec(fn: ast.FunctionDef) -> Optional[Set[str]]:
    """Donated PARAM NAMES when ``fn`` is jitted with donate_argnums /
    donate_argnames; None otherwise."""
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        dnc = dotted_name(dec.func)
        if dnc is None:
            continue
        tail = dnc.split(".")[-1]
        target = dec
        if tail == "partial":
            if not dec.args:
                continue
            first = dotted_name(dec.args[0])
            if first is None or first.split(".")[-1] != "jit":
                continue
        elif tail != "jit":
            continue
        params = [a.arg for a in fn.args.args]
        donated: Set[str] = set()
        for kw in target.keywords:
            if kw.arg == "donate_argnums":
                try:
                    v = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                idxs = (v,) if isinstance(v, int) else tuple(v)
                donated |= {params[i] for i in idxs if i < len(params)}
            elif kw.arg == "donate_argnames":
                try:
                    v = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    continue
                names = (v,) if isinstance(v, str) else tuple(v)
                donated |= set(names)
        if donated:
            return donated
    return None


_CONTRACT_HEADING = re.compile(
    r"^#+\s*Donation\s*/\s*aliasing contract\s*$", re.IGNORECASE | re.M
)


def _contract_section(text: str) -> str:
    """The §"Donation / aliasing contract" body — the roster the check
    greps.  Prose mentions elsewhere in the doc must not satisfy it, so
    the section is cut at the next heading; a doc without the heading
    yields the empty string (every donating root is then undocumented,
    which is the honest verdict)."""
    m = _CONTRACT_HEADING.search(text)
    if m is None:
        return ""
    rest = text[m.end():]
    nxt = re.search(r"^#+\s", rest, re.M)
    return rest[: nxt.start()] if nxt else rest


class _Root:
    def __init__(self, base: str, qual: str, node: ast.FunctionDef,
                 donated: Set[str]):
        self.base = base
        self.qual = qual
        self.name = node.name
        self.node = node
        self.params = [a.arg for a in node.args.args]
        self.donated = donated


class DonationChecker(Checker):
    rule = RULE_DONATION

    def __init__(self) -> None:
        super().__init__()
        # module base → fn name → _Root (alias-table lookups), plus the
        # path-scoped view for each module's OWN bare names (two modules
        # sharing a basename must not resolve each other's)
        self.roots: Dict[str, Dict[str, _Root]] = {}
        self.roots_by_path: Dict[str, Dict[str, _Root]] = {}

    # ----- entry point ------------------------------------------------------

    def run(
        self,
        mods: Sequence[SourceModule],
        contract_text: Optional[str] = None,
    ) -> None:
        root_mods: List[Tuple[SourceModule, _Root]] = []
        for mod in mods:
            base = _module_base(mod.path)
            merged = self.roots.setdefault(base, {})
            per = self.roots_by_path.setdefault(mod.path, {})

            def index(fn: ast.AST, qual: str) -> None:
                for node in ast.iter_child_nodes(fn):
                    if isinstance(node, ast.FunctionDef):
                        q = f"{qual}.{node.name}" if qual else node.name
                        donated = _donation_spec(node)
                        if donated:
                            r = _Root(base, q, node, donated)
                            per[node.name] = r
                            merged[node.name] = r
                            root_mods.append((mod, r))
                        index(node, q)
                    elif isinstance(node, (ast.ClassDef, ast.If, ast.Try)):
                        index(node, qual)

            index(mod.tree, "")

        if contract_text is not None:
            roster = _contract_section(contract_text)
            for mod, r in root_mods:
                if not re.search(rf"\b{re.escape(r.name)}\b", roster):
                    self.emit(
                        mod,
                        r.node.lineno,
                        f"donating kernel {r.qual!r} is not documented in "
                        "the donation/aliasing contract (RESIDENT.md) — "
                        "callers code against that text",
                    )

        for mod in mods:
            refs = ImportRefs(mod.tree)
            self._check_module(
                mod, refs, self.roots_by_path.get(mod.path, {})
            )

    # ----- caller-side liveness ---------------------------------------------

    def _check_module(
        self, mod: SourceModule, refs: ImportRefs,
        self_roots: Dict[str, _Root],
    ) -> None:
        def walk_fns(container: ast.AST) -> None:
            for node in ast.iter_child_nodes(container):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._check_function(mod, refs, self_roots, node)
                    walk_fns(node)
                elif isinstance(node, ast.ClassDef):
                    walk_fns(node)

        walk_fns(mod.tree)

    def _resolve_root(
        self, refs: ImportRefs, self_roots: Dict[str, _Root],
        func: ast.expr
    ) -> Optional[_Root]:
        return resolve_root(refs, self_roots, self.roots, func)

    def _check_function(
        self,
        mod: SourceModule,
        refs: ImportRefs,
        self_roots: Dict[str, _Root],
        fn: ast.FunctionDef,
    ) -> None:
        # dead name → the donating call that killed it ("fn@line")
        dead: Dict[str, str] = {}
        aliases: Dict[str, str] = {}  # name → root name it aliases
        self._walk_block(mod, refs, self_roots, fn.body, dead, aliases)

    def _walk_block(
        self,
        mod: SourceModule,
        refs: ImportRefs,
        self_roots: Dict[str, _Root],
        stmts: List[ast.stmt],
        dead: Dict[str, str],
        aliases: Dict[str, str],
    ) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs run later — fresh liveness scope
            self._flag_dead_reads(mod, st, dead)
            self._apply_donations(mod, refs, self_roots, st, dead, aliases)
            if isinstance(st, ast.Assign):
                self._track(st, dead, aliases)
            elif isinstance(st, ast.If):
                d1, d2 = dict(dead), dict(dead)
                a1, a2 = dict(aliases), dict(aliases)
                self._walk_block(mod, refs, self_roots, st.body, d1, a1)
                self._walk_block(mod, refs, self_roots, st.orelse, d2, a2)
                # a name donated on EITHER path is suspect afterwards;
                # revived only when both paths rebound it
                dead.clear()
                dead.update(d2)
                dead.update(d1)
                aliases.clear()
                aliases.update(a2)
                aliases.update(a1)
                continue
            elif isinstance(st, (ast.For, ast.While)):
                if isinstance(st, ast.For):
                    # the loop target is rebound every iteration — revive
                    self._revive(st.target, dead, aliases)
                self._walk_block(mod, refs, self_roots, st.body, dead, aliases)
                self._walk_block(mod, refs, self_roots, st.orelse, dead, aliases)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for it in st.items:
                    if it.optional_vars is not None:
                        self._revive(it.optional_vars, dead, aliases)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    self._walk_block(mod, refs, self_roots, sub, dead, aliases)
            for handler in getattr(st, "handlers", ()) or ():
                self._walk_block(
                    mod, refs, self_roots, handler.body, dead, aliases
                )

    @staticmethod
    def _revive(target: ast.expr, dead: Dict[str, str],
                aliases: Dict[str, str]) -> None:
        """A binding target (for-loop variable, `with ... as` name,
        unpacked tuple) revives the names it rebinds."""
        for node in ast.walk(target):
            if isinstance(node, ast.Name):
                dead.pop(node.id, None)
                aliases.pop(node.id, None)

    @staticmethod
    def _expr_children(st: ast.stmt):
        """Direct expression children of a statement, including `with`
        context expressions (withitem nodes are not exprs and would
        otherwise hide their headers from the scan)."""
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                yield child
            elif isinstance(child, ast.withitem):
                yield child.context_expr

    def _flag_dead_reads(
        self, mod: SourceModule, st: ast.stmt, dead: Dict[str, str]
    ) -> None:
        if not dead:
            return
        for child in self._expr_children(st):
            for node in ast.walk(child):
                if (
                    isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)
                    and node.id in dead
                ):
                    self.emit(
                        mod,
                        node.lineno,
                        f"read of {node.id!r} after it was donated to "
                        f"{dead[node.id]} — the buffer may already hold "
                        "the callee's outputs",
                    )

    def _apply_donations(
        self,
        mod: SourceModule,
        refs: ImportRefs,
        self_roots: Dict[str, _Root],
        st: ast.stmt,
        dead: Dict[str, str],
        aliases: Dict[str, str],
    ) -> None:
        for child in self._expr_children(st):
            for node in ast.walk(child):
                if not isinstance(node, ast.Call):
                    continue
                root = self._resolve_root(refs, self_roots, node.func)
                if root is None:
                    continue
                killed: Set[str] = set()
                for i, a in enumerate(node.args):
                    if i < len(root.params) and root.params[i] in root.donated:
                        if isinstance(a, ast.Name):
                            killed.add(a.id)
                for kw in node.keywords:
                    if kw.arg in root.donated and isinstance(
                        kw.value, ast.Name
                    ):
                        killed.add(kw.value.id)
                if not killed:
                    continue
                # alias closure: killing a root name kills its aliases
                groups: Set[str] = set(killed)
                for k in killed:
                    groups.add(aliases.get(k, k))
                tag = f"{root.name}() at line {node.lineno}"
                for name, rootname in list(aliases.items()):
                    if rootname in groups or name in groups:
                        dead[name] = tag
                for name in groups:
                    dead[name] = tag

    def _track(
        self,
        st: ast.Assign,
        dead: Dict[str, str],
        aliases: Dict[str, str],
    ) -> None:
        # rebinding revives; `b = a` aliases b to a's root
        targets: List[ast.expr] = []
        for t in st.targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
            else:
                targets.append(t)
        for t in targets:
            if isinstance(t, ast.Name):
                dead.pop(t.id, None)
                aliases.pop(t.id, None)
        if (
            len(st.targets) == 1
            and isinstance(st.targets[0], ast.Name)
            and isinstance(st.value, ast.Name)
        ):
            src = st.value.id
            aliases[st.targets[0].id] = aliases.get(src, src)
