"""Plugin-purity checker (rule: ``plugin-purity``).

A plugin declaring ``pre_filter_spec_pure = True`` promises the fast path
that, for a signature-gated pod, its ``pre_filter`` verdict is a pure
function of the pod SPEC — the per-signature PreFilter grouping replays
one representative's verdict for every pod of the signature, so anything
the spec path reads beyond the pod (handle caches, CycleState, plugin
fields that mutate) or writes anywhere silently diverges per pod.

The SPEC PATH is the statement prefix a gated pod executes: top-level
statements up to and including the first *gate* — an ``if`` whose
condition is spec-derived and whose body unconditionally returns a
Status. Code after the gate only runs for non-gated pods (the plugin is
relevant; the per-pod walk applies) and is exempt.  A ``pre_filter``
with no gate is entirely spec path.

Checked on the spec path:

  * conditions and assigned expressions must be SPEC-DERIVED: built only
    from ``pod`` (attribute reads and method calls on it are assumed
    pure), locals already proven spec-derived, constants, and a small
    pure-builtin allowlist — reading ``state``, ``self.handle``, any
    global lister, clocks or RNGs is a finding;
  * no writes: assignments/deletes targeting attributes or subscripts of
    anything non-local (``state``, ``self``, handle caches) are findings,
    as are calls to known-mutating APIs (``state.write``, ``.pop``,
    ``.setdefault`` …) on non-spec objects.
"""

from __future__ import annotations

import ast
from typing import List, Set

from kubernetes_tpu.analysis.core import (
    RULE_PURITY,
    Checker,
    SourceModule,
    dotted_name,
)

PURITY_FLAG = "pre_filter_spec_pure"

# names a spec-path expression may reference besides `pod` and locals
PURE_GLOBALS = {
    "Status",
    "len",
    "bool",
    "int",
    "float",
    "str",
    "set",
    "frozenset",
    "tuple",
    "list",
    "dict",
    "any",
    "all",
    "isinstance",
    "getattr",
    "min",
    "max",
    "sorted",
    "None",
    "True",
    "False",
}

# reads of self.<attr> are allowed (class constants like `name`), but
# CALLS routed through these roots are impure on the spec path
IMPURE_ROOTS = {"state", "self", "handle"}


def _flag_declared_true(cls: ast.ClassDef) -> bool:
    for st in cls.body:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                if isinstance(t, ast.Name) and t.id == PURITY_FLAG:
                    return isinstance(st.value, ast.Constant) and st.value.value is True
    return False


class PurityChecker(Checker):
    rule = RULE_PURITY

    def run(self, mods: List[SourceModule]) -> None:
        for mod in mods:
            for node in mod.tree.body:
                if not isinstance(node, ast.ClassDef):
                    continue
                if not _flag_declared_true(node):
                    continue
                pf = next(
                    (
                        st
                        for st in node.body
                        if isinstance(st, ast.FunctionDef)
                        and st.name == "pre_filter"
                    ),
                    None,
                )
                if pf is None:
                    continue  # inherits the base no-op — nothing to check
                self._check_pre_filter(mod, node.name, pf)

    # ----- spec-path walk ---------------------------------------------------

    def _check_pre_filter(
        self, mod: SourceModule, cls_name: str, fn: ast.FunctionDef
    ) -> None:
        args = [a.arg for a in fn.args.args]
        pod_name = args[2] if len(args) >= 3 else "pod"
        spec_locals: Set[str] = {pod_name}

        for st in fn.body:
            if self._is_gate(st, spec_locals):
                # the gate's own condition and returned Status must be pure
                self._check_expr(mod, cls_name, st.test, spec_locals)
                for sub in st.body:
                    if isinstance(sub, ast.Return) and sub.value is not None:
                        self._check_return_value(mod, cls_name, sub.value, spec_locals)
                return  # statements past the gate are off the spec path
            self._check_stmt(mod, cls_name, st, spec_locals)

    def _is_gate(self, st: ast.stmt, spec_locals: Set[str]) -> bool:
        """A spec-derived ``if`` whose body unconditionally returns."""
        if not isinstance(st, ast.If) or st.orelse:
            return False
        if not st.body or not isinstance(st.body[-1], ast.Return):
            return False
        if not all(isinstance(s, (ast.Return, ast.Expr)) for s in st.body):
            return False
        return self._is_spec_expr(st.test, spec_locals)

    def _check_stmt(
        self,
        mod: SourceModule,
        cls_name: str,
        st: ast.stmt,
        spec_locals: Set[str],
    ) -> None:
        if isinstance(st, ast.Assign):
            for t in st.targets:
                self._check_write_target(mod, cls_name, t)
            self._check_expr(mod, cls_name, st.value, spec_locals)
            # a local assigned a spec-derived expression joins the set
            if (
                len(st.targets) == 1
                and isinstance(st.targets[0], ast.Name)
                and self._is_spec_expr(st.value, spec_locals)
            ):
                spec_locals.add(st.targets[0].id)
            return
        if isinstance(st, ast.AugAssign):
            self._check_write_target(mod, cls_name, st.target)
            self._check_expr(mod, cls_name, st.value, spec_locals)
            return
        if isinstance(st, ast.Return):
            if st.value is not None:
                self._check_return_value(mod, cls_name, st.value, spec_locals)
            return
        if isinstance(st, ast.If):
            # non-gate conditional: both arms stay on the spec path
            self._check_expr(mod, cls_name, st.test, spec_locals)
            for sub in st.body + st.orelse:
                self._check_stmt(mod, cls_name, sub, spec_locals)
            return
        if isinstance(st, (ast.For, ast.While)):
            if isinstance(st, ast.For):
                self._check_expr(mod, cls_name, st.iter, spec_locals)
                if isinstance(st.target, ast.Name):
                    spec_locals.add(st.target.id)
            else:
                self._check_expr(mod, cls_name, st.test, spec_locals)
            for sub in st.body + st.orelse:
                self._check_stmt(mod, cls_name, sub, spec_locals)
            return
        if isinstance(st, ast.Expr):
            self._check_expr(mod, cls_name, st.value, spec_locals)
            return
        if isinstance(st, (ast.Pass, ast.Import, ast.ImportFrom)):
            return
        # anything structurally unusual on the spec path (try/with/del/
        # global …) is outside the purity contract's shape
        self.emit(
            mod,
            st.lineno,
            f"{cls_name}.pre_filter: {type(st).__name__} statement on the "
            f"spec path of a pre_filter_spec_pure plugin",
        )

    # ----- expression checks ------------------------------------------------

    def _check_write_target(
        self, mod: SourceModule, cls_name: str, target: ast.expr
    ) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_write_target(mod, cls_name, el)
            return
        if isinstance(target, ast.Name):
            return  # plain local
        self.emit(
            mod,
            target.lineno,
            f"{cls_name}.pre_filter: write to non-local state "
            f"({ast.unparse(target)}) on the spec path",
        )

    def _check_return_value(
        self, mod: SourceModule, cls_name: str, value: ast.expr, spec_locals: Set[str]
    ) -> None:
        # Status.<ctor>(...) with spec-derived args, a bare constant, or a
        # spec-derived expression
        if isinstance(value, ast.Call):
            dn = dotted_name(value.func)
            if dn is not None and dn.split(".")[0] == "Status":
                for a in value.args:
                    self._check_expr(mod, cls_name, a, spec_locals)
                for kw in value.keywords:
                    self._check_expr(mod, cls_name, kw.value, spec_locals)
                return
        self._check_expr(mod, cls_name, value, spec_locals)

    @staticmethod
    def _comp_targets(expr: ast.expr) -> Set[str]:
        """Comprehension-bound names inside the expression — scoped to it,
        and spec-derived whenever their iterables pass the checks."""
        out: Set[str] = set()
        for node in ast.walk(expr):
            if isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                for gen in node.generators:
                    for t in ast.walk(gen.target):
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    @staticmethod
    def _is_constant_name(name: str) -> bool:
        """Module-level constants by convention (ALL_CAPS) are immutable
        trace-through reads, not hidden state."""
        return name.isupper() or (
            name.startswith("_") and name[1:].isupper() and len(name) > 1
        )

    def _check_expr(
        self, mod: SourceModule, cls_name: str, expr: ast.expr, spec_locals: Set[str]
    ) -> None:
        spec_locals = spec_locals | self._comp_targets(expr)
        reported: Set[int] = set()  # Attribute ids already covered by a call
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn is not None:
                    root = dn.split(".")[0]
                    if root in IMPURE_ROOTS or (
                        len(dn.split(".")) > 1
                        and root not in spec_locals
                        and root not in PURE_GLOBALS
                        and root != "Status"
                    ):
                        self.emit(
                            mod,
                            node.lineno,
                            f"{cls_name}.pre_filter: impure call "
                            f"{dn}(...) on the spec path",
                        )
                        sub = node.func
                        while isinstance(sub, ast.Attribute):
                            reported.add(id(sub))
                            sub = sub.value
            elif isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
                # plain reads through self/state/handle are hidden state
                # too: `if self.disabled: …` diverges per pod exactly like
                # a call would.  Class constants (self.name, self._STATE_
                # KEY-style ALL_CAPS) are the allowed exceptions.
                if id(node) in reported:
                    continue
                dn = dotted_name(node)
                if dn is None:
                    continue
                parts = dn.split(".")
                if parts[0] not in IMPURE_ROOTS:
                    continue
                if (
                    parts[0] == "self"
                    and len(parts) == 2
                    and (parts[1] == "name" or self._is_constant_name(parts[1]))
                ):
                    continue
                self.emit(
                    mod,
                    node.lineno,
                    f"{cls_name}.pre_filter: read of mutable state "
                    f"{dn} on the spec path",
                )
                sub = node.value
                while isinstance(sub, ast.Attribute):
                    reported.add(id(sub))
                    sub = sub.value
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if (
                    node.id not in spec_locals
                    and node.id not in PURE_GLOBALS
                    and node.id not in IMPURE_ROOTS  # reported at the call
                    and not self._is_constant_name(node.id)
                ):
                    # a bare read of `self`/`state` attribute is allowed only
                    # through Attribute nodes; bare foreign names are reads
                    # of globals/closures — not spec-derived
                    self.emit(
                        mod,
                        node.lineno,
                        f"{cls_name}.pre_filter: read of non-spec name "
                        f"{node.id!r} on the spec path",
                    )

    # ----- spec-derived test ------------------------------------------------

    def _is_spec_expr(self, expr: ast.expr, spec_locals: Set[str]) -> bool:
        """True when every leaf name is `pod`/spec-derived/pure-builtin and
        no call routes through an impure root."""
        spec_locals = spec_locals | self._comp_targets(expr)
        for node in ast.walk(expr):
            if isinstance(node, ast.Name):
                if (
                    node.id in spec_locals
                    or node.id in PURE_GLOBALS
                    or self._is_constant_name(node.id)
                ):
                    continue
                return False
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn is None:
                    return False
                root = dn.split(".")[0]
                if root in IMPURE_ROOTS:
                    return False
            if isinstance(node, (ast.Await, ast.Yield, ast.YieldFrom, ast.NamedExpr)):
                return False
        return True
