"""Slice-clamp hazard checker (rule: ``slice-clamp``).

XLA CLAMPS an out-of-range ``dynamic_update_slice`` start index: a window
write whose traced start would run past the array end silently shifts
backwards onto earlier rows and overwrites them — the PR 6 bug class
(``ops/resident.py`` once corrupted earlier results this way, fixed by
padding the choices buffer).  ``.at[...].set`` is the sibling hazard:
its out-of-bounds writes are silently DROPPED unless the author spells
an explicit ``mode=``.

The checker rides the jit checker's staticness machinery (same
reachability from the ``jax.jit`` roots, same abstract interpretation of
which values are trace-time constants), and flags:

  * ``jax.lax.dynamic_update_slice(dst, delta, start)`` where any start
    component is traced, and
  * ``x.at[idx].set(...)`` where ``idx`` is traced and no explicit
    ``mode=`` keyword is given,

UNLESS the hazard is discharged by one of the accepted proofs:

  * the start/index is provably static (trace-time constant — the
    staticness fixpoint says so), or
  * the destination is provably padded: it was constructed in the same
    function by ``jnp.full/zeros/ones/empty`` with a leading dimension
    spelled as a SUM (``(P + W,)``) — the sanctioned padded-buffer idiom
    from the resident fixed point, or
  * a ``# ktpu: allow(slice-clamp) — <why the start is bounded>``
    suppression, which forces the boundedness argument into the diff
    (see ops/chain.py: the append cursors are bounded by the scheduler's
    host-side capacity check before dispatch).

``.at[...].add`` scatter-adds and ``dynamic_slice`` READS are out of
scope: a clamped read duplicates a value, it does not corrupt committed
state.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set, Tuple

from kubernetes_tpu.analysis.core import RULE_CLAMP, SourceModule, dotted_name
from kubernetes_tpu.analysis.jit import JitChecker, _FuncInfo

PADDED_CTORS = {"full", "zeros", "ones", "empty"}


class ClampChecker(JitChecker):
    rule = RULE_CLAMP

    def __init__(self) -> None:
        super().__init__()
        self._seen: Set[Tuple[str, int, str]] = set()

    # jit-boundary emission is jit.py's job — this subclass only reuses
    # the reachability + staticness machinery
    def _violation(self, f: _FuncInfo, line: int, message: str) -> None:
        pass

    def _check_call(self, f, base, node, env) -> None:
        func = node.func
        dn = dotted_name(func)
        if dn is not None and dn.split(".")[-1] == "dynamic_update_slice":
            start = None
            if len(node.args) >= 3:
                start = node.args[2]
            else:
                for kw in node.keywords:
                    if kw.arg == "start_indices":
                        start = kw.value
            dest = node.args[0] if node.args else None
            if (
                start is not None
                and not self._static(f, base, start, env)
                and not self._padded_dest(f, dest)
            ):
                self._clamp(
                    f,
                    node.lineno,
                    "dynamic_update_slice with a traced start — XLA clamps "
                    "an out-of-range start and the window write silently "
                    "shifts onto earlier rows; pad the destination by the "
                    "window size or prove the start bounded",
                )
            return
        if (
            isinstance(func, ast.Attribute)
            and func.attr == "set"
            and isinstance(func.value, ast.Subscript)
            and isinstance(func.value.value, ast.Attribute)
            and func.value.value.attr == "at"
        ):
            if any(kw.arg == "mode" for kw in node.keywords):
                return  # explicit out-of-bounds semantics — author chose
            idx = func.value.slice
            dest = func.value.value.value
            if not self._static(f, base, idx, env) and not self._padded_dest(
                f, dest
            ):
                self._clamp(
                    f,
                    node.lineno,
                    ".at[...].set with a traced index silently DROPS "
                    "out-of-bounds writes — pass an explicit mode= or "
                    "prove the index bounded",
                )

    def _clamp(self, f: _FuncInfo, line: int, message: str) -> None:
        if not self._emit_mode:
            return
        key = (f.mod.path, line, message)
        if key in self._seen:
            return  # nested fns are analyzed from several contexts
        self._seen.add(key)
        fn_name = f.key.split(":", 1)[1]
        self.emit(f.mod, line, f"{fn_name}: {message}")

    def _padded_dest(self, f: _FuncInfo, dest: Optional[ast.expr]) -> bool:
        """True when ``dest`` is a local name constructed (in this function
        or an enclosing one) with a padded leading dimension — either
        directly, or through a ``lax.while_loop`` carry whose matching
        init element is padded (the resident fixed point's idiom: the
        loop body unpacks ``choices`` from the carry, and the init tuple
        seeds it with ``jnp.full((P + W,), …)``)."""
        if not isinstance(dest, ast.Name):
            return False
        if self._padded_binding(f, dest.id):
            return True
        return self._padded_carry(f, dest.id)

    def _padded_binding(self, f: _FuncInfo, name: str) -> bool:
        scope: Optional[_FuncInfo] = f
        while scope is not None:
            for n in ast.walk(scope.node):
                if isinstance(n, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == name
                    for t in n.targets
                ):
                    if self._is_padded_ctor(n.value):
                        return True
            scope = scope.enclosing
        return False

    def _padded_carry(self, f: _FuncInfo, name: str) -> bool:
        """``name`` unpacked at position i from this loop-body function's
        carry parameter, and some enclosing scope runs
        ``while_loop(cond, <this body>, (..., init_i, ...))`` with a
        padded init at position i."""
        if len(f.params) != 1:
            return False
        carry = f.params[0]
        idx = None
        for n in f.node.body:
            if (
                isinstance(n, ast.Assign)
                and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Tuple)
                and isinstance(n.value, ast.Name)
                and n.value.id == carry
            ):
                for i, el in enumerate(n.targets[0].elts):
                    if isinstance(el, ast.Name) and el.id == name:
                        idx = i
                        break
        if idx is None:
            return False
        body_name = f.node.name
        scope = f.enclosing
        while scope is not None:
            for n in ast.walk(scope.node):
                if not isinstance(n, ast.Call):
                    continue
                dn = dotted_name(n.func)
                if dn is None or dn.split(".")[-1] != "while_loop":
                    continue
                if len(n.args) < 3:
                    continue
                if not (
                    isinstance(n.args[1], ast.Name)
                    and n.args[1].id == body_name
                ):
                    continue
                init = n.args[2]
                if isinstance(init, ast.Tuple) and idx < len(init.elts):
                    el = init.elts[idx]
                    if self._is_padded_ctor(el):
                        return True
                    if isinstance(el, ast.Name) and self._padded_binding(
                        scope, el.id
                    ):
                        return True
            scope = scope.enclosing
        return False

    @staticmethod
    def _is_padded_ctor(value: ast.expr) -> bool:
        if not isinstance(value, ast.Call):
            return False
        dn = dotted_name(value.func)
        if dn is None or dn.split(".")[-1] not in PADDED_CTORS:
            return False
        if not value.args:
            return False
        shape = value.args[0]
        lead = shape.elts[0] if isinstance(shape, ast.Tuple) and shape.elts else shape
        return isinstance(lead, ast.BinOp) and isinstance(lead.op, ast.Add)
