"""Invariant analyzers for the TPU scheduler (``python -m kubernetes_tpu.analysis``).

Three AST checkers guard the contracts PR 1's concurrency layering relies
on (the race-detector/vet role the reference scheduler gets from the Go
toolchain):

  * ``lock-discipline`` — registered lock-guarded fields are only mutated
    under their lock or in callers-verified ``*_under_lock`` methods;
  * ``plugin-purity`` — ``pre_filter_spec_pure`` plugins keep their spec
    path free of state reads/writes;
  * ``jit-boundary`` — nothing reachable from the jitted pipelines in
    ``ops/`` host-syncs or branches on tracers.

Plus a runtime sanitizer (``KTPU_SANITIZE=1``, see ``sanitizer.py``).
Suppressions: ``# ktpu: allow(<rule>) — <reason>`` (reason mandatory).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from kubernetes_tpu.analysis.core import (
    Finding,
    SourceModule,
    collect_bare_suppressions,
    render_json,
    render_text,
)
from kubernetes_tpu.analysis.jit import JitChecker
from kubernetes_tpu.analysis.locks import LockChecker
from kubernetes_tpu.analysis.purity import PurityChecker

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the shipped tree's checker targets
LOCK_MODULES = (
    "scheduler.py",
    os.path.join("cache", "cache.py"),
    os.path.join("cache", "mirror.py"),
    os.path.join("queue", "scheduling_queue.py"),
    # chaos subsystem: the injection log / one-shot ledger, per-seam
    # ordinal counters, and journal entries are all appended from
    # reflector threads and binding workers concurrently
    os.path.join("chaos", "faults.py"),
    os.path.join("chaos", "proxy.py"),
    os.path.join("chaos", "journal.py"),
    # observability: the span buffer and flight-recorder ring are appended
    # from the scheduling loop, binding workers, informer threads, and HTTP
    # debug handlers; explain holds the Scheduler lock across its prep
    os.path.join("observability", "tracer.py"),
    os.path.join("observability", "flightrecorder.py"),
    os.path.join("observability", "explain.py"),
    # SLO tier: ingest runs on every flight-recorder producer thread,
    # snapshot/evaluate on HTTP handlers and the bench harness
    os.path.join("observability", "slo.py"),
)
PURITY_MODULES = (
    os.path.join("framework", "plugins.py"),
    os.path.join("framework", "volume_plugins.py"),
    os.path.join("framework", "volumebinding.py"),
    os.path.join("framework", "dynamicresources.py"),
)
JIT_MODULES = (
    os.path.join("ops", "chain.py"),
    os.path.join("ops", "common.py"),
    os.path.join("ops", "explain.py"),
    os.path.join("ops", "fastpath.py"),
    os.path.join("ops", "filters.py"),
    os.path.join("ops", "gang.py"),
    os.path.join("ops", "pipeline.py"),
    os.path.join("ops", "preemption.py"),
    os.path.join("ops", "resident.py"),
    os.path.join("ops", "scores.py"),
    os.path.join("ops", "wave.py"),
    os.path.join("ops", "wire.py"),
)


def default_targets() -> Dict[str, List[str]]:
    return {
        "locks": [os.path.join(_PKG_ROOT, p) for p in LOCK_MODULES],
        "purity": [os.path.join(_PKG_ROOT, p) for p in PURITY_MODULES],
        "jit": [os.path.join(_PKG_ROOT, p) for p in JIT_MODULES],
    }


def run_analysis(
    targets: Optional[Dict[str, Sequence[str]]] = None,
) -> List[Finding]:
    """Run every checker over its target file set; returns ALL findings
    (post-suppression), sorted by path/line.  ``targets`` maps checker key
    ('locks'/'purity'/'jit') → file paths; defaults to the shipped tree.
    """
    t = dict(default_targets())
    if targets is not None:
        t.update({k: list(v) for k, v in targets.items()})

    loaded: Dict[str, SourceModule] = {}

    def load(paths: Sequence[str]) -> List[SourceModule]:
        out = []
        for p in paths:
            key = os.path.abspath(p)
            if key not in loaded:
                loaded[key] = SourceModule.load(p)
            out.append(loaded[key])
        return out

    findings: List[Finding] = []

    lc = LockChecker()
    lc.run(load(t.get("locks", ())))
    findings.extend(lc.findings)

    pc = PurityChecker()
    pc.run(load(t.get("purity", ())))
    findings.extend(pc.findings)

    jc = JitChecker()
    jc.run(load(t.get("jit", ())))
    findings.extend(jc.findings)

    findings.extend(collect_bare_suppressions(loaded.values()))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


__all__ = [
    "Finding",
    "run_analysis",
    "default_targets",
    "render_text",
    "render_json",
]
