"""Invariant analyzers for the TPU scheduler (``python -m kubernetes_tpu.analysis``).

Eleven AST checkers guard the contracts the concurrency layering, the
device boundary, and the named-axis shape algebra rely on (the
race-detector/vet role the reference scheduler gets from the Go
toolchain):

  * ``lock-discipline`` — registered lock-guarded fields are only mutated
    under their lock or in callers-verified ``*_under_lock`` methods;
  * ``plugin-purity`` — ``pre_filter_spec_pure`` plugins keep their spec
    path free of state reads/writes;
  * ``jit-boundary`` — nothing reachable from the jitted pipelines in
    ``ops/`` host-syncs or branches on tracers;
  * ``d2h-leak`` — every BLOCKING device→host fetch on the host side
    routes through ``Scheduler._d2h`` (the round-trip accounting choke
    point), nothing coerces/truth-tests a device value ad hoc;
  * ``donation`` — no caller reads a buffer after donating it to a
    ``donate_argnums`` kernel, and every donating kernel is documented
    in RESIDENT.md's donation/aliasing contract;
  * ``slice-clamp`` — ``dynamic_update_slice``/``.at[...].set`` with a
    traced start is only allowed with a padded destination, a provably
    static start, or a justified suppression (XLA clamps/drops
    out-of-range window writes SILENTLY);
  * ``retrace`` — no weak-typed Python scalars or unbucketed
    shape-derived static args leak into jit signatures;
  * ``shape`` — a symbolic named-dim interpreter over everything
    reachable from the jit roots (``# ktpu: axes(...)`` annotations +
    ``_KTPU_AXES`` schema tables) flags named-axis mismatches that
    rank-1 broadcasting would silently absorb, and scan-carry drift;
  * ``dtype`` — implicit promotions inside the integer kernels (true
    division, bool arithmetic, weak float widening, per-root
    ``accum(...)`` carry contracts);
  * ``shard`` — classifies every op against the ('pods','nodes') mesh:
    N-axis reductions/gathers must sit under a helper declared in the
    module's ``_KTPU_N_COLLECTIVES`` roster (the multichip collective
    inventory, MULTICHIP.md), and every roster entry must carry a
    ``resolved(collective|local|replicated): <how>`` sharding story —
    the worklist is a burn-down, not a parking lot;
  * ``breaker`` — every module-level jit root must carry a
    ``_KTPU_BREAKER_FALLBACKS`` entry (observability/kernels.py) naming
    the parity-certified engine its open circuit breaker routes to
    (``fallback(<engine>): <how>``) or an explicit ``no_fallback: <why>``
    waiver — the device-fault tier's drain story is analyzer-gated
    (ISSUE 15, CHAOS.md "Device seams").

Plus a runtime sanitizer (``KTPU_SANITIZE=1``, see ``sanitizer.py``),
including the jit recompile hook (``scheduler_tpu_jit_recompiles_total``)
and the eval_shape cross-check of the shape interpreter
(``scheduler_tpu_shape_check_failures_total``, ``shapecheck.py``).
Suppressions: ``# ktpu: allow(<rule>) — <reason>`` (reason mandatory).
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from kubernetes_tpu.analysis.core import (
    Finding,
    SourceModule,
    collect_bare_suppressions,
    load_source,
    render_json,
    render_text,
)
from kubernetes_tpu.analysis.breaker import BreakerChecker
from kubernetes_tpu.analysis.clamp import ClampChecker
from kubernetes_tpu.analysis.d2h import D2HChecker
from kubernetes_tpu.analysis.donation import DonationChecker
from kubernetes_tpu.analysis.jit import JitChecker
from kubernetes_tpu.analysis.locks import LockChecker
from kubernetes_tpu.analysis.purity import PurityChecker
from kubernetes_tpu.analysis.retrace import RetraceChecker
from kubernetes_tpu.analysis.shape import (
    DtypeChecker,
    ShapeChecker,
    ShardChecker,
    collective_roster,
)

_PKG_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PKG_ROOT)

# the shipped tree's checker targets
LOCK_MODULES = (
    "scheduler.py",
    os.path.join("cache", "cache.py"),
    os.path.join("cache", "mirror.py"),
    os.path.join("queue", "scheduling_queue.py"),
    # chaos subsystem: the injection log / one-shot ledger, per-seam
    # ordinal counters, and journal entries are all appended from
    # reflector threads and binding workers concurrently
    os.path.join("chaos", "faults.py"),
    os.path.join("chaos", "proxy.py"),
    os.path.join("chaos", "journal.py"),
    # wire codec: pure by design (empty registry) — vetted so any mutable
    # module state a future change adds lands under the lock checker;
    # frames are encoded on apiserver handler + watch-cache append threads
    os.path.join("client", "wire_codec.py"),
    # observability: the span buffer and flight-recorder ring are appended
    # from the scheduling loop, binding workers, informer threads, and HTTP
    # debug handlers; explain holds the Scheduler lock across its prep
    os.path.join("observability", "tracer.py"),
    os.path.join("observability", "flightrecorder.py"),
    os.path.join("observability", "explain.py"),
    # SLO tier: ingest runs on every flight-recorder producer thread,
    # snapshot/evaluate on HTTP handlers and the bench harness
    os.path.join("observability", "slo.py"),
    # device telemetry ledger: the scheduling loop records dispatches,
    # the planner thread records d2h, HTTP handlers read tables/costs
    os.path.join("observability", "kernels.py"),
    # control-plane pipeline tier: chains are stamped from apiserver
    # handler threads, reflector threads, informer handlers, and the
    # flight-recorder sink; scrape-time sync reads from HTTP handlers
    os.path.join("observability", "controlplane.py"),
    # workloads tier: the GangDirectory registry/bookkeeping is mutated by
    # informer handlers, the workloads dispatch, and bind-failure unwinds
    os.path.join("workloads", "gang.py"),
)
PURITY_MODULES = (
    os.path.join("framework", "plugins.py"),
    os.path.join("framework", "volume_plugins.py"),
    os.path.join("framework", "volumebinding.py"),
    os.path.join("framework", "dynamicresources.py"),
)
JIT_MODULES = (
    os.path.join("ops", "chain.py"),
    os.path.join("ops", "common.py"),
    os.path.join("ops", "coscheduling.py"),
    os.path.join("ops", "counterfactual.py"),
    os.path.join("ops", "dra.py"),
    os.path.join("ops", "explain.py"),
    os.path.join("ops", "fastpath.py"),
    os.path.join("ops", "filters.py"),
    os.path.join("ops", "gang.py"),
    os.path.join("ops", "pipeline.py"),
    os.path.join("ops", "preemption.py"),
    os.path.join("ops", "resident.py"),
    os.path.join("ops", "scores.py"),
    os.path.join("ops", "wave.py"),
    os.path.join("ops", "wire.py"),
)
# host modules that handle device values — the d2h-leak surface.
# ops/pipeline.py is targeted but allowlisted inside the checker (the
# standalone parity harness has no Scheduler, hence no counters to feed).
D2H_MODULES = (
    "scheduler.py",
    "fastpath.py",
    os.path.join("cache", "mirror.py"),
    os.path.join("cache", "device_mirror.py"),
    os.path.join("observability", "explain.py"),
    os.path.join("ops", "pipeline.py"),
    os.path.join("ops", "wire.py"),
)
# donation roots live in the kernels; the callers that can hold dead
# references are the scheduler and the device-mirror glue
DONATION_MODULES = JIT_MODULES + (
    os.path.join("cache", "device_mirror.py"),
    "scheduler.py",
    "fastpath.py",
)
CLAMP_MODULES = JIT_MODULES + (os.path.join("cache", "device_mirror.py"),)
# breaker-fallback roster rule (ISSUE 15): the jit-root surface plus the
# module that owns the _KTPU_BREAKER_FALLBACKS literal
BREAKER_MODULES = JIT_MODULES + (
    os.path.join("cache", "device_mirror.py"),
    os.path.join("observability", "kernels.py"),
)
# the symbolic shape/dtype/shard interpreter walks everything reachable
# from the jit roots; device_mirror's delta splicer is a root too
SHAPE_MODULES = JIT_MODULES + (os.path.join("cache", "device_mirror.py"),)
RETRACE_MODULES = JIT_MODULES + (
    os.path.join("cache", "device_mirror.py"),
    "scheduler.py",
    "fastpath.py",
    os.path.join("observability", "explain.py"),
)
# the repo-root bench driver fetches through the Scheduler's public API —
# checked when running from a source tree
_BENCH = os.path.join(_REPO_ROOT, "bench.py")
DONATION_CONTRACT_DOC = os.path.join(_REPO_ROOT, "RESIDENT.md")


def default_targets() -> Dict[str, List[str]]:
    d2h = [os.path.join(_PKG_ROOT, p) for p in D2H_MODULES]
    if os.path.exists(_BENCH):
        d2h.append(_BENCH)
    return {
        "locks": [os.path.join(_PKG_ROOT, p) for p in LOCK_MODULES],
        "purity": [os.path.join(_PKG_ROOT, p) for p in PURITY_MODULES],
        "jit": [os.path.join(_PKG_ROOT, p) for p in JIT_MODULES],
        "d2h": d2h,
        "donation": [os.path.join(_PKG_ROOT, p) for p in DONATION_MODULES],
        "clamp": [os.path.join(_PKG_ROOT, p) for p in CLAMP_MODULES],
        "retrace": [os.path.join(_PKG_ROOT, p) for p in RETRACE_MODULES],
        "shape": [os.path.join(_PKG_ROOT, p) for p in SHAPE_MODULES],
        "dtype": [os.path.join(_PKG_ROOT, p) for p in SHAPE_MODULES],
        "shard": [os.path.join(_PKG_ROOT, p) for p in SHAPE_MODULES],
        "breaker": [os.path.join(_PKG_ROOT, p) for p in BREAKER_MODULES],
    }


# per-rule wall time of the most recent run_analysis() call, seconds —
# surfaced by `--json` (analyzer-perf telemetry; the shape/dtype/shard
# families share ONE interpretation, whose cost lands on 'shape')
last_rule_seconds: Dict[str, float] = {}


def run_analysis(
    targets: Optional[Dict[str, Sequence[str]]] = None,
) -> List[Finding]:
    """Run every checker over its target file set; returns ALL findings
    (post-suppression), sorted by path/line.  ``targets`` maps checker key
    ('locks'/'purity'/'jit'/'d2h'/'donation'/'clamp'/'retrace'/'shape'/
    'dtype'/'shard') → file paths; defaults to the shipped tree.  The
    donation contract document (RESIDENT.md) is only consulted on
    shipped-tree runs — fixture runs override 'donation' and skip it.

    Every checker shares one parsed AST per file (core.load_source's
    mtime-keyed process cache), and the shape/dtype/shard families share
    one symbolic interpretation per target set.
    """
    import time as _time

    t = dict(default_targets())
    fixture_donation = targets is not None and "donation" in targets
    if targets is not None:
        t.update({k: list(v) for k, v in targets.items()})

    loaded: Dict[str, SourceModule] = {}

    def load(paths: Sequence[str]) -> List[SourceModule]:
        out = []
        for p in paths:
            key = os.path.abspath(p)
            if key not in loaded:
                loaded[key] = load_source(p)
            out.append(loaded[key])
        return out

    findings: List[Finding] = []
    last_rule_seconds.clear()

    contract = None
    if not fixture_donation and os.path.exists(DONATION_CONTRACT_DOC):
        with open(DONATION_CONTRACT_DOC, "r", encoding="utf-8") as f:
            contract = f.read()

    engine_cache: Dict[tuple, object] = {}
    plan = (
        ("locks", LockChecker, {}),
        ("purity", PurityChecker, {}),
        ("jit", JitChecker, {}),
        ("d2h", D2HChecker, {"root_mods": lambda: load(t.get("jit", ()))}),
        ("donation", DonationChecker, {"contract_text": lambda: contract}),
        ("clamp", ClampChecker, {}),
        ("retrace", RetraceChecker, {}),
        ("shape", ShapeChecker, {"engine_cache": lambda: engine_cache}),
        ("dtype", DtypeChecker, {"engine_cache": lambda: engine_cache}),
        ("shard", ShardChecker, {"engine_cache": lambda: engine_cache}),
        ("breaker", BreakerChecker, {}),
    )
    for key, cls, extra in plan:
        start = _time.perf_counter()
        checker = cls()
        kwargs = {k: v() for k, v in extra.items()}
        checker.run(load(t.get(key, ())), **kwargs)
        findings.extend(checker.findings)
        last_rule_seconds[checker.rule] = _time.perf_counter() - start

    findings.extend(collect_bare_suppressions(loaded.values()))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


__all__ = [
    "Finding",
    "run_analysis",
    "default_targets",
    "collective_roster",
    "render_text",
    "render_json",
]
