"""Runtime eval_shape cross-check of the symbolic shape interpreter
(KTPU_SANITIZE=1; the dynamic half of the ``shape`` rule).

The static interpreter (analysis/shape.py) infers every jit root's
return shapes from its ``# ktpu: axes(...)`` annotation.  If the
interpreter's model of an op drifts from jax's (or an annotation drifts
from the code), its findings silently rot.  This module closes the
loop: for every annotated root it builds a REPRESENTATIVE instantiation
— ``jax.ShapeDtypeStruct`` leaves shaped by a small distinct-prime size
assignment, the declared ``static(...)`` values, a real PRNG key for
``key`` params — runs ``jax.eval_shape`` (abstract tracing, no
compilation, no device), and compares the traced output pytree against
the interpreter's inferred symbolic return evaluated at the same sizes.

Any disagreement is a CROSS-CHECK FAILURE: either the kernel changed
shape behaviour the annotation/interpreter didn't follow, or the
interpreter mis-models an op.  Failures count into
``scheduler_tpu_shape_check_failures_total{fn=}`` (wired by the
scheduler under KTPU_SANITIZE, once per process) and fail the tier-1
gate via tests/test_static_analysis.py.

Roots marked ``# ktpu: noinstantiate — <reason>`` are excluded (their
shapes live outside the signature, e.g. wire's lru_cache treedefs);
``skipped()`` reports them so the exclusion list stays visible.
"""

from __future__ import annotations

import ast
import importlib
import os
from typing import Dict, List, Optional, Tuple

from kubernetes_tpu.analysis.shape import (
    Arr,
    DictV,
    RecV,
    TupV,
    Unknown,
    _DTYPES,
    _as_lin,
    dim_str,
    root_summaries,
    spec_to_aval,
)

# PAIRWISE-DISTINCT sizes per canonical axis, so a transposed or
# mislabeled dim CANNOT alias another axis's size (the whole point of
# the cross-check — a swap of any two named axes changes a traced
# shape).  Symbols not listed (private DTable widths, opaque composites)
# fall back to DEFAULT_DIM, which deliberately collides only with other
# unlisted symbols (they are per-instance namespaced and never unify).
DEFAULT_SIZES = {
    "P": 5,
    "N": 7,
    "S": 11,
    "Rn": 4,  # >= N_FIXED_LANES
    "Rp": 6,  # pod lanes may exceed node lanes (extended resources)
    "C": 2,
    "A": 8,
    "K": 9,
    "V": 31,
    "TA": 10,
    "TL": 12,
    "U": 13,
    "UP": 14,
    "E": 15,
    "M": 16,
    "NS": 17,
    "IMG": 18,
    "IP": 19,
    "G": 20,
    "Kd": 21,
    "Kd2": 22,
    "Tsp": 23,
    "Tip": 24,
    "NT": 25,
    "PT": 26,
    "L": 27,
    # workloads tier (ops/coscheduling.py, ops/dra.py): device slots per
    # node, attribute slots, DRA request/selector/value slots, claim
    # slots, per-pod claim refs, gang slots, per-pod PV slots
    "DD": 28,
    "DA": 29,
    "DQ": 33,
    "DS": 34,
    "DV": 35,
    "CL": 37,
    "CQ": 38,
    "G2": 39,
    "PV2": 40,
    "VT": 41,
    # wave port-term carry (ops/wave.py) and preemption batch-peer rows
    # (ops/preemption.py)
    "Tpt": 42,
    "B2": 43,
    # counterfactual planner tier (ops/counterfactual.py): the leading
    # fork axis of the batched [KF, P, N] what-if kernel ("K" is taken by
    # label keys)
    "KF": 45,
    "B": 64,
}
assert len(set(DEFAULT_SIZES.values())) == len(DEFAULT_SIZES)
DEFAULT_DIM = 3

_NP_DTYPES = {
    "bool": "bool_",
    "i8": "int8",
    "i16": "int16",
    "i32": "int32",
    "i64": "int64",
    "u8": "uint8",
    "u16": "uint16",
    "u32": "uint32",
    "u64": "uint64",
    "f16": "float16",
    "f32": "float32",
    "f64": "float64",
}

# where the annotated classes live (resolution order)
_CLASS_MODULES = (
    "kubernetes_tpu.ops.common",
    "kubernetes_tpu.ops.gang",
)


def _concrete_dim(d, sizes) -> Optional[int]:
    lin = _as_lin(d)
    if lin is None:
        return None
    const, syms = lin
    out = const
    for s, c in syms:
        out += c * sizes.get(s, DEFAULT_DIM)
    return out


def _np_dtype(dt: str):
    import numpy as np

    return getattr(np, _NP_DTYPES[dt])


def _resolve_class(name: str):
    for modname in _CLASS_MODULES:
        try:
            mod = importlib.import_module(modname)
        except Exception:  # noqa: BLE001 — partial trees
            continue
        obj = getattr(mod, name, None)
        if obj is not None:
            return obj
    return None


def _build_value(av, sizes):
    """Abstract value → instantiation (ShapeDtypeStruct leaves)."""
    import jax

    if isinstance(av, Arr):
        if av.shape is None or av.dtype is None:
            raise ValueError("unconcretizable array spec")
        dims = [_concrete_dim(d, sizes) for d in av.shape]
        if any(d is None for d in dims):
            raise ValueError("unconcretizable dim")
        return jax.ShapeDtypeStruct(tuple(dims), _np_dtype(av.dtype))
    if isinstance(av, RecV):
        cls = _resolve_class(av.cls)
        if cls is None:
            raise ValueError(f"class {av.cls} not importable")
        fields = {k: _build_value(v, sizes) for k, v in av.fields.items()}
        return cls(**fields)
    if isinstance(av, TupV):
        return tuple(_build_value(i, sizes) for i in av.items)
    raise ValueError(f"unconcretizable spec {av!r}")


def _compare(path: str, inferred, actual, sizes, problems: List[str]) -> None:
    """Walk the inferred symbolic value against the eval_shape pytree.
    Unknown / unknown dims are wildcards — the check only bites where the
    interpreter CLAIMED knowledge."""
    if isinstance(inferred, Unknown):
        return
    if isinstance(inferred, Arr):
        shape = getattr(actual, "shape", None)
        if shape is None:
            problems.append(
                f"{path}: inferred array {inferred!r}, traced {type(actual).__name__}"
            )
            return
        if inferred.shape is not None:
            if len(inferred.shape) != len(shape):
                problems.append(
                    f"{path}: inferred rank {len(inferred.shape)} "
                    f"({_fmt_shape(inferred.shape, sizes)}), traced shape "
                    f"{tuple(shape)}"
                )
                return
            for i, (d, real) in enumerate(zip(inferred.shape, shape)):
                want = _concrete_dim(d, sizes)
                if want is not None and want != real:
                    problems.append(
                        f"{path}: axis {i} inferred {dim_str(d)}={want}, "
                        f"traced {real}"
                    )
        if inferred.dtype is not None:
            import numpy as np

            want_dt = np.dtype(_np_dtype(inferred.dtype))
            got_dt = np.dtype(getattr(actual, "dtype", None))
            if want_dt != got_dt:
                problems.append(
                    f"{path}: inferred dtype {want_dt}, traced {got_dt}"
                )
        return
    if isinstance(inferred, TupV):
        items = None
        if isinstance(actual, (tuple, list)):
            items = list(actual)
        elif hasattr(actual, "_fields"):  # NamedTuple output
            items = list(actual)
        if items is None:
            problems.append(
                f"{path}: inferred {len(inferred.items)}-tuple, traced "
                f"{type(actual).__name__}"
            )
            return
        if len(items) != len(inferred.items):
            problems.append(
                f"{path}: inferred {len(inferred.items)} elements, traced "
                f"{len(items)}"
            )
            return
        for i, (iv, av) in enumerate(zip(inferred.items, items)):
            _compare(f"{path}[{i}]", iv, av, sizes, problems)
        return
    if isinstance(inferred, DictV):
        if not isinstance(actual, dict):
            problems.append(
                f"{path}: inferred dict, traced {type(actual).__name__}"
            )
            return
        missing = set(inferred.entries) - set(actual)
        extra = set(actual) - set(inferred.entries)
        if missing or extra:
            problems.append(
                f"{path}: key drift — inferred-only {sorted(missing)}, "
                f"traced-only {sorted(extra)}"
            )
        for k in set(inferred.entries) & set(actual):
            _compare(f"{path}[{k!r}]", inferred.entries[k], actual[k],
                     sizes, problems)
        return
    if isinstance(inferred, RecV):
        for k, iv in inferred.fields.items():
            if hasattr(actual, k):
                _compare(f"{path}.{k}", iv, getattr(actual, k), sizes,
                         problems)
        return
    # host statics / dims in return position: nothing to compare


def _fmt_shape(shape, sizes):
    return "[" + ", ".join(dim_str(d) for d in shape) + "]"


def _instantiate_args(rec, ann, engine, sizes):
    """(traced kwargs, static kwargs) for the root call, per the
    annotation: axes() params get ShapeDtypeStructs/class instances,
    `key` params a real PRNGKey, static(...) params their declared
    values; everything else relies on its default.  Statics are closed
    over with functools.partial — jax.eval_shape abstracts every direct
    argument, and a tracer in a static_argnames slot is unhashable."""
    import jax

    kwargs = {}
    statics = {}
    fnode = rec.node
    params = {p.arg for p in fnode.args.args + fnode.args.kwonlyargs}
    has_default = set()
    pos = fnode.args.args
    for p in pos[len(pos) - len(fnode.args.defaults):]:
        has_default.add(p.arg)
    for p, d in zip(fnode.args.kwonlyargs, fnode.args.kw_defaults):
        if d is not None:
            has_default.add(p.arg)
    for name, expr in ann.axes.items():
        if name not in params:
            continue
        if isinstance(expr, ast.Name) and expr.id == "key":
            kwargs[name] = jax.random.PRNGKey(0)
            continue
        av = spec_to_aval(expr, engine.class_tables, ns=name)
        if isinstance(av, Unknown):
            continue  # `any` — leave to the default
        kwargs[name] = _build_value(av, sizes)
    for name, value in ann.static_values.items():
        if name in params:
            statics[name] = value
    for p in params:
        if p not in kwargs and p not in statics and p not in has_default:
            raise ValueError(f"parameter {p!r} has no annotation and no "
                             "default — cannot instantiate")
    return kwargs, statics


def cross_check(sizes: Optional[Dict[str, int]] = None,
                mods=None) -> Dict[str, List[str]]:
    """Run the eval_shape cross-check over every instantiable annotated
    root.  Returns {root → [mismatch descriptions]}; empty dict = all
    clean.  Instantiation failures are reported as mismatches too — a
    root that can no longer be built from its annotation IS drift.
    """
    import jax

    from kubernetes_tpu.analysis import SHAPE_MODULES, _PKG_ROOT
    from kubernetes_tpu.analysis.core import load_source

    sizes = dict(DEFAULT_SIZES, **(sizes or {}))
    if mods is None:
        mods = [load_source(os.path.join(_PKG_ROOT, p))
                for p in SHAPE_MODULES]
    out: Dict[str, List[str]] = {}
    for key, rec, ann, inferred, engine in root_summaries(mods):
        if ann.noinstantiate is not None or not ann.has_axes:
            continue
        if "." in rec.qual:
            # a nested root cannot be imported by qualname; silently
            # skipping would lose coverage invisibly — demand the
            # reasoned opt-out instead
            out[key] = [
                "nested jit root cannot be instantiated from its "
                "annotation — add `# ktpu: noinstantiate — <reason>` "
                "(and cover it with an end-to-end test)"
            ]
            continue
        modname = _module_name_for(rec.mod.path)
        problems: List[str] = []
        try:
            import functools

            mod = importlib.import_module(modname)
            fn = getattr(mod, rec.qual)
            kwargs, statics = _instantiate_args(rec, ann, engine, sizes)
            if statics:
                fn = functools.partial(fn, **statics)
            traced = jax.eval_shape(fn, **kwargs)
        except Exception as e:  # noqa: BLE001 — any failure IS a finding
            out[key] = [f"instantiation/trace failed: {e!r:.300}"]
            continue
        # an int-valued static (g_cap=4) IS the concrete size of any
        # return dim the interpreter named after it — bind it for the
        # comparison (canonical axis sizes still win)
        local_sizes = dict(sizes)
        for sname, sval in statics.items():
            if isinstance(sval, int) and not isinstance(sval, bool):
                local_sizes.setdefault(sname, sval)
        _compare("return", inferred, traced, local_sizes, problems)
        if problems:
            out[key] = problems
    return out


def skipped(mods=None) -> Dict[str, str]:
    """{root → reason} for roots excluded via `# ktpu: noinstantiate`."""
    from kubernetes_tpu.analysis import SHAPE_MODULES, _PKG_ROOT
    from kubernetes_tpu.analysis.core import load_source

    if mods is None:
        mods = [load_source(os.path.join(_PKG_ROOT, p))
                for p in SHAPE_MODULES]
    out = {}
    for key, rec, ann, _inferred, _eng in root_summaries(mods):
        if ann.noinstantiate is not None:
            out[key] = ann.noinstantiate
    return out


def _module_name_for(path: str) -> str:
    parts = os.path.normpath(os.path.abspath(path)).split(os.sep)
    if "kubernetes_tpu" in parts:
        idx = len(parts) - 1 - parts[::-1].index("kubernetes_tpu")
        return ".".join(parts[idx:])[: -len(".py")]
    # out-of-tree module (test fixtures): import by basename via sys.path
    return parts[-1][: -len(".py")]
