"""Breaker-fallback roster checker (rule ``breaker``, ISSUE 15).

Every module-level jit root is a dispatch the per-kernel circuit breaker
can park — and a parked kernel with no registered fallback is a drain
that silently stops.  This rule makes the fallback story a BURN-DOWN,
the same discipline as the shard rule's ``resolved(...)`` roster: each
discovered root must carry an entry in ``_KTPU_BREAKER_FALLBACKS``
(observability/kernels.py) whose value leads with

    ``fallback(<engine>): <how>``   — the parity-certified engine that
                                      replaces it when the breaker opens
    ``no_fallback: <why>``          — an explicit waiver (diagnostic-only
                                      roots, the parity harness itself)

Roots are discovered statically (module-level defs decorated ``jax.jit``
or ``functools.partial(jax.jit, ...)`` — the same surface the sanitizer's
runtime discovery walks); the roster literal is read without importing
anything, so fixture files carrying their own roots and rosters analyze
identically.  Stale entries (naming a vanished root of an analyzed
module) are findings too — the roster must not rot into a parking lot.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Sequence, Tuple

from kubernetes_tpu.analysis.core import (
    Checker,
    RULE_BREAKER,
    SourceModule,
    module_literal,
)

ROSTER_NAME = "_KTPU_BREAKER_FALLBACKS"

# a registered story must lead with its mechanism and carry substance
_STORY_RE = re.compile(r"^(fallback\([a-z0-9_-]+\):\s+\S|no_fallback:\s+\S)")


def _is_jit_decorator(d: ast.expr) -> bool:
    """``@jax.jit`` or ``@functools.partial(jax.jit, ...)`` (either
    imported-module or from-imported ``partial`` spelling)."""
    if isinstance(d, ast.Attribute) and d.attr == "jit":
        return True
    if isinstance(d, ast.Call):
        f = d.func
        named_partial = (
            isinstance(f, ast.Attribute) and f.attr == "partial"
        ) or (isinstance(f, ast.Name) and f.id == "partial")
        if named_partial and d.args:
            a0 = d.args[0]
            if isinstance(a0, ast.Attribute) and a0.attr == "jit":
                return True
    return False


def discover_roots(mod: SourceModule) -> Dict[str, int]:
    """``{"<module short>.<fn>": def lineno}`` for every module-level jit
    root of one analyzed file."""
    short = os.path.basename(mod.path)
    if short.endswith(".py"):
        short = short[:-3]
    out: Dict[str, int] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.FunctionDef) and any(
            _is_jit_decorator(d) for d in node.decorator_list
        ):
            out[f"{short}.{node.name}"] = node.lineno
    return out


def _roster_of(mod: SourceModule) -> Tuple[Dict[str, str], Dict[str, int]]:
    """(entries, entry key linenos) of a module's roster literal."""
    roster = module_literal(mod.tree, ROSTER_NAME)
    if not isinstance(roster, dict):
        return {}, {}
    lines: Dict[str, int] = {}
    for node in mod.tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == ROSTER_NAME
            for t in node.targets
        ):
            if isinstance(node.value, ast.Dict):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant):
                        lines[str(k.value)] = k.lineno
    return {str(k): str(v) for k, v in roster.items()}, lines


class BreakerChecker(Checker):
    rule = RULE_BREAKER

    def run(self, mods: Sequence[SourceModule]) -> None:
        roster: Dict[str, str] = {}
        roster_lines: Dict[str, Tuple[SourceModule, int]] = {}
        roots: Dict[str, Tuple[SourceModule, int]] = {}
        analyzed_shorts = set()
        # the rule engages only when the analyzed set carries a roster:
        # the shipped tree always does (observability/kernels.py is a
        # registered target), and a fixture opting in defines its own —
        # a lone jit-root fixture for ANOTHER rule must not cross-fire.
        # Deleting the shipped roster outright is caught by the runtime
        # coverage test (jit-root roster ⊆ breaker_fallbacks()).
        if not any(
            module_literal(mod.tree, ROSTER_NAME) is not None for mod in mods
        ):
            return
        for mod in mods:
            short = os.path.basename(mod.path)
            if short.endswith(".py"):
                short = short[:-3]
            analyzed_shorts.add(short)
            entries, lines = _roster_of(mod)
            for key, story in entries.items():
                roster[key] = story
                roster_lines[key] = (mod, lines.get(key, 1))
            for name, lineno in discover_roots(mod).items():
                roots[name] = (mod, lineno)

        for name, (mod, lineno) in sorted(roots.items()):
            story = roster.get(name)
            if story is None:
                self.emit(
                    mod,
                    lineno,
                    f"jit root {name} has no breaker fallback "
                    f"registration: add a {ROSTER_NAME} entry leading "
                    "with 'fallback(<engine>): <how>' naming the "
                    "parity-certified engine an open breaker routes to, "
                    "or an explicit 'no_fallback: <why>' waiver",
                )
            elif not _STORY_RE.match(story):
                rmod, rline = roster_lines[name]
                self.emit(
                    rmod,
                    rline,
                    f"breaker fallback entry for {name} does not lead "
                    "with 'fallback(<engine>): <how>' or "
                    "'no_fallback: <why>' — the roster is a burn-down, "
                    "not a parking lot",
                )
        # stale entries: the named module was analyzed but the root is gone
        for key, (rmod, rline) in sorted(roster_lines.items()):
            short = key.split(".", 1)[0]
            if short in analyzed_shorts and key not in roots:
                self.emit(
                    rmod,
                    rline,
                    f"breaker fallback entry {key} names no existing "
                    "module-level jit root — delete the stale entry",
                )
