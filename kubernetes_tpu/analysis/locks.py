"""Lock-discipline checker (rule: ``lock-discipline``).

The concurrency-bearing modules declare a ``_KTPU_GUARDED`` literal that
registers which fields are guarded by which lock:

    _KTPU_GUARDED = {
        "Scheduler": {
            "lock": "_mu",
            "guards": {"cache": "Cache", "queue": "SchedulingQueue", ...},
            "requires_lock": ["_view_pod_added", ...],
        },
        "Cache": {
            "external_lock": "Scheduler._mu",
            "readonly": ["is_assumed", "real_nodes", ...],
        },
    }

Enforced invariants:

  * a MUTATION routed through a guarded field (attribute/subscript
    assignment, augmented assignment, delete, or a call to any method not
    registered read-only) must happen inside a ``with <lock>`` block, or
    inside a method whose callers are verified to hold the lock — a
    ``*_under_lock``/``*_locked`` method or one listed in
    ``requires_lock``;
  * every intra-package call site of such a lock-expecting method must
    itself be in a lock-held context (the call-graph walk — transitively,
    since lock-expecting callers are only accepted when all THEIR callers
    verify);
  * methods of a class registered with ``external_lock`` are contractually
    entered with that lock held (their bodies are exempt); calls INTO them
    from other code follow the mutating-vs-readonly rules above.

Simple aliases are tracked per function: ``done = self.queue.done`` makes
a later ``done(uid)`` a guarded call, and ``cn = self.cache.nodes.get(x)``
taints ``cn`` so ``cn.node = ...`` needs the lock.

Reads are deliberately NOT flagged: the codebase's snapshot/epoch
machinery does racy reads by design (generation watermarks); it is the
writes that corrupt.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.analysis.core import (
    RULE_LOCK,
    Checker,
    SourceModule,
    dotted_name,
    module_literal,
)

REGISTRY_NAME = "_KTPU_GUARDED"

# method names safe on ANY guarded object without the lock (builtin
# container accessors and pure introspection)
GENERIC_READONLY = {
    "get",
    "keys",
    "values",
    "items",
    "copy",
    "index",
    "count",
    "stats",
}

LOCK_SUFFIXES = ("_under_lock", "_locked")


def _is_lock_expecting(name: str, requires: Set[str]) -> bool:
    return name.endswith(LOCK_SUFFIXES) or name in requires


class _ClassSpec:
    def __init__(self, name: str, spec: dict):
        self.name = name
        self.lock: Optional[str] = spec.get("lock")
        self.external_lock: Optional[str] = spec.get("external_lock")
        self.guards: Dict[str, Optional[str]] = dict(spec.get("guards", {}))
        self.requires_lock: Set[str] = set(spec.get("requires_lock", ()))
        self.readonly: Set[str] = set(spec.get("readonly", ()))


class LockChecker(Checker):
    rule = RULE_LOCK

    def __init__(self) -> None:
        super().__init__()
        # externally-guarded class name → readonly method set
        self._ext_readonly: Dict[str, Set[str]] = {}
        # guarded field name → guarded class name (or None for plain)
        self._field_class: Dict[str, Optional[str]] = {}
        self._requires: Set[str] = set()
        # (mod, funcname-qual, line) of unverified lock-expecting callsites
        self._lock_names: Set[str] = set()

    # ----- entry point ------------------------------------------------------

    def run(self, mods: List[SourceModule]) -> None:
        specs: List[Tuple[SourceModule, _ClassSpec]] = []
        for mod in mods:
            reg = module_literal(mod.tree, REGISTRY_NAME)
            if not isinstance(reg, dict):
                continue
            for cls_name, spec in reg.items():
                if isinstance(spec, dict):
                    specs.append((mod, _ClassSpec(cls_name, spec)))
        for _, spec in specs:
            if spec.external_lock is not None:
                self._ext_readonly[spec.name] = spec.readonly
            for f, cls in spec.guards.items():
                self._field_class[f] = cls
            self._requires |= spec.requires_lock
            if spec.lock:
                self._lock_names.add(spec.lock)
        if not self._lock_names:
            self._lock_names = {"_mu"}

        # map guarded class name → its registered readonly set (guards may
        # point at externally-guarded classes declared in ANOTHER module)
        for mod in mods:
            self._check_module(mod)

    # ----- per-module walk --------------------------------------------------

    def _check_module(self, mod: SourceModule) -> None:
        ext_classes = set(self._ext_readonly)
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                exempt = node.name in ext_classes
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        # __init__ runs before the object is published to
                        # any other thread — the standard ctor exemption
                        self._check_function(
                            mod,
                            item,
                            exempt_body=exempt or item.name == "__init__",
                        )
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(mod, node, exempt_body=False)

    def _check_function(
        self, mod: SourceModule, fn: ast.FunctionDef, exempt_body: bool
    ) -> None:
        held = exempt_body or _is_lock_expecting(fn.name, self._requires)
        aliases: Dict[str, str] = {}  # local name → guarded field it taints
        self._walk(mod, list(fn.body), held, aliases, exempt_body)

    def _walk(
        self,
        mod: SourceModule,
        stmts: List[ast.stmt],
        held: bool,
        aliases: Dict[str, str],
        exempt: bool,
    ) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a nested closure runs later, on another thread's schedule:
                # the enclosing lock scope does NOT carry over
                self._check_function(mod, st, exempt_body=exempt)
                continue
            if isinstance(st, ast.With):
                if any(self._is_lock_acquire(item.context_expr) for item in st.items):
                    self._walk(mod, list(st.body), True, aliases, exempt)
                    continue
                self._check_stmt_exprs(mod, st, held, aliases, exempt)
                self._walk(mod, list(st.body), held, aliases, exempt)
                continue
            self._check_stmt_exprs(mod, st, held, aliases, exempt)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    self._walk(mod, list(sub), held, aliases, exempt)
            for handler in getattr(st, "handlers", ()) or ():
                self._walk(mod, list(handler.body), held, aliases, exempt)

    # ----- statement / expression checks ------------------------------------

    def _check_stmt_exprs(
        self,
        mod: SourceModule,
        st: ast.stmt,
        held: bool,
        aliases: Dict[str, str],
        exempt: bool,
    ) -> None:
        # assignment targets
        if isinstance(st, ast.Assign):
            for t in st.targets:
                self._check_target(mod, t, held, aliases, exempt)
            self._track_alias(st, aliases)
            self._check_expr_calls(mod, st.value, held, aliases, exempt)
            return
        if isinstance(st, ast.AugAssign):
            self._check_target(mod, st.target, held, aliases, exempt)
            self._check_expr_calls(mod, st.value, held, aliases, exempt)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._check_target(mod, t, held, aliases, exempt)
            return
        # everything else: scan only the statement's own expressions, not
        # nested statement bodies (handled by _walk)
        for child in ast.iter_child_nodes(st):
            if isinstance(child, (ast.stmt, ast.excepthandler)):
                continue
            self._check_expr_calls(mod, child, held, aliases, exempt)

    def _check_target(
        self,
        mod: SourceModule,
        target: ast.expr,
        held: bool,
        aliases: Dict[str, str],
        exempt: bool,
    ) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._check_target(mod, el, held, aliases, exempt)
            return
        if isinstance(target, ast.Name):
            return  # plain local rebind is never a guarded mutation
        base = target
        while isinstance(base, ast.Subscript):
            base = base.value
        field = self._guarded_field_of(base, aliases)
        if field is not None and not held and not exempt:
            self.emit(
                mod,
                target.lineno,
                f"mutation of lock-guarded state through {field!r} outside "
                f"the guarding lock",
            )

    def _check_expr_calls(
        self,
        mod: SourceModule,
        expr: ast.expr,
        held: bool,
        aliases: Dict[str, str],
        exempt: bool,
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            method: Optional[str] = None
            field: Optional[str] = None
            if isinstance(func, ast.Attribute):
                method = func.attr
                field = self._guarded_field_of(func.value, aliases)
            elif isinstance(func, ast.Name):
                method = func.id
                if func.id in aliases:
                    # alias of a bound method of a guarded object
                    field = aliases[func.id]
            if method is None:
                continue
            # (a) mutating call on guarded state
            if field is not None and not held and not exempt:
                if not self._is_readonly(field, method):
                    self.emit(
                        mod,
                        node.lineno,
                        f"call to mutating method {method!r} on lock-guarded "
                        f"{field!r} outside the guarding lock",
                    )
            # (b) call-graph verification of lock-expecting functions
            if (
                _is_lock_expecting(method, self._requires)
                and not held
                and not exempt
            ):
                self.emit(
                    mod,
                    node.lineno,
                    f"call to {method!r} (contract: lock already held) from "
                    f"a context not verified to hold the lock",
                )

    # ----- helpers ----------------------------------------------------------

    def _track_alias(self, st: ast.Assign, aliases: Dict[str, str]) -> None:
        if len(st.targets) != 1 or not isinstance(st.targets[0], ast.Name):
            return
        name = st.targets[0].id
        value = st.value
        # method/object alias: local = <chain through a guarded field>
        src = value
        if isinstance(src, ast.Call):
            src = src.func
            # a call RESULT taints only when routed through a guarded field
            # via a readonly accessor (e.g. nodes.get) — anything else
            # returns fresh data
        field = self._guarded_field_of(src, aliases)
        if field is not None:
            aliases[name] = field
        elif name in aliases:
            del aliases[name]  # rebound to something unguarded

    def _guarded_field_of(
        self, node: ast.expr, aliases: Dict[str, str]
    ) -> Optional[str]:
        """The guarded field a Name/Attribute chain routes through, if any.

        ``self.cache.nodes`` → 'cache'; ``self._s.queue`` → 'queue'; a Name
        that aliases guarded state resolves through the alias table.
        """
        dn = dotted_name(node)
        if dn is None:
            # chains through subscripts/calls: peel and retry on the value
            while isinstance(node, (ast.Subscript, ast.Call)):
                node = node.value if isinstance(node, ast.Subscript) else node.func
            dn = dotted_name(node)
            if dn is None:
                return None
        parts = dn.split(".")
        root = parts[0]
        if root in aliases:
            return aliases[root]
        # the ROOT name only matches through the alias table — a bare local
        # that happens to be called `cache` (memo dicts, loop locals) is not
        # the scheduler's cache; guarded fields are reached as ATTRIBUTES
        # (self.cache…, self._s.queue…)
        for comp in parts[1:]:
            if comp in self._field_class:
                return comp
        return None

    def _is_readonly(self, field: str, method: str) -> bool:
        if method in GENERIC_READONLY:
            return True
        cls = self._field_class.get(field)
        if cls is not None and method in self._ext_readonly.get(cls, ()):
            return True
        return False

    def _is_lock_acquire(self, expr: ast.expr) -> bool:
        dn = dotted_name(expr)
        if dn is None:
            return False
        return dn.split(".")[-1] in self._lock_names
