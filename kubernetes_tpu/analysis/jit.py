"""Jit-boundary hygiene checker (rule: ``jit-boundary``).

Everything reachable from a ``jax.jit``-decorated function in ``ops/``
executes under tracing: an ``.item()``, a ``float()/int()/bool()`` on an
array, an ``np.*`` call on a device value, or a Python branch on a tracer
either crashes at trace time in a rarely-exercised shape configuration or
— worse — silently forces a host sync that erases the drain overlap wins.

The checker is a small abstract interpretation over STATICNESS:

  * module-level globals are trace-time constants → static;
  * a jitted root's parameters are traced except its ``static_argnames``;
  * a helper's parameter is static when annotated ``int/bool/str/float``
    or when every intra-package call site passes a static argument
    (computed to fixpoint over the call graph, reachable-from-roots only);
  * ``.shape``/``.ndim``/``.dtype``/``.size`` and ``len()`` NEUTRALIZE:
    they are static even on traced arrays (shapes are compile-time under
    jit) — this is what lets genuinely shape-driven host Python inside
    kernels pass without suppressions.

Violations (all reported under the one ``jit-boundary`` rule):

  * ``.item()`` / ``.tolist()`` / ``.block_until_ready()`` on a traced
    value, and ``jax.device_get(...)`` of one;
  * ``np.<fn>(traced)`` — numpy coerces through the host;
  * ``int()/float()/bool()`` of a traced value;
  * ``if``/``while``/``assert`` conditions, and ``for``/comprehension
    iterables, that are traced.

Host-side wrappers in ``ops/`` (``from_host`` packers, dispatch glue) are
exempt by construction: they are not reachable from any jitted root.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from kubernetes_tpu.analysis.core import (
    RULE_JIT,
    Checker,
    SourceModule,
    dotted_name,
)

NEUTRAL_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize"}
SYNC_METHODS = {"item", "tolist", "block_until_ready"}
STATIC_ANNOTATIONS = {"int", "bool", "str", "float"}
CAST_BUILTINS = {"int", "float", "bool"}
# builtins whose result is static whenever their arguments are
LEN_LIKE = {"len"}
MAX_FIXPOINT_ROUNDS = 12


def _jit_decoration(fn: ast.FunctionDef) -> Optional[Tuple[bool, Set[str]]]:
    """(is_jitted, static_argnames) when a decorator is jax.jit or a
    partial over it."""
    for dec in fn.decorator_list:
        dn = dotted_name(dec)
        if dn is not None and dn.split(".")[-1] == "jit":
            return True, set()
        if isinstance(dec, ast.Call):
            dnc = dotted_name(dec.func)
            if dnc is not None and dnc.split(".")[-1] == "jit":
                return True, _static_argnames(dec)
            if dnc is not None and dnc.split(".")[-1] == "partial" and dec.args:
                first = dotted_name(dec.args[0])
                if first is not None and first.split(".")[-1] == "jit":
                    return True, _static_argnames(dec)
    return None


def _static_argnames(call: ast.Call) -> Set[str]:
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            try:
                v = ast.literal_eval(kw.value)
            except (ValueError, SyntaxError):
                return set()
            if isinstance(v, str):
                return {v}
            return set(v)
    return set()


class _FuncInfo:
    def __init__(self, key: str, mod: SourceModule, node: ast.FunctionDef,
                 enclosing: Optional["_FuncInfo"] = None):
        self.key = key  # "module_basename:qualname"
        self.mod = mod
        self.node = node
        self.enclosing = enclosing
        self.is_root = False
        self.static_argnames: Set[str] = set()
        self.params = [a.arg for a in node.args.args + node.args.kwonlyargs]
        self.annotated_static = {
            a.arg
            for a in node.args.args + node.args.kwonlyargs
            if a.annotation is not None
            and isinstance(a.annotation, ast.Name)
            and a.annotation.id in STATIC_ANNOTATIONS
        }
        # param → static?  (fixpoint state; optimistic start)
        self.param_static: Dict[str, bool] = {}


class JitChecker(Checker):
    rule = RULE_JIT

    def __init__(self) -> None:
        super().__init__()
        self.funcs: Dict[str, _FuncInfo] = {}
        self.by_module: Dict[str, Dict[str, str]] = {}  # mod base → name → key
        self.aliases: Dict[str, Dict[str, str]] = {}  # mod base → alias → module base
        self.np_roots: Dict[str, Set[str]] = {}  # mod base → names bound to numpy
        self.jax_roots: Dict[str, Set[str]] = {}
        self.reachable: Set[str] = set()
        # callee key → param → all-static-so-far
        self._callsite_static: Dict[str, Dict[str, bool]] = {}
        self._emit_mode = False

    # ----- entry point ------------------------------------------------------

    def run(self, mods: List[SourceModule]) -> None:
        for mod in mods:
            self._index_module(mod)

        roots = [f for f in self.funcs.values() if f.is_root]
        for f in self.funcs.values():
            init = {}
            for p in f.params:
                if f.is_root:
                    init[p] = p in f.static_argnames or p in f.annotated_static
                else:
                    init[p] = True  # optimistic; downgraded by call sites
            f.param_static = init

        self.reachable = {f.key for f in roots}
        for _ in range(MAX_FIXPOINT_ROUNDS):
            changed = False
            self._callsite_static = {}
            frontier = list(self.reachable)
            for key in frontier:
                self._analyze(self.funcs[key])
            # grow reachability
            for key in list(self._callsite_static):
                if key not in self.reachable:
                    self.reachable.add(key)
                    changed = True
            # downgrade params from observed call sites
            for key, per_param in self._callsite_static.items():
                f = self.funcs.get(key)
                if f is None or f.is_root:
                    continue
                for p, is_static in per_param.items():
                    forced = p in f.annotated_static
                    new = forced or is_static
                    if f.param_static.get(p, True) != new:
                        f.param_static[p] = new
                        changed = True
            if not changed:
                break

        # final pass with emission on
        self._emit_mode = True
        for key in sorted(self.reachable):
            self._analyze(self.funcs[key])

    # ----- indexing ---------------------------------------------------------

    def _index_module(self, mod: SourceModule) -> None:
        base = mod.path.rsplit("/", 1)[-1].rsplit(".", 1)[0]
        self.by_module[base] = {}
        self.aliases[base] = {}
        self.np_roots[base] = set()
        self.jax_roots[base] = set()

        for node in mod.tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_roots[base].add(name)
                    elif a.name == "jax.numpy":
                        pass  # jnp stays device-side
                    elif a.name == "jax":
                        self.jax_roots[base].add(name)
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                if m == "numpy":
                    for a in node.names:
                        self.np_roots[base].add(a.asname or a.name)
                    continue
                tail = m.rsplit(".", 1)[-1] if m else ""
                for a in node.names:
                    local = a.asname or a.name
                    if a.name == "numpy":
                        self.np_roots[base].add(local)
                    elif m.endswith("ops") or ".ops." in m + ".":
                        # from kubernetes_tpu.ops import filters as F /
                        # from kubernetes_tpu.ops.common import eval_table
                        if m.endswith(".ops") or m == "ops":
                            self.aliases[base][local] = a.name
                        else:
                            self.aliases[base][local] = f"{tail}.{a.name}"

        def index_fn(fn: ast.FunctionDef, qual: str, enclosing: Optional[_FuncInfo]):
            key = f"{base}:{qual}"
            info = _FuncInfo(key, mod, fn, enclosing)
            jd = _jit_decoration(fn)
            if jd is not None:
                info.is_root = True
                info.static_argnames = jd[1]
            self.funcs[key] = info
            self.by_module[base][qual] = key
            if "." not in qual:
                self.by_module[base].setdefault(fn.name, key)
            for sub in fn.body:
                if isinstance(sub, ast.FunctionDef):
                    index_fn(sub, f"{qual}.{sub.name}", info)

        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef):
                index_fn(node, node.name, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        index_fn(item, f"{node.name}.{item.name}", None)

    def _resolve_call(self, base_mod: str, func: ast.expr) -> Optional[str]:
        """Resolve a call expression to an indexed function key."""
        dn = dotted_name(func)
        if dn is None:
            return None
        parts = dn.split(".")
        local = self.by_module.get(base_mod, {})
        if len(parts) == 1:
            key = local.get(parts[0])
            if key is not None:
                return key
            target = self.aliases.get(base_mod, {}).get(parts[0])
            if target and "." in target:
                m, fn = target.split(".", 1)
                return self.by_module.get(m, {}).get(fn)
            return None
        # F.all_masks → alias F = module 'filters'
        target = self.aliases.get(base_mod, {}).get(parts[0])
        if target and "." not in target and len(parts) == 2:
            return self.by_module.get(target, {}).get(parts[1])
        return None

    # ----- per-function analysis --------------------------------------------

    def _analyze(self, f: _FuncInfo) -> None:
        base = f.key.split(":", 1)[0]
        env: Dict[str, bool] = dict(f.param_static)
        # defaults evaluated at module scope → params missing a call-site
        # record keep their optimistic/static value
        self._exec_block(f, base, f.node.body, env)

    def _exec_block(
        self, f: _FuncInfo, base: str, stmts: List[ast.stmt], env: Dict[str, bool]
    ) -> None:
        for st in stmts:
            if isinstance(st, ast.FunctionDef):
                # nested defs (vmap bodies etc.) analyzed via closure env:
                # params traced unless annotated, closures resolve to env
                key = f"{f.key.split(':', 1)[1]}.{st.name}"
                info = self.funcs.get(f"{base}:{key}")
                if info is not None and f.key in self.reachable:
                    self.reachable.add(info.key)
                    nested_env = {
                        p: (p in info.annotated_static) for p in info.params
                    }
                    closure_env = dict(env)
                    closure_env.update(nested_env)
                    self._exec_block(info, base, info.node.body, closure_env)
                env[st.name] = True
                continue
            if isinstance(st, ast.Assign):
                s = self._static(f, base, st.value, env)
                self._scan_expr(f, base, st.value, env)
                for t in st.targets:
                    self._bind_target(t, s, env)
                continue
            if isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    s = self._static(f, base, st.value, env)
                    self._scan_expr(f, base, st.value, env)
                    self._bind_target(st.target, s, env)
                continue
            if isinstance(st, ast.AugAssign):
                s = self._static(f, base, st.value, env)
                self._scan_expr(f, base, st.value, env)
                if isinstance(st.target, ast.Name):
                    env[st.target.id] = env.get(st.target.id, True) and s
                continue
            if isinstance(st, (ast.If, ast.While)):
                if not self._static(f, base, st.test, env):
                    self._violation(
                        f,
                        st.test.lineno,
                        f"branch on a traced value ({ast.unparse(st.test)[:60]})",
                    )
                self._scan_expr(f, base, st.test, env)
                self._exec_block(f, base, st.body, env)
                self._exec_block(f, base, st.orelse, env)
                continue
            if isinstance(st, ast.For):
                if not self._static_iterable(f, base, st.iter, env):
                    self._violation(
                        f,
                        st.iter.lineno,
                        f"iteration over a traced value "
                        f"({ast.unparse(st.iter)[:60]})",
                    )
                self._scan_expr(f, base, st.iter, env)
                self._bind_target(st.target, self._static(f, base, st.iter, env), env)
                self._exec_block(f, base, st.body, env)
                self._exec_block(f, base, st.orelse, env)
                continue
            if isinstance(st, ast.Assert):
                if not self._static(f, base, st.test, env):
                    self._violation(
                        f, st.test.lineno, "assert on a traced value"
                    )
                self._scan_expr(f, base, st.test, env)
                continue
            if isinstance(st, ast.Return):
                if st.value is not None:
                    self._scan_expr(f, base, st.value, env)
                continue
            if isinstance(st, ast.Expr):
                self._scan_expr(f, base, st.value, env)
                continue
            # generic recursion (With/Try/…)
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    self._exec_block(f, base, sub, env)
            for handler in getattr(st, "handlers", ()) or ():
                self._exec_block(f, base, handler.body, env)

    def _bind_target(self, target: ast.expr, static: bool, env: Dict[str, bool]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = static
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind_target(el, static, env)
        # attribute/subscript writes don't rebind names

    # ----- violation scanning ----------------------------------------------

    def _scan_expr(
        self, f: _FuncInfo, base: str, expr: ast.expr, env: Dict[str, bool]
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(f, base, node, env)
                # record call-site staticness for indexed callees
                key = self._resolve_call(base, node.func)
                if key is not None:
                    self._record_callsite(f, base, key, node, env)
            elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)):
                for gen in node.generators:
                    if not self._static_iterable(f, base, gen.iter, env):
                        self._violation(
                            f,
                            gen.iter.lineno,
                            "comprehension over a traced value",
                        )

    def _check_call(
        self, f: _FuncInfo, base: str, node: ast.Call, env: Dict[str, bool]
    ) -> None:
        func = node.func
        args_traced = any(
            not self._static(f, base, a, env)
            for a in list(node.args) + [kw.value for kw in node.keywords]
        )
        if isinstance(func, ast.Attribute):
            if func.attr in SYNC_METHODS and not self._static(
                f, base, func.value, env
            ):
                self._violation(
                    f,
                    node.lineno,
                    f".{func.attr}() forces a host sync on a traced value",
                )
                return
            dn = dotted_name(func)
            if dn is not None:
                root = dn.split(".")[0]
                if root in self.np_roots.get(base, ()) and args_traced:
                    self._violation(
                        f,
                        node.lineno,
                        f"{dn}(...) coerces a traced value through host numpy",
                    )
                    return
                if (
                    root in self.jax_roots.get(base, ())
                    and dn.split(".")[-1] == "device_get"
                ):
                    self._violation(
                        f, node.lineno, "jax.device_get inside a jitted pipeline"
                    )
                    return
        elif isinstance(func, ast.Name):
            if (
                func.id in CAST_BUILTINS
                and func.id not in env  # not shadowed by a local
                and node.args
                and not self._static(f, base, node.args[0], env)
            ):
                self._violation(
                    f,
                    node.lineno,
                    f"{func.id}() on a traced value forces a host sync",
                )

    def _record_callsite(
        self, f: _FuncInfo, base: str, callee_key: str, node: ast.Call, env: Dict[str, bool]
    ) -> None:
        callee = self.funcs.get(callee_key)
        if callee is None:
            return
        rec = self._callsite_static.setdefault(callee_key, {})
        params = callee.params
        has_self = params and params[0] == "self"
        offset = 1 if has_self else 0
        for i, a in enumerate(node.args):
            if i + offset < len(params):
                p = params[i + offset]
                s = self._static(f, base, a, env)
                rec[p] = rec.get(p, True) and s
        for kw in node.keywords:
            if kw.arg is not None and kw.arg in params:
                s = self._static(f, base, kw.value, env)
                rec[kw.arg] = rec.get(kw.arg, True) and s

    def _violation(self, f: _FuncInfo, line: int, message: str) -> None:
        if self._emit_mode:
            fn_name = f.key.split(":", 1)[1]
            self.emit(f.mod, line, f"{fn_name}: {message}")

    # ----- staticness -------------------------------------------------------

    def _static_iterable(
        self, f: _FuncInfo, base: str, node: ast.expr, env: Dict[str, bool]
    ) -> bool:
        """Can Python iterate this without consuming a tracer?  A tuple/
        list DISPLAY has static structure even with traced elements
        (``for a, b in ((x, y), (z, w))``); zip/enumerate inherit from
        their operands' structure."""
        if isinstance(node, (ast.Tuple, ast.List)):
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
            if node.func.id in ("zip", "enumerate") and node.func.id not in env:
                return all(
                    self._static_iterable(f, base, a, env) for a in node.args
                )
        return self._static(f, base, node, env)

    def _static(
        self, f: _FuncInfo, base: str, node: ast.expr, env: Dict[str, bool]
    ) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            return env.get(node.id, True)  # unknown → module global → static
        if isinstance(node, ast.Attribute):
            if node.attr in NEUTRAL_ATTRS:
                return True
            return self._static(f, base, node.value, env)
        if isinstance(node, ast.Subscript):
            return self._static(f, base, node.value, env) and self._static(
                f, base, node.slice, env
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return all(self._static(f, base, el, env) for el in node.elts)
        if isinstance(node, ast.Dict):
            return all(
                self._static(f, base, v, env)
                for v in list(node.keys) + list(node.values)
                if v is not None
            )
        if isinstance(node, ast.BoolOp):
            return all(self._static(f, base, v, env) for v in node.values)
        if isinstance(node, ast.BinOp):
            return self._static(f, base, node.left, env) and self._static(
                f, base, node.right, env
            )
        if isinstance(node, ast.UnaryOp):
            return self._static(f, base, node.operand, env)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` on a traced OBJECT is a Python
            # identity check, not a tracer branch — the optional-array
            # idiom (kernels take `nom_node=None` to drop whole phases)
            if (
                len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                and any(
                    isinstance(side, ast.Constant) and side.value is None
                    for side in (node.left, node.comparators[0])
                )
            ):
                return True
            return self._static(f, base, node.left, env) and all(
                self._static(f, base, c, env) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return (
                self._static(f, base, node.test, env)
                and self._static(f, base, node.body, env)
                and self._static(f, base, node.orelse, env)
            )
        if isinstance(node, ast.Slice):
            return all(
                self._static(f, base, p, env)
                for p in (node.lower, node.upper, node.step)
                if p is not None
            )
        if isinstance(node, ast.Starred):
            return self._static(f, base, node.value, env)
        if isinstance(node, ast.Call):
            return self._static_call(f, base, node, env)
        if isinstance(node, ast.JoinedStr):
            return True
        # conservative fallback: traced if any referenced name is traced
        return not any(
            isinstance(n, ast.Name) and not env.get(n.id, True)
            for n in ast.walk(node)
        )

    def _static_call(
        self, f: _FuncInfo, base: str, node: ast.Call, env: Dict[str, bool]
    ) -> bool:
        func = node.func
        args = list(node.args) + [kw.value for kw in node.keywords]
        args_static = all(self._static(f, base, a, env) for a in args)
        if isinstance(func, ast.Name):
            if func.id in LEN_LIKE and func.id not in env:
                return True  # len() of a tracer is its static leading dim
            if func.id in ("range", "enumerate", "zip", "min", "max", "abs",
                           "sum", "sorted", "reversed", "tuple", "list",
                           "set", "dict", "repr", "str") and func.id not in env:
                return args_static
            if func.id in CAST_BUILTINS and func.id not in env:
                return args_static
        key = self._resolve_call(base, func)
        if key is not None:
            # intra-package helper: static result iff static inputs
            return args_static
        if isinstance(func, ast.Attribute):
            dn = dotted_name(func)
            if dn is not None:
                root = dn.split(".")[0]
                if root in self.np_roots.get(base, ()):
                    return args_static  # np on static data stays host/static
                if root in env and not env[root]:
                    return False  # method on a traced object
                if root in env and env[root]:
                    return args_static
                # module global (jnp/jax/…): traced iff any traced arg
                return args_static
            return args_static and self._static(f, base, func, env)
        return args_static
