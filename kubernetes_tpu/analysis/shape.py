"""Symbolic shape / dtype / shard interpreter for the kernel surface
(rules: ``shape``, ``dtype``, ``shard``).

The term-factored algebra spans four dispatch paths whose correctness is
a NAMED-axis discipline — ``[P, N]`` speculation, ``[T, N]`` term counts,
``[C, N, d_cap]`` readbacks, ``[S, N]`` resident keys — but at trace time
jax only sees the concrete sizes, and rank-1 broadcasting silently
absorbs a ``[P, N]`` tensor where a ``[T, N]`` one was meant whenever the
bucketed sizes happen to coincide.  This module is an abstract
interpreter over SYMBOLIC shapes: every ``jax.jit`` root declares its
parameter axes with a ``# ktpu: axes(...)`` annotation (dataclass params
resolve through the ``_KTPU_AXES`` tables next to their definitions),
and the interpreter propagates named dims through broadcasting, einsum /
dot_general contraction, reshape / concatenation, advanced indexing,
``lax.scan`` / ``while_loop`` carries and ``dynamic_update_slice``.

Annotation grammar (comment lines immediately above the root's
decorators; ``axes`` lines stack and merge):

    # ktpu: axes(sig_ids=i32[P], sig_req=i64[S,R], dc=DeviceCluster)
    # ktpu: accum(i64, i32, bool)      — dtypes allowed in loop carries
    # ktpu: static(v_cap=16)           — representative static-arg values
    #                                     for the eval_shape cross-check
    # ktpu: noinstantiate — <reason>   — root excluded from the runtime
    #                                     cross-check (shapecheck.py)

Findings:

  * ``shape`` — a root without an axes annotation; an axes name that
    matches no parameter; two DIFFERENT named dims aligned in one
    broadcast axis; vmapped operands whose mapped axes carry different
    names; einsum/dot_general contracting mismatched names; scan /
    while_loop carries whose named shape drifts between init and step.
  * ``dtype`` — true division on integer/bool operands (silent float
    promotion in integer-score kernels); arithmetic on a bool operand
    without an ``astype`` (silent bool→int promotion); a float literal
    widening an integer array (weak-type promotion inside the kernel —
    the in-kernel complement of the ``retrace`` literal rule); a loop
    carry whose dtype leaves the root's declared ``accum(...)`` set.
  * ``shard`` — with ``parallel/mesh.py``'s ``('pods', 'nodes')`` mesh
    sharding the N axis, every op is classified N-axis-preserving
    (elementwise / other-axis reductions: fine), N-axis-REDUCING
    (reductions, einsum contractions and segment ops over N — each must
    live under a helper declared in its module's ``_KTPU_N_COLLECTIVES``
    roster, the static inventory of cross-shard collectives the
    multichip refactor must route through jax collectives), or
    implicitly N-axis-GATHERING (advanced indexing / scatter with a
    traced index into an N axis — flagged the same way).

The interpreter is deliberately PERMISSIVE: anything it cannot model
evaluates to Unknown and Unknown never produces a finding — only
confidently-known named mismatches fire.  The runtime complement
(``analysis/shapecheck.py``, KTPU_SANITIZE=1) cross-validates the
inferred root shapes against ``jax.eval_shape`` so the interpreter
itself cannot silently rot as the kernels evolve.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubernetes_tpu.analysis.core import (
    RULE_DTYPE,
    RULE_SHAPE,
    RULE_SHARD,
    Checker,
    SourceModule,
    dotted_name,
    module_literal,
)
from kubernetes_tpu.analysis.jit import _jit_decoration

# the mesh axis this analysis audits (parallel/mesh.py: ('pods', 'nodes')
# with node-major snapshot tensors partitioned over 'nodes', i.e. dim N)
NODE_AXIS = "N"

# A roster entry is RESOLVED once its reason leads with an explicit
# sharding story: ``resolved(<mechanism>): <how>`` where mechanism is
#   collective — GSPMD inserts the cross-shard psum/all-gather/all-to-all
#   local      — the op addresses only the owning shard's rows (rank-1
#                commits, fork-axis parallelism)
#   replicated — the crossed operand replicates on the mesh, so the
#                "crossing" is shard-local by layout
# Unresolved entries are findings: the multichip worklist is a BURN-DOWN
# (MULTICHIP.md inventory), not a parking lot.
RESOLVED_ROSTER_RE = re.compile(
    r"^resolved\((collective|local|replicated)\):\s+\S"
)

_ANNOT_RE = re.compile(
    r"#\s*ktpu:\s*(axes|static|accum|noinstantiate)\b\s*(.*)$"
)

_DTYPES = {
    "bool": "bool",
    "i8": "i8",
    "i16": "i16",
    "i32": "i32",
    "i64": "i64",
    "u8": "u8",
    "u16": "u16",
    "u32": "u32",
    "u64": "u64",
    "f16": "f16",
    "f32": "f32",
    "f64": "f64",
}
_JNP_DTYPE_ATTRS = {
    "int8": "i8",
    "int16": "i16",
    "int32": "i32",
    "int64": "i64",
    "uint8": "u8",
    "uint16": "u16",
    "uint32": "u32",
    "uint64": "u64",
    "bool_": "bool",
    "float16": "f16",
    "float32": "f32",
    "float64": "f64",
    "bfloat16": "f16",
}
_INT_DTYPES = {"i8", "i16", "i32", "i64", "u8", "u16", "u32", "u64"}
_FLOAT_DTYPES = {"f16", "f32", "f64"}
_WIDTH = {"bool": 0, "i8": 1, "u8": 1, "i16": 2, "u16": 2, "i32": 3,
          "u32": 3, "i64": 4, "u64": 4, "f16": 5, "f32": 6, "f64": 7}

_REDUCERS = {
    "sum", "max", "min", "all", "any", "prod", "mean", "argmax", "argmin",
    "count_nonzero", "nanmax", "nanmin", "nansum",
}
_SAME_SHAPE_FNS = {
    "abs", "sign", "negative", "logical_not", "invert", "exp", "log",
    "sqrt", "flip", "sort", "argsort", "cumsum", "cummax", "cumprod",
    "cumulative_sum", "round", "floor", "ceil", "bitwise_not",
}
_BROADCAST_FNS = {
    "where", "minimum", "maximum", "add", "subtract", "multiply",
    "logical_and", "logical_or", "logical_xor", "equal", "not_equal",
    "greater", "greater_equal", "less", "less_equal", "clip", "mod",
    "floor_divide", "power", "bitwise_and", "bitwise_or",
}
_BOOL_RESULT_FNS = {
    "logical_and", "logical_or", "logical_xor", "logical_not", "equal",
    "not_equal", "greater", "greater_equal", "less", "less_equal",
    "isin", "isnan",
}


# ---------------------------------------------------------------------------
# symbolic dims: canonical linear combinations over named symbols.
# A dim is an int, a "lin" tuple (const, ((sym, coeff), ...)) or None
# (unknown).  Non-linear combinations collapse to a single OPAQUE symbol
# whose name is the canonical rendering — deterministic, so two
# occurrences of the same computation stay equal.
# ---------------------------------------------------------------------------


def dim_of_sym(sym: str):
    return (0, ((sym, 1),))


def _as_lin(d):
    if d is None:
        return None
    if isinstance(d, int):
        return (d, ())
    return d


def dim_add(a, b, sign: int = 1):
    a, b = _as_lin(a), _as_lin(b)
    if a is None or b is None:
        return None
    syms: Dict[str, int] = dict(a[1])
    for s, c in b[1]:
        syms[s] = syms.get(s, 0) + sign * c
    items = tuple(sorted((s, c) for s, c in syms.items() if c != 0))
    const = a[0] + sign * b[0]
    if not items:
        return const
    return (const, items)


def dim_mul(a, b):
    a, b = _as_lin(a), _as_lin(b)
    if a is None or b is None:
        return None
    if not a[1] and not b[1]:
        return a[0] * b[0]
    if not a[1]:
        if a[0] == 0:
            return 0
        syms = tuple((s, c * a[0]) for s, c in b[1])
        return (b[0] * a[0], syms)
    if not b[1]:
        return dim_mul(b, a)
    x, y = sorted((dim_str(a), dim_str(b)))
    return dim_of_sym(f"({x}*{y})")


def dim_opaque(op: str, *parts):
    rendered = []
    for p in parts:
        p = _as_lin(p)
        if p is None:
            return None
        rendered.append(dim_str(p))
    return dim_of_sym(f"{op}({','.join(rendered)})")


def dim_str(d) -> str:
    d = _as_lin(d)
    if d is None:
        return "?"
    const, syms = d
    parts = []
    for s, c in syms:
        parts.append(s if c == 1 else f"{c}*{s}")
    if const or not parts:
        parts.append(str(const))
    return "+".join(parts).replace("+-", "-")


def dim_eq(a, b) -> bool:
    a, b = _as_lin(a), _as_lin(b)
    return a is not None and b is not None and a == b


def dim_is_one(d) -> bool:
    return _as_lin(d) == (1, ())


def dim_is_named(d) -> bool:
    d = _as_lin(d)
    return d is not None and bool(d[1])


def dim_is_node_axis(d) -> bool:
    return dim_eq(d, dim_of_sym(NODE_AXIS))


def shape_str(shape) -> str:
    if shape is None:
        return "[?]"
    return "[" + ", ".join(dim_str(d) for d in shape) + "]"


def dims_product(dims):
    out = 1
    for d in dims:
        out = dim_mul(out, d)
        if out is None:
            return None
    return out


# ---------------------------------------------------------------------------
# abstract values
# ---------------------------------------------------------------------------

_UNSET = object()


class Unknown:
    __slots__ = ()

    def __repr__(self):
        return "Unknown"


UNKNOWN = Unknown()


class Arr:
    __slots__ = ("shape", "dtype")

    def __init__(self, shape, dtype=None):
        # shape: tuple of dims (each int / lin / None) or None = unknown rank
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype

    def __repr__(self):
        return f"Arr({shape_str(self.shape)}, {self.dtype})"


class TupV:
    __slots__ = ("items",)

    def __init__(self, items):
        self.items = list(items)

    def __repr__(self):
        return f"TupV({self.items})"


class DictV:
    __slots__ = ("entries",)

    def __init__(self, entries=None):
        self.entries = dict(entries or {})

    def __repr__(self):
        return f"DictV({sorted(self.entries)})"


class RecV:
    __slots__ = ("cls", "fields")

    def __init__(self, cls, fields=None):
        self.cls = cls
        self.fields = dict(fields or {})

    def __repr__(self):
        return f"RecV({self.cls})"


class CtorV:
    """A NamedTuple / dataclass class object (callable constructor)."""

    __slots__ = ("cls", "field_order")

    def __init__(self, cls, field_order):
        self.cls = cls
        self.field_order = list(field_order)


class FuncV:
    """A locally-defined function or lambda with its live closure env."""

    __slots__ = ("key", "node", "env", "base")

    def __init__(self, key, node, env, base):
        self.key = key  # engine func key, or None for lambdas
        self.node = node
        self.env = env  # LIVE reference to the defining environment
        self.base = base


class DimV:
    """A host int whose value is a symbolic dim (usually from .shape[i])."""

    __slots__ = ("lin",)

    def __init__(self, lin):
        self.lin = _as_lin(lin) if not (lin is None or isinstance(lin, tuple)) else lin

    def __repr__(self):
        return f"DimV({dim_str(self.lin)})"


class StaticV:
    """A host static value (trace-time constant).  ``value`` is the
    concrete Python value when known, else UNSET."""

    __slots__ = ("value",)

    def __init__(self, value=_UNSET):
        self.value = value

    def __repr__(self):
        return "StaticV" if self.value is _UNSET else f"StaticV({self.value!r})"


class DtypeV:
    __slots__ = ("dt",)

    def __init__(self, dt):
        self.dt = dt


class ModV:
    __slots__ = ("base",)

    def __init__(self, base):
        self.base = base


NONE = StaticV(None)


def is_none_val(v) -> bool:
    return isinstance(v, StaticV) and v.value is None


def definitely_not_none(v) -> bool:
    return isinstance(v, (Arr, TupV, DictV, RecV, FuncV, DimV, CtorV)) or (
        isinstance(v, StaticV) and v.value is not _UNSET and v.value is not None
    )


def join(a, b):
    """Pointwise join of two abstract values (if/else merge, loop carry)."""
    if a is b:
        return a
    if isinstance(a, Unknown) or isinstance(b, Unknown):
        return UNKNOWN
    if isinstance(a, Arr) and isinstance(b, Arr):
        if a.shape is None or b.shape is None or len(a.shape) != len(b.shape):
            shape = None
        else:
            shape = tuple(
                da if dim_eq(da, db_) else None
                for da, db_ in zip(a.shape, b.shape)
            )
        return Arr(shape, a.dtype if a.dtype == b.dtype else None)
    if isinstance(a, TupV) and isinstance(b, TupV) and len(a.items) == len(b.items):
        return TupV([join(x, y) for x, y in zip(a.items, b.items)])
    if isinstance(a, DictV) and isinstance(b, DictV):
        out = {}
        for k in set(a.entries) | set(b.entries):
            if k in a.entries and k in b.entries:
                out[k] = join(a.entries[k], b.entries[k])
            else:
                out[k] = a.entries.get(k, b.entries.get(k))
        return DictV(out)
    if isinstance(a, RecV) and isinstance(b, RecV) and a.cls == b.cls:
        out = {}
        for k in set(a.fields) | set(b.fields):
            if k in a.fields and k in b.fields:
                out[k] = join(a.fields[k], b.fields[k])
            else:
                out[k] = a.fields.get(k, b.fields.get(k))
        return RecV(a.cls, out)
    if isinstance(a, DimV) and isinstance(b, DimV):
        return a if dim_eq(a.lin, b.lin) else DimV(None)
    if isinstance(a, StaticV) and isinstance(b, StaticV):
        if a.value is not _UNSET and b.value is not _UNSET and a.value == b.value:
            return a
        return StaticV()
    if isinstance(a, FuncV) and isinstance(b, FuncV) and a.node is b.node:
        return a
    return UNKNOWN


def promote_dtype(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a is None or b is None:
        return None
    if a == b:
        return a
    wa, wb = _WIDTH.get(a), _WIDTH.get(b)
    if wa is None or wb is None:
        return None
    return a if wa >= wb else b


# ---------------------------------------------------------------------------
# annotation parsing
# ---------------------------------------------------------------------------


class RootAnnotation:
    __slots__ = ("axes", "accum", "static_values", "noinstantiate", "line",
                 "has_axes", "ret")

    def __init__(self):
        self.axes: Dict[str, ast.expr] = {}
        self.ret: Optional[ast.expr] = None
        self.accum: Optional[Set[str]] = None
        self.static_values: Dict[str, object] = {}
        self.noinstantiate: Optional[str] = None
        self.has_axes = False
        self.line = 0


def _split_arrow(payload: str) -> Tuple[str, Optional[str]]:
    depth = 0
    for i in range(len(payload) - 1):
        ch = payload[i]
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif depth == 0 and payload[i : i + 2] == "->":
            return payload[:i].rstrip(), payload[i + 2 :].strip()
    return payload.rstrip(), None


def parse_annotations(mod: SourceModule, first_line: int) -> RootAnnotation:
    """Collect the ``# ktpu:`` annotation block of comment lines
    immediately above ``first_line`` (the def or its first decorator)."""
    ann = RootAnnotation()
    i = first_line - 1  # line above, 1-based
    block: List[Tuple[int, str, str]] = []
    while i >= 1:
        raw = mod.lines[i - 1].strip()
        if not raw.startswith("#"):
            break
        m = _ANNOT_RE.search(raw)
        if m:
            block.append((i, m.group(1), m.group(2).strip()))
        i -= 1
    for line, kind, payload in reversed(block):
        ann.line = ann.line or line
        if kind == "noinstantiate":
            ann.noinstantiate = payload.lstrip("—-– :").strip() or "unspecified"
            continue
        body, arrow = _split_arrow(payload)
        try:
            call = ast.parse(f"__a__{body}", mode="eval").body
        except SyntaxError:
            continue
        if not isinstance(call, ast.Call):
            continue
        if kind == "axes":
            ann.has_axes = True
            for kw in call.keywords:
                if kw.arg is not None:
                    ann.axes[kw.arg] = kw.value
            if arrow:
                try:
                    ann.ret = ast.parse(arrow, mode="eval").body
                except SyntaxError:
                    pass
        elif kind == "accum":
            ann.accum = set()
            for a in call.args:
                if isinstance(a, ast.Name):
                    ann.accum.add(_DTYPES.get(a.id, a.id))
        elif kind == "static":
            for kw in call.keywords:
                if kw.arg is None:
                    continue
                try:
                    ann.static_values[kw.arg] = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    pass
    return ann


def spec_to_aval(expr: ast.expr, class_tables: Dict[str, Dict[str, str]],
                 ns: str = ""):
    """An annotation spec expression → abstract value.

    ``i64[S,R]`` → Arr; bare dtype → scalar Arr; ``DeviceCluster`` (a
    ``_KTPU_AXES`` class) → RecV from its table; ``DTable[M,1]`` → the
    class with its ``*`` lead dims bound; ``any`` → Unknown.  ``ns``
    namespaces the class schema's own symbols (two DTables bucketed
    independently must not unify their per-table widths).
    """
    if isinstance(expr, ast.Name):
        if expr.id == "any" or expr.id == "key":
            return UNKNOWN
        if expr.id in _DTYPES:
            return Arr((), _DTYPES[expr.id])
        if expr.id in class_tables:
            return _class_to_rec(expr.id, (), class_tables, ns or expr.id)
        return UNKNOWN
    if isinstance(expr, ast.Tuple):
        return TupV([spec_to_aval(e, class_tables, ns) for e in expr.elts])
    if isinstance(expr, ast.Subscript):
        base = expr.value
        dims_expr = expr.slice
        dims = _spec_dims(dims_expr, ns)
        if isinstance(base, ast.Name):
            if base.id in _DTYPES:
                return Arr(dims, _DTYPES[base.id])
            if base.id in class_tables:
                return _class_to_rec(base.id, dims, class_tables, ns or base.id)
    return UNKNOWN


def _spec_dims(expr: ast.expr, ns: str):
    elts = expr.elts if isinstance(expr, ast.Tuple) else [expr]
    dims = []
    for e in elts:
        if isinstance(e, ast.Constant) and isinstance(e.value, int):
            dims.append(e.value)
        elif isinstance(e, ast.Name):
            if e.id == "_":
                dims.append(None)
            else:
                dims.append(dim_of_sym(e.id))
        else:
            dims.append(None)
    return tuple(dims)


def _class_to_rec(cls: str, lead, class_tables, ns: str):
    table = class_tables.get(cls, {})
    fields = {}
    for fname, spec in table.items():
        fields[fname] = _field_spec_to_aval(
            spec, lead, class_tables, ns, fname
        )
    return RecV(cls, fields)


def _field_spec_to_aval(spec: str, lead, class_tables, ns: str,
                        fname: str = ""):
    """A ``_KTPU_AXES`` field spec string → abstract value.  ``*`` in a
    dims position splices the owner's lead dims; symbols spelled with a
    trailing underscore (``Q_``) are PRIVATE to the class schema and get
    namespaced by the owning field path — two independently-bucketed
    DTables must not unify their per-table widths."""
    try:
        expr = ast.parse(spec.strip().replace("*", "_star_"), mode="eval").body
    except SyntaxError:
        return UNKNOWN
    if isinstance(expr, ast.Subscript):
        base = expr.value
        raw = expr.slice
        elts = raw.elts if isinstance(raw, ast.Tuple) else [raw]
        dims: List[object] = []
        for e in elts:
            if isinstance(e, ast.Name) and e.id == "_star_":
                dims.extend(lead)
            elif isinstance(e, ast.Constant) and isinstance(e.value, int):
                dims.append(e.value)
            elif isinstance(e, ast.Name):
                if e.id.endswith("_"):
                    dims.append(dim_of_sym(f"{ns}.{e.id[:-1]}"))
                else:
                    dims.append(dim_of_sym(e.id))
            else:
                dims.append(None)
        if isinstance(base, ast.Name):
            if base.id in _DTYPES:
                return Arr(tuple(dims), _DTYPES[base.id])
            if base.id in class_tables:
                return _class_to_rec(
                    base.id, tuple(dims), class_tables,
                    f"{ns}.{fname}" if fname else ns,
                )
    if isinstance(expr, ast.Name):
        if expr.id in _DTYPES:
            return Arr((), _DTYPES[expr.id])
        if expr.id in class_tables:
            return _class_to_rec(
                expr.id, (), class_tables, f"{ns}.{fname}" if fname else ns
            )
    return UNKNOWN


# ---------------------------------------------------------------------------
# module indexing
# ---------------------------------------------------------------------------


class _FuncRec:
    __slots__ = ("key", "mod", "node", "qual", "base", "enclosing")

    def __init__(self, key, mod, node, qual, base, enclosing):
        self.key = key
        self.mod = mod
        self.node = node
        self.qual = qual
        self.base = base
        self.enclosing = enclosing


class _ModIndex:
    def __init__(self, mod: SourceModule, base: str):
        self.mod = mod
        self.base = base
        self.funcs: Dict[str, _FuncRec] = {}  # qual -> rec
        self.classes: Dict[str, List[str]] = {}  # NamedTuple fields
        self.dtype_aliases: Dict[str, str] = {}
        self.constants: Dict[str, object] = {}
        self.imports: Dict[str, Tuple[str, Optional[str]]] = {}
        # local name -> ('jnp'|'np'|'jax'|'lax', None) or (module_base, sym)
        self.roster: Dict[str, str] = {}
        self.roster_lines: Dict[str, int] = {}  # qual -> dict-key lineno
        self.axes_table: Dict[str, Dict[str, str]] = {}


class ShapeEngine:
    """One pass over the target modules; findings accumulate as raw
    (rule, mod, line, message) tuples — the checkers apply suppressions."""

    MAX_DEPTH = 24

    def __init__(self) -> None:
        self.mods: Dict[str, _ModIndex] = {}  # base -> index
        self.raw_findings: List[Tuple[str, SourceModule, int, str]] = []
        self._emitted: Set[Tuple[str, str, int, str]] = set()
        self.roots: List[Tuple[_FuncRec, RootAnnotation]] = []
        self.class_tables: Dict[str, Dict[str, str]] = {}
        self.summaries: Dict[tuple, object] = {}
        self._stack: List[str] = []  # active func keys (roster coverage)
        self._accum: List[Optional[Set[str]]] = []
        self.root_returns: Dict[str, object] = {}  # "base.qual" -> aval

    # -- indexing ----------------------------------------------------------

    def run(self, mods: Sequence[SourceModule]) -> "ShapeEngine":
        for mod in mods:
            self._index(mod)
        for mi in self.mods.values():
            for qual, reason in sorted(mi.roster.items()):
                if not RESOLVED_ROSTER_RE.match(reason):
                    self.emit(
                        RULE_SHARD,
                        mi.mod,
                        mi.roster_lines.get(qual, 1),
                        f"_KTPU_N_COLLECTIVES entry {qual!r} has no "
                        "resolved sharding story — prefix the reason with "
                        "'resolved(collective|local|replicated): <how>' "
                        "once the site has an explicit cross-shard "
                        "treatment (MULTICHIP.md inventory)",
                    )
        for mi in self.mods.values():
            self.class_tables.update(mi.axes_table)
        for mi in self.mods.values():
            for qual, rec in sorted(mi.funcs.items()):
                jd = _jit_decoration(rec.node)
                if jd is None:
                    continue
                first = min(
                    [d.lineno for d in rec.node.decorator_list]
                    + [rec.node.lineno]
                )
                ann = parse_annotations(rec.mod, first)
                if not ann.has_axes:
                    self.emit(
                        RULE_SHAPE,
                        rec.mod,
                        rec.node.lineno,
                        f"{qual}: jit root without a `# ktpu: axes(...)` "
                        "annotation — declare the named dims of every "
                        "array parameter",
                    )
                    continue
                self.roots.append((rec, ann))
        for rec, ann in self.roots:
            self._analyze_root(rec, ann)
        return self

    def _index(self, mod: SourceModule) -> None:
        base = mod.path.rsplit("/", 1)[-1].rsplit(".", 1)[0]
        # two target files sharing a basename must BOTH be analyzed:
        # disambiguate the index key (cross-module import resolution into
        # the shadowed one simply won't resolve — permissive, never a
        # silently-dropped file)
        n = 2
        while base in self.mods:
            base = f"{base}#{n}"
            n += 1
        mi = _ModIndex(mod, base)
        self.mods[mi.base] = mi
        roster = module_literal(mod.tree, "_KTPU_N_COLLECTIVES")
        if isinstance(roster, dict):
            mi.roster = {str(k): str(v) for k, v in roster.items()}
            # per-entry line numbers: the burn-down findings (and their
            # suppressions) anchor to the entry's own dict-key line
            for node in mod.tree.body:
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "_KTPU_N_COLLECTIVES"
                    and isinstance(node.value, ast.Dict)
                ):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant):
                            mi.roster_lines[str(k.value)] = k.lineno
        axes = module_literal(mod.tree, "_KTPU_AXES")
        if isinstance(axes, dict):
            mi.axes_table = {
                str(c): {str(f): str(s) for f, s in t.items()}
                for c, t in axes.items()
                if isinstance(t, dict)
            }

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    if a.name == "jax.numpy":
                        mi.imports[a.asname or "jnp"] = ("jnp", None)
                    elif a.name == "numpy":
                        mi.imports[local] = ("np", None)
                    elif a.name == "jax":
                        mi.imports[local] = ("jax", None)
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    if m == "jax" and a.name == "numpy":
                        mi.imports[local] = ("jnp", None)
                    elif m == "jax" and a.name == "lax":
                        mi.imports[local] = ("lax", None)
                    elif m == "jax":
                        mi.imports[local] = ("jax", None)
                    elif m == "numpy":
                        mi.imports[local] = ("np", None)
                    elif m.startswith("kubernetes_tpu"):
                        tail = m.rsplit(".", 1)[-1]
                        if a.name[:1].islower() and m.count(".") <= 1:
                            mi.imports[local] = ("@mod", a.name)
                        else:
                            mi.imports[local] = (tail, a.name)
                    else:
                        mi.imports[local] = ("@ext", a.name)

        for node in mod.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                dn = dotted_name(node.value)
                if dn is not None:
                    leaf = dn.split(".")[-1]
                    if leaf in _JNP_DTYPE_ATTRS:
                        mi.dtype_aliases[name] = _JNP_DTYPE_ATTRS[leaf]
                        continue
                try:
                    mi.constants[name] = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    pass
            elif isinstance(node, ast.ClassDef):
                bases = [dotted_name(b) for b in node.bases]
                fields = [
                    st.target.id
                    for st in node.body
                    if isinstance(st, ast.AnnAssign)
                    and isinstance(st.target, ast.Name)
                ]
                if any(b and b.split(".")[-1] == "NamedTuple" for b in bases) \
                        or any(
                            dotted_name(d) and dotted_name(d).split(".")[-1]
                            == "dataclass"
                            or (isinstance(d, ast.Call) and dotted_name(d.func))
                            for d in node.decorator_list
                        ) or fields:
                    mi.classes[node.name] = fields

        def walk_defs(body, qual, rec):
            for sub in body:
                if isinstance(sub, ast.FunctionDef):
                    index_fn(sub, f"{qual}.{sub.name}", rec)
                    continue
                # nested defs under if/for/with/try still get keys —
                # resident's run_tail and explain's _spread_one live
                # inside conditionals
                for attr in ("body", "orelse", "finalbody"):
                    b = getattr(sub, attr, None)
                    if b:
                        walk_defs(b, qual, rec)
                for h in getattr(sub, "handlers", ()) or ():
                    walk_defs(h.body, qual, rec)

        def index_fn(fn, qual, enclosing):
            rec = _FuncRec(f"{mi.base}:{qual}", mod, fn, qual, mi.base,
                           enclosing)
            mi.funcs[qual] = rec
            walk_defs(fn.body, qual, rec)

        for node in mod.tree.body:
            if isinstance(node, ast.FunctionDef):
                index_fn(node, node.name, None)
            elif isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef):
                        index_fn(item, f"{node.name}.{item.name}", None)

    # -- findings ----------------------------------------------------------

    def emit(self, rule: str, mod: SourceModule, line: int, msg: str) -> None:
        key = (rule, mod.path, line, msg)
        if key in self._emitted:
            return
        self._emitted.add(key)
        self.raw_findings.append((rule, mod, line, msg))

    def _covered(self) -> bool:
        """Is the current abstract call stack under a rostered collective
        helper?  (func keys are "base:qual"; rosters are per-module.)"""
        for key in self._stack:
            base, qual = key.split(":", 1)
            mi = self.mods.get(base)
            if mi is not None and qual in mi.roster:
                return True
        return False

    def _fn_label(self) -> str:
        return self._stack[-1].split(":", 1)[1] if self._stack else "<module>"

    def _cur_mod(self) -> Optional[SourceModule]:
        if not self._stack:
            return None
        base = self._stack[-1].split(":", 1)[0]
        mi = self.mods.get(base)
        return mi.mod if mi else None

    def _shard_flag(self, node, kind: str, detail: str) -> None:
        if self._covered():
            return
        mod = self._cur_mod()
        if mod is None:
            return
        self.emit(
            RULE_SHARD,
            mod,
            node.lineno,
            f"{self._fn_label()}: {kind} over the sharded {NODE_AXIS} axis "
            f"({detail}) outside a declared collective helper — add the "
            "enclosing function to its module's _KTPU_N_COLLECTIVES roster "
            "(with a reason) or restructure to keep the op shard-local",
        )

    def _shape_flag(self, node, msg: str) -> None:
        mod = self._cur_mod()
        if mod is not None:
            self.emit(RULE_SHAPE, mod, node.lineno, f"{self._fn_label()}: {msg}")

    def _dtype_flag(self, node, msg: str) -> None:
        mod = self._cur_mod()
        if mod is not None:
            self.emit(RULE_DTYPE, mod, node.lineno, f"{self._fn_label()}: {msg}")

    # -- broadcasting ------------------------------------------------------

    def broadcast_shapes(self, shapes, node):
        """Right-aligned broadcast with named-dim mismatch detection."""
        known = [s for s in shapes if s is not None]
        if not known:
            return None
        rank = max(len(s) for s in known)
        out = []
        for i in range(1, rank + 1):
            dims = [s[-i] for s in known if len(s) >= i]
            cur = None
            conflicted = False
            for d in dims:
                if d is None or dim_is_one(d):
                    continue
                if cur is None:
                    cur = d
                elif not dim_eq(cur, d):
                    if dim_is_named(cur) and dim_is_named(d):
                        self._shape_flag(
                            node,
                            f"named-dim mismatch: axis -{i} aligns "
                            f"{dim_str(cur)} with {dim_str(d)} "
                            f"(shapes {', '.join(shape_str(s) for s in known)})"
                            " — rank-1 broadcasting would silently absorb "
                            "this when the bucketed sizes coincide",
                        )
                    cur = None
                    conflicted = True
                    break
            if cur is None and not conflicted and dims and all(
                d is not None and dim_is_one(d) for d in dims
            ):
                cur = 1
            out.append(cur)
        out.reverse()
        return tuple(out)

    # -- dims from values --------------------------------------------------

    def dim_of_value(self, v):
        """Host value → symbolic dim (for shape tuples / sizes)."""
        if isinstance(v, DimV):
            return v.lin
        if isinstance(v, StaticV) and isinstance(v.value, int) and not \
                isinstance(v.value, bool):
            return v.value
        return None

    def shape_from_value(self, v):
        """A shape argument value → dims tuple (or None)."""
        if isinstance(v, TupV):
            return tuple(self.dim_of_value(x) for x in v.items)
        d = self.dim_of_value(v)
        if d is not None:
            return (d,)
        return None

    # -- name resolution ---------------------------------------------------

    def global_av(self, base: str, name: str, depth: int = 0):
        """Module-global lookup (functions, classes, dtype aliases,
        literal constants, import aliases)."""
        mi = self.mods.get(base)
        if mi is None or depth > 4:
            return UNKNOWN
        if name in mi.dtype_aliases:
            return DtypeV(mi.dtype_aliases[name])
        if name in mi.funcs and "." not in name:
            return FuncV(mi.funcs[name].key, mi.funcs[name].node, None, base)
        if name in mi.classes:
            return CtorV(name, mi.classes[name])
        if name in self.class_tables and name in mi.axes_table:
            return CtorV(name, list(mi.axes_table[name]))
        if name in mi.constants:
            return StaticV(mi.constants[name])
        imp = mi.imports.get(name)
        if imp is not None:
            kind, sym = imp
            if kind in ("jnp", "np", "jax", "lax"):
                return ModV(kind)
            if kind == "@mod":
                return ModV(f"#{sym}") if sym in self.mods else UNKNOWN
            if kind == "@ext":
                return StaticV()
            if kind in self.mods:
                return self.global_av(kind, sym, depth + 1)
            return StaticV()
        return UNKNOWN

    # -- dtype resolution for astype()/dtype= arguments --------------------

    def dtype_from_value(self, v) -> Optional[str]:
        if isinstance(v, DtypeV):
            return v.dt
        if isinstance(v, StaticV) and isinstance(v.value, str):
            return _DTYPES.get(v.value)
        return None

    def dtype_from_expr(self, node, env, base) -> Optional[str]:
        dn = dotted_name(node)
        if dn is not None:
            leaf = dn.split(".")[-1]
            if leaf in _JNP_DTYPE_ATTRS:
                return _JNP_DTYPE_ATTRS[leaf]
            if leaf == "bool":
                return "bool"
            if leaf in ("int", "float"):
                return "i64" if leaf == "int" else "f64"
            # .dtype attribute of a known array
            if isinstance(node, ast.Attribute) and node.attr == "dtype":
                v = self.eval(node.value, env, base)
                if isinstance(v, Arr):
                    return v.dtype
            v = self.eval(node, env, base)
            return self.dtype_from_value(v)
        v = self.eval(node, env, base)
        return self.dtype_from_value(v)

    # -- expression evaluation ---------------------------------------------

    def eval(self, node, env, base):
        try:
            return self._eval(node, env, base)
        except RecursionError:
            raise
        except Exception:
            return UNKNOWN

    def _eval(self, node, env, base):
        if isinstance(node, ast.Constant):
            if node.value is None:
                return NONE
            return StaticV(node.value)
        if isinstance(node, ast.Name):
            if node.id in env:
                return env[node.id]
            return self.global_av(base, node.id)
        if isinstance(node, ast.Attribute):
            return self._eval_attr(node, env, base)
        if isinstance(node, ast.Subscript):
            return self._eval_subscript(node, env, base)
        if isinstance(node, (ast.Tuple, ast.List)):
            return TupV([self.eval(e, env, base) for e in node.elts])
        if isinstance(node, ast.Dict):
            out = {}
            for k, v in zip(node.keys, node.values):
                if k is None:
                    sub = self.eval(v, env, base)
                    if isinstance(sub, DictV):
                        out.update(sub.entries)
                    continue
                kv = self.eval(k, env, base)
                if isinstance(kv, StaticV) and isinstance(kv.value, str):
                    out[kv.value] = self.eval(v, env, base)
            return DictV(out)
        if isinstance(node, ast.BinOp):
            return self._eval_binop(node, env, base)
        if isinstance(node, ast.UnaryOp):
            return self._eval_unary(node, env, base)
        if isinstance(node, ast.BoolOp):
            vals = [self.eval(v, env, base) for v in node.values]
            known = [v for v in vals if isinstance(v, StaticV)
                     and v.value is not _UNSET]
            if len(known) == len(vals):
                if isinstance(node.op, ast.And):
                    res = True
                    for v in known:
                        res = res and v.value
                    return StaticV(res)
                res = False
                for v in known:
                    res = res or v.value
                return StaticV(res)
            arrs = [v for v in vals if isinstance(v, Arr)]
            if arrs:
                shape = self.broadcast_shapes(
                    [a.shape for a in arrs], node
                )
                return Arr(shape, arrs[0].dtype)
            return StaticV()
        if isinstance(node, ast.Compare):
            return self._eval_compare(node, env, base)
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, base)
        if isinstance(node, ast.IfExp):
            t = self.truthiness(node.test, env, base)
            if t is True:
                return self.eval(node.body, env, base)
            if t is False:
                return self.eval(node.orelse, env, base)
            return join(
                self.eval(node.body, env, base),
                self.eval(node.orelse, env, base),
            )
        if isinstance(node, ast.Lambda):
            return FuncV(None, node, env, base)
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env, base)
        if isinstance(node, ast.JoinedStr):
            return StaticV()
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            return self._eval_comp(node, env, base)
        return UNKNOWN

    def _eval_comp(self, node, env, base):
        # a list comprehension over a STATIC iterable of known length
        # (fixed tuples) would need unrolling; approximate: element type
        # from one abstract pass, unknown length
        inner = dict(env)
        for gen in node.generators:
            self.bind_target(gen.target, UNKNOWN, inner)
        self.eval(node.elt, inner, base)
        return UNKNOWN

    def _eval_attr(self, node, env, base):
        v = self.eval(node.value, env, base)
        attr = node.attr
        if isinstance(v, Arr):
            if attr == "shape":
                if v.shape is None:
                    return UNKNOWN
                return TupV([DimV(d) for d in v.shape])
            if attr == "ndim":
                return StaticV(len(v.shape)) if v.shape is not None else StaticV()
            if attr == "dtype":
                return DtypeV(v.dtype) if v.dtype else StaticV()
            if attr == "T":
                if v.shape is None:
                    return Arr(None, v.dtype)
                return Arr(tuple(reversed(v.shape)), v.dtype)
            if attr == "at":
                return TupV([v])  # wrapped; unwrapped by .at[...].set/add
            return UNKNOWN
        if isinstance(v, RecV):
            return v.fields.get(attr, UNKNOWN)
        if isinstance(v, ModV):
            return self._module_attr(v, attr)
        if isinstance(v, DictV):
            return UNKNOWN  # method handled at call sites
        if isinstance(v, StaticV) and v.value is not _UNSET:
            try:
                return StaticV(getattr(v.value, attr))
            except Exception:
                return StaticV()
        if isinstance(v, TupV) and attr in ("items", "keys", "values"):
            return UNKNOWN
        return UNKNOWN

    def _module_attr(self, mod: ModV, attr: str):
        if mod.base.startswith("#"):
            return self.global_av(mod.base[1:], attr)
        if mod.base in ("jnp", "np"):
            if attr in _JNP_DTYPE_ATTRS:
                return DtypeV(_JNP_DTYPE_ATTRS[attr])
            return UNKNOWN  # jnp functions handled at call sites
        return UNKNOWN

    # -- subscripting ------------------------------------------------------

    def _slice_dim(self, sl: ast.Slice, length, env, base):
        """Resulting dim of a basic slice over an axis of dim ``length``
        (bounds assumed in range — this is a linter, not a prover)."""
        if sl.step is not None:
            st = self.eval(sl.step, env, base)
            if not (isinstance(st, StaticV) and st.value == 1):
                return None

        def _neg_const(d):
            lin = _as_lin(d)
            return lin is not None and not lin[1] and lin[0] < 0

        lo = 0
        if sl.lower is not None:
            lo = self.dim_of_value(self.eval(sl.lower, env, base))
            if lo is None:
                return None
        if sl.upper is None:
            if _neg_const(lo):
                return -_as_lin(lo)[0]  # x[-k:] → k
            return dim_add(length, lo, -1) if lo != 0 else length
        up = self.dim_of_value(self.eval(sl.upper, env, base))
        if up is None:
            return None
        if _neg_const(up):
            up = dim_add(length, up)  # x[:-k] → len - k
            if up is None:
                return None
        if _neg_const(lo):
            lo = dim_add(length, lo)
            if lo is None:
                return None
        return dim_add(up, lo, -1)

    def _eval_subscript(self, node, env, base):
        v = self.eval(node.value, env, base)
        sl = node.slice
        # x.at[idx] → wrapped (base, idx-node) for the .set/.add call model
        if isinstance(v, TupV) and len(v.items) == 1 and isinstance(
            node.value, ast.Attribute
        ) and node.value.attr == "at":
            return TupV([v.items[0], StaticV(("at-index", node))])
        if isinstance(v, TupV):
            iv = self.eval(sl, env, base)
            if isinstance(iv, StaticV) and isinstance(iv.value, int):
                if -len(v.items) <= iv.value < len(v.items):
                    return v.items[iv.value]
                return UNKNOWN
            if isinstance(sl, ast.Slice) and sl.step is None:
                def _bound(e):
                    if e is None:
                        return None, True
                    bv = self.eval(e, env, base)
                    if isinstance(bv, StaticV) and isinstance(bv.value, int):
                        return bv.value, True
                    return None, False
                lo, lo_ok = _bound(sl.lower)
                up, up_ok = _bound(sl.upper)
                if lo_ok and up_ok:
                    return TupV(v.items[slice(lo, up)])
            return UNKNOWN
        if isinstance(v, DictV):
            kv = self.eval(sl, env, base)
            if isinstance(kv, StaticV) and isinstance(kv.value, str):
                return v.entries.get(kv.value, UNKNOWN)
            return UNKNOWN
        if isinstance(v, StaticV):
            if v.value is _UNSET:
                return StaticV()
            kv = self.eval(sl, env, base)
            if isinstance(kv, StaticV) and kv.value is not _UNSET:
                try:
                    return StaticV(v.value[kv.value])
                except Exception:
                    return StaticV()
            return StaticV()
        if not isinstance(v, Arr):
            return UNKNOWN
        if v.shape is None:
            return Arr(None, v.dtype)

        items = list(sl.elts) if isinstance(sl, ast.Tuple) else [sl]
        # expand Ellipsis into full slices
        n_concrete = sum(
            1 for it in items
            if not (isinstance(it, ast.Constant) and it.value is Ellipsis)
            and not (isinstance(it, ast.Constant) and it.value is None)
        )
        expanded = []
        for it in items:
            if isinstance(it, ast.Constant) and it.value is Ellipsis:
                for _ in range(len(v.shape) - n_concrete):
                    expanded.append("full")
            else:
                expanded.append(it)
        out: List[object] = []
        axis = 0
        adv_shapes = []
        adv_pos = None
        gathered_axes = []
        for it in expanded:
            if it == "full":
                out.append(v.shape[axis] if axis < len(v.shape) else None)
                axis += 1
                continue
            if isinstance(it, ast.Constant) and it.value is None:
                out.append(1)
                continue
            if isinstance(it, ast.Slice):
                length = v.shape[axis] if axis < len(v.shape) else None
                if it.lower is None and it.upper is None and it.step is None:
                    out.append(length)
                else:
                    out.append(self._slice_dim(it, length, env, base))
                axis += 1
                continue
            iv = self.eval(it, env, base)
            if isinstance(iv, Arr):
                # advanced index: traced gather into this axis
                if axis < len(v.shape):
                    gathered_axes.append(v.shape[axis])
                if adv_pos is None:
                    adv_pos = len(out)
                    out.append("ADV")
                adv_shapes.append(iv.shape)
                axis += 1
                continue
            # static / host-int index: drops the axis
            axis += 1
        # trailing untouched axes
        while axis < len(v.shape):
            out.append(v.shape[axis])
            axis += 1
        for g in gathered_axes:
            if g is not None and dim_is_node_axis(g):
                self._shard_flag(
                    node, "implicit gather",
                    f"traced index into an {NODE_AXIS}-sized axis of "
                    f"{shape_str(v.shape)}",
                )
        if adv_pos is not None:
            bshape = self.broadcast_shapes(adv_shapes, node)
            final = []
            for o in out:
                if o == "ADV":
                    final.extend(bshape if bshape is not None else [None])
                else:
                    final.append(o)
            if bshape is None:
                return Arr(None, v.dtype)
            return Arr(tuple(final), v.dtype)
        return Arr(tuple(out), v.dtype)

    # -- operators ---------------------------------------------------------

    def _arith_dtype_checks(self, node, op, vals):
        arrs = [v for v in vals if isinstance(v, Arr)]
        if not arrs:
            return
        if isinstance(op, ast.Div):
            if all(
                a.dtype in _INT_DTYPES or a.dtype == "bool"
                for a in arrs if a.dtype is not None
            ) and any(a.dtype is not None for a in arrs) and not any(
                isinstance(v, StaticV) and isinstance(v.value, float)
                for v in vals
            ):
                self._dtype_flag(
                    node,
                    "true division on integer operands promotes to float "
                    "(the integer-score kernels are exact by construction) "
                    "— use // or an explicit astype",
                )
            return
        if isinstance(op, (ast.Add, ast.Sub, ast.Mult, ast.FloorDiv,
                           ast.Mod, ast.Pow)):
            for a in arrs:
                if a.dtype == "bool":
                    self._dtype_flag(
                        node,
                        "arithmetic on a bool operand promotes implicitly "
                        "— spell .astype(...) so the accumulator dtype is "
                        "chosen, not inherited",
                    )
                    break
            for v in vals:
                if isinstance(v, StaticV) and isinstance(v.value, float) \
                        and not isinstance(v.value, bool):
                    if any(a.dtype in _INT_DTYPES for a in arrs):
                        self._dtype_flag(
                            node,
                            "float literal widens an integer array "
                            "(weak-type promotion inside the kernel)",
                        )
                    break

    def _eval_binop(self, node, env, base):
        lv = self.eval(node.left, env, base)
        rv = self.eval(node.right, env, base)
        op = node.op
        # host-int symbolic arithmetic
        hl = isinstance(lv, (DimV, StaticV))
        hr = isinstance(rv, (DimV, StaticV))
        if hl and hr:
            if isinstance(lv, StaticV) and isinstance(rv, StaticV) and \
                    lv.value is not _UNSET and rv.value is not _UNSET:
                try:
                    return StaticV(_PYOPS[type(op)](lv.value, rv.value))
                except Exception:
                    return StaticV()
            dl = self.dim_of_value(lv)
            dr = self.dim_of_value(rv)
            if dl is not None and dr is not None:
                if isinstance(op, ast.Add):
                    return DimV(dim_add(dl, dr))
                if isinstance(op, ast.Sub):
                    return DimV(dim_add(dl, dr, -1))
                if isinstance(op, ast.Mult):
                    return DimV(dim_mul(dl, dr))
                if isinstance(op, ast.FloorDiv):
                    return DimV(dim_opaque("div", dl, dr))
                if isinstance(op, ast.Mod):
                    return DimV(dim_opaque("mod", dl, dr))
            if isinstance(lv, TupV) or isinstance(rv, TupV):
                pass
            return StaticV()
        # tuple concatenation / repetition (shape algebra)
        if isinstance(lv, TupV) and isinstance(rv, TupV) and \
                isinstance(op, ast.Add):
            return TupV(lv.items + rv.items)
        if isinstance(lv, TupV) and isinstance(op, ast.Mult):
            n = rv.value if isinstance(rv, StaticV) and isinstance(
                rv.value, int) else None
            if n is not None and 0 <= n <= 16:
                return TupV(lv.items * n)
            return UNKNOWN
        if isinstance(rv, TupV) and isinstance(op, ast.Mult):
            n = lv.value if isinstance(lv, StaticV) and isinstance(
                lv.value, int) else None
            if n is not None and 0 <= n <= 16:
                return TupV(rv.items * n)
            return UNKNOWN
        arrs = [v for v in (lv, rv) if isinstance(v, Arr)]
        if not arrs:
            return UNKNOWN
        self._arith_dtype_checks(node, op, [lv, rv])
        shape = self.broadcast_shapes(
            [a.shape for a in arrs], node
        )
        if isinstance(op, ast.Div):
            dt = "f64"
        elif isinstance(op, (ast.LShift, ast.RShift, ast.BitAnd, ast.BitOr,
                             ast.BitXor)):
            dts = [a.dtype for a in arrs]
            if all(d == "bool" for d in dts if d is not None) and any(dts):
                dt = "bool"
            else:
                dt = promote_dtype(*(dts + [dts[0]])[:2]) if len(dts) == 2 \
                    else dts[0]
                if dt == "bool":
                    dt = None
        else:
            if len(arrs) == 2:
                dt = promote_dtype(arrs[0].dtype, arrs[1].dtype)
                if dt == "bool":
                    dt = "i64"  # bool arithmetic promotes (flagged above)
            else:
                dt = arrs[0].dtype
                if dt == "bool" and isinstance(op, (ast.Add, ast.Sub,
                                                    ast.Mult)):
                    dt = "i64"
        return Arr(shape, dt)

    def _eval_unary(self, node, env, base):
        v = self.eval(node.operand, env, base)
        if isinstance(node.op, ast.Not):
            if isinstance(v, StaticV) and v.value is not _UNSET:
                return StaticV(not v.value)
            return StaticV()
        if isinstance(v, Arr):
            if isinstance(node.op, ast.USub):
                self._arith_dtype_checks(node, ast.Sub(), [v])
            return Arr(v.shape, v.dtype)
        if isinstance(v, (DimV, StaticV)):
            if isinstance(v, StaticV) and v.value is not _UNSET:
                try:
                    return StaticV(
                        -v.value if isinstance(node.op, ast.USub) else v.value
                    )
                except Exception:
                    return StaticV()
            if isinstance(v, DimV) and isinstance(node.op, ast.USub):
                return DimV(dim_mul(v.lin, -1))
            return StaticV()
        return UNKNOWN

    def _eval_compare(self, node, env, base):
        # `x is None` / `x is not None` decide when the operand is known
        if len(node.ops) == 1 and isinstance(node.ops[0], (ast.Is, ast.IsNot)):
            sides = [node.left, node.comparators[0]]
            if any(isinstance(s, ast.Constant) and s.value is None
                   for s in sides):
                other = sides[1] if isinstance(sides[0], ast.Constant) \
                    else sides[0]
                ov = self.eval(other, env, base)
                neg = isinstance(node.ops[0], ast.IsNot)
                if is_none_val(ov):
                    return StaticV(not neg)  # `x is None` → True
                if definitely_not_none(ov):
                    return StaticV(neg)  # `x is None` → False
                return StaticV()
        vals = [self.eval(node.left, env, base)] + [
            self.eval(c, env, base) for c in node.comparators
        ]
        # dtype identity checks (`rows.dtype == jnp.bool_`) decide when
        # both sides resolve — prunes per-dtype dispatch branches
        if len(vals) == 2 and all(isinstance(v, DtypeV) for v in vals) and \
                len(node.ops) == 1 and isinstance(node.ops[0],
                                                  (ast.Eq, ast.NotEq)):
            same = vals[0].dt == vals[1].dt
            if vals[0].dt is not None and vals[1].dt is not None:
                return StaticV(
                    same if isinstance(node.ops[0], ast.Eq) else not same
                )
            return StaticV()
        statics = [v for v in vals if isinstance(v, StaticV)
                   and v.value is not _UNSET]
        if len(statics) == len(vals) and len(node.ops) == 1:
            try:
                return StaticV(
                    _PYCMP[type(node.ops[0])](statics[0].value,
                                              statics[1].value)
                )
            except Exception:
                return StaticV()
        arrs = [v for v in vals if isinstance(v, Arr)]
        if arrs:
            shape = self.broadcast_shapes([a.shape for a in arrs], node)
            return Arr(shape, "bool")
        return StaticV()

    def truthiness(self, test, env, base):
        """True / False when statically decidable, else None."""
        v = self.eval(test, env, base)
        if isinstance(v, StaticV) and v.value is not _UNSET:
            try:
                return bool(v.value)
            except Exception:
                return None
        if is_none_val(v):
            return False
        return None

    # -- calls -------------------------------------------------------------

    def _eval_call(self, node, env, base):
        func = node.func
        # call-of-a-call: `jax.vmap(fn)(args)` and friends — evaluate the
        # inner call ONCE and dispatch on its value
        if isinstance(func, ast.Call):
            callee = self.eval(func, env, base)
            if isinstance(callee, _MappedV):
                return self._call_mapped(node, callee, env, base)
            if isinstance(callee, FuncV):
                return self._call_funcv(node, callee, env, base)
            if isinstance(callee, CtorV):
                return self._construct(node, callee, env, base)
            for a in node.args:
                self.eval(a, env, base)
            return UNKNOWN
        # dict(...) / tuple() / list() builtins and dict(state, k=v) copies
        if isinstance(func, ast.Name) and func.id not in env:
            r = self._builtin_call(node, func.id, env, base)
            if r is not NOT_BUILTIN:
                return r
        # method calls on abstract values
        if isinstance(func, ast.Attribute):
            r = self._method_call(node, func, env, base)
            if r is not NOT_BUILTIN:
                return r
        dn = dotted_name(func)
        if dn is not None:
            parts = dn.split(".")
            rootv = env.get(parts[0], None)
            if rootv is None:
                rootv = self.global_av(base, parts[0])
            # jnp./np./jax./lax. library calls
            if isinstance(rootv, ModV) and not rootv.base.startswith("#"):
                return self._library_call(node, rootv.base, parts[1:], env,
                                          base)
        callee = self.eval(func, env, base)
        if isinstance(callee, FuncV):
            return self._call_funcv(node, callee, env, base)
        if isinstance(callee, CtorV):
            return self._construct(node, callee, env, base)
        if isinstance(callee, DtypeV):
            return callee  # I32(x)-style casts don't occur; keep dtype
        return UNKNOWN

    def _args_kwargs(self, node, env, base):
        args = [self.eval(a, env, base) for a in node.args
                if not isinstance(a, ast.Starred)]
        kwargs = {}
        for kw in node.keywords:
            if kw.arg is None:
                v = self.eval(kw.value, env, base)
                if isinstance(v, DictV):
                    kwargs.update(v.entries)
            else:
                kwargs[kw.arg] = self.eval(kw.value, env, base)
        return args, kwargs

    def _builtin_call(self, node, name, env, base):
        args, kwargs = None, None
        if name == "len":
            if node.args:
                v = self.eval(node.args[0], env, base)
                if isinstance(v, Arr) and v.shape is not None and v.shape:
                    return DimV(v.shape[0])
                if isinstance(v, TupV):
                    return StaticV(len(v.items))
            return StaticV()
        if name in ("min", "max"):
            args = [self.eval(a, env, base) for a in node.args]
            dims = [self.dim_of_value(a) for a in args]
            if all(d is not None for d in dims) and len(dims) >= 2:
                ints = [d for d in dims if isinstance(d, int)]
                if len(ints) == len(dims):
                    return StaticV(min(ints) if name == "min" else max(ints))
                return DimV(dim_opaque(name, *dims))
            return StaticV()
        if name in ("int", "bool", "float", "str", "abs", "sorted", "sum",
                    "repr", "hash", "isinstance", "getattr", "hasattr",
                    "frozenset", "set", "enumerate", "zip", "range",
                    "reversed", "print", "id", "any", "all", "map"):
            for a in node.args:
                self.eval(a, env, base)
            return StaticV()
        if name == "tuple":
            if node.args:
                v = self.eval(node.args[0], env, base)
                if isinstance(v, TupV):
                    return v
            return TupV([]) if not node.args else StaticV()
        if name == "list":
            if node.args:
                v = self.eval(node.args[0], env, base)
                if isinstance(v, TupV):
                    return v
                return UNKNOWN
            return TupV([])
        if name == "dict":
            args, kwargs = self._args_kwargs(node, env, base)
            entries = {}
            for a in args:
                if isinstance(a, DictV):
                    entries.update(a.entries)
                else:
                    return UNKNOWN
            entries.update(kwargs)
            return DictV(entries)
        return NOT_BUILTIN

    def _method_call(self, node, func, env, base):
        attr = func.attr
        recv_node = func.value
        # x.at[idx].set(v) / .add(v)
        if attr in ("set", "add", "multiply", "min", "max") and isinstance(
            recv_node, ast.Subscript
        ):
            wrapped = self.eval(recv_node, env, base)
            if isinstance(wrapped, TupV) and len(wrapped.items) == 2 and \
                    isinstance(wrapped.items[1], StaticV) and isinstance(
                        wrapped.items[1].value, tuple) and \
                    wrapped.items[1].value[0] == "at-index":
                arr = wrapped.items[0]
                idx_node = wrapped.items[1].value[1]
                for a in node.args:
                    self.eval(a, env, base)
                if isinstance(arr, Arr) and arr.shape is not None and \
                        arr.shape and dim_is_node_axis(arr.shape[0]):
                    iv = self.eval(idx_node.slice, env, base)
                    if isinstance(iv, Arr):
                        self._shard_flag(
                            node, "scatter",
                            f".at[...].{attr} with a traced index into an "
                            f"{NODE_AXIS}-leading array "
                            f"{shape_str(arr.shape)}",
                        )
                return arr if isinstance(arr, Arr) else UNKNOWN
        recv = self.eval(recv_node, env, base)
        if isinstance(recv, Arr):
            if attr == "astype":
                dt = None
                if node.args:
                    dt = self.dtype_from_expr(node.args[0], env, base)
                return Arr(recv.shape, dt)
            if attr == "reshape":
                return self._reshape(node, recv, env, base)
            if attr in _REDUCERS:
                return self._reduce_call(node, recv, attr, env, base)
            if attr in ("copy", "block_until_ready", "clip"):
                return Arr(recv.shape, recv.dtype)
            if attr == "transpose":
                if recv.shape is not None and not node.args:
                    return Arr(tuple(reversed(recv.shape)), recv.dtype)
                return Arr(None, recv.dtype)
            return UNKNOWN
        if isinstance(recv, DictV):
            if attr == "get":
                kv = self.eval(node.args[0], env, base) if node.args else None
                default = self.eval(node.args[1], env, base) \
                    if len(node.args) > 1 else NONE
                if isinstance(kv, StaticV) and isinstance(kv.value, str):
                    return recv.entries.get(kv.value, default)
                return UNKNOWN
            if attr == "pop":
                kv = self.eval(node.args[0], env, base) if node.args else None
                if isinstance(kv, StaticV) and isinstance(kv.value, str):
                    return recv.entries.pop(kv.value, UNKNOWN)
                return UNKNOWN
            if attr == "update":
                for a in node.args:
                    av = self.eval(a, env, base)
                    if isinstance(av, DictV):
                        recv.entries.update(av.entries)
                _, kwargs = self._args_kwargs(node, env, base)
                recv.entries.update(kwargs)
                return NONE
            if attr == "values":
                vals = list(recv.entries.values())
                return TupV(vals)
            if attr == "keys":
                return TupV([StaticV(k) for k in recv.entries])
            if attr == "items":
                return TupV([
                    TupV([StaticV(k), v]) for k, v in recv.entries.items()
                ])
            if attr == "setdefault":
                return UNKNOWN
            return UNKNOWN
        if isinstance(recv, TupV):
            if attr == "append" and node.args:
                recv.items.append(self.eval(node.args[0], env, base))
                return NONE
            if attr == "extend" and node.args:
                v = self.eval(node.args[0], env, base)
                if isinstance(v, TupV):
                    recv.items.extend(v.items)
                return NONE
            return UNKNOWN
        if isinstance(recv, RecV):
            if attr == "_replace":
                _, kwargs = self._args_kwargs(node, env, base)
                fields = dict(recv.fields)
                fields.update(kwargs)
                return RecV(recv.cls, fields)
            return UNKNOWN
        if isinstance(recv, StaticV):
            for a in node.args:
                self.eval(a, env, base)
            return StaticV()
        return NOT_BUILTIN

    # -- library (jnp / lax / jax) calls -----------------------------------

    def _library_call(self, node, libroot, tail, env, base):
        if not tail:
            return UNKNOWN
        name = tail[-1]
        # jax.lax.X / jax.ops.X / jax.random.X routed by their submodule
        sub = tail[0] if len(tail) > 1 else None
        if libroot == "jax" and sub in ("numpy",):
            libroot, sub = "jnp", None
        if libroot == "lax" or (libroot == "jax" and sub == "lax"):
            return self._lax_call(node, name, env, base)
        if libroot == "jax" and sub == "ops":
            return self._segment_call(node, name, env, base)
        if libroot == "jax" and sub == "random":
            return self._random_call(node, name, env, base)
        if libroot == "jax" and sub == "tree_util":
            for a in node.args:
                self.eval(a, env, base)
            return UNKNOWN
        if libroot == "jax":
            if name == "vmap":
                return self._vmap(node, env, base)
            if name == "jit":
                return UNKNOWN
            for a in node.args:
                self.eval(a, env, base)
            return UNKNOWN
        # jnp.* / np.*
        return self._jnp_call(node, name, env, base)

    def _keyword(self, node, name):
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _dtype_kw(self, node, env, base, pos=None):
        kw = self._keyword(node, "dtype")
        if kw is not None:
            return self.dtype_from_expr(kw, env, base)
        if pos is not None and len(node.args) > pos:
            return self.dtype_from_expr(node.args[pos], env, base)
        return None

    def _reduce_axes(self, node, arr, env, base):
        """(reduced dims, kept shape) for a reduction call over ``arr``."""
        if arr.shape is None:
            return None, None
        kw = self._keyword(node, "axis")
        if kw is None and len(node.args) > 1:
            kw = node.args[1]
        keepdims = False
        kd = self._keyword(node, "keepdims")
        if kd is not None:
            v = self.eval(kd, env, base)
            keepdims = bool(isinstance(v, StaticV) and v.value is True)
        rank = len(arr.shape)
        if kw is None:
            axes = list(range(rank))
        else:
            av = self.eval(kw, env, base)
            axes = None
            if isinstance(av, StaticV) and isinstance(av.value, int):
                axes = [av.value % rank if rank else 0]
            elif isinstance(av, TupV):
                axes = []
                for it in av.items:
                    if isinstance(it, StaticV) and isinstance(it.value, int):
                        axes.append(it.value % rank if rank else 0)
                    else:
                        return None, None
            if axes is None:
                return None, None
        reduced = [arr.shape[a] for a in axes if a < rank]
        if keepdims:
            kept = tuple(
                1 if i in axes else d for i, d in enumerate(arr.shape)
            )
        else:
            kept = tuple(
                d for i, d in enumerate(arr.shape) if i not in axes
            )
        return reduced, kept

    def _reduce_call(self, node, arr, name, env, base):
        reduced, kept = self._reduce_axes(node, arr, env, base)
        if reduced is None:
            if arr.shape is not None and len(arr.shape) <= 1 and \
                    self._keyword(node, "axis") is None and \
                    len(node.args) <= 1:
                reduced, kept = list(arr.shape), ()
            else:
                # unresolvable axis argument: permissive silence
                return Arr(None, None)
        for d in reduced:
            if d is not None and dim_is_node_axis(d):
                self._shard_flag(
                    node, f"{name} reduction",
                    f"reduces {shape_str(arr.shape)} over {NODE_AXIS}",
                )
                break
        if name in ("any", "all"):
            dt = "bool"
        elif name in ("argmax", "argmin", "count_nonzero"):
            dt = None
        elif name in ("sum", "prod", "nansum") and (
            arr.dtype == "bool" or arr.dtype in _INT_DTYPES
        ):
            # numpy accumulation semantics: integer/bool sums promote to
            # the default int — i64 with x64 (enforced at package import)
            dt = "i64" if arr.dtype != "u64" else "u64"
        elif name == "mean":
            dt = None
        else:
            dt = arr.dtype
        return Arr(kept, dt)

    def _reshape(self, node, arr, env, base):
        args = [self.eval(a, env, base) for a in node.args]
        if len(args) == 1 and isinstance(args[0], TupV):
            dims = list(self.shape_from_value(args[0]) or [])
            if not dims and args[0].items == []:
                dims = []
        else:
            dims = [self.dim_of_value(a) for a in args]
        if any(
            isinstance(a, StaticV) and a.value == -1 for a in (
                args[0].items if len(args) == 1 and isinstance(args[0], TupV)
                else args
            )
        ):
            # resolve -1 deterministically from the total element count
            flat = args[0].items if len(args) == 1 and isinstance(
                args[0], TupV) else args
            total = dims_product(arr.shape) if arr.shape is not None else None
            known = []
            neg_at = None
            for i, a in enumerate(flat):
                d = self.dim_of_value(a)
                if isinstance(a, StaticV) and a.value == -1:
                    neg_at = i
                    known.append(None)
                else:
                    known.append(d)
            if total is not None and neg_at is not None and all(
                d is not None for i, d in enumerate(known) if i != neg_at
            ):
                rest = dims_product(
                    [d for i, d in enumerate(known) if i != neg_at] or [1]
                )
                if rest is not None:
                    if dim_eq(rest, 1):
                        known[neg_at] = total
                    elif dim_eq(total, rest):
                        known[neg_at] = 1
                    else:
                        known[neg_at] = dim_opaque("div", total, rest)
            return Arr(tuple(known), arr.dtype)
        if dims and all(d is not None for d in dims):
            return Arr(tuple(dims), arr.dtype)
        if len(args) == 1 and isinstance(args[0], TupV):
            return Arr(tuple(self.dim_of_value(x) for x in args[0].items),
                       arr.dtype)
        return Arr(None, arr.dtype)

    def _jnp_call(self, node, name, env, base):
        args = [self.eval(a, env, base) for a in node.args]
        if name in ("zeros", "ones", "empty", "full"):
            shape = self.shape_from_value(args[0]) if args else None
            if name == "full":
                dt = self._dtype_kw(node, env, base, pos=2)
                if dt is None and len(args) > 1:
                    fill = args[1]
                    if isinstance(fill, StaticV) and isinstance(
                            fill.value, bool):
                        dt = "bool"
            else:
                dt = self._dtype_kw(node, env, base, pos=1)
            if dt is None:
                # jnp.zeros((N,), bool)-style positional dtype
                pos = 2 if name == "full" else 1
                if len(node.args) > pos:
                    dt = self.dtype_from_expr(node.args[pos], env, base)
            return Arr(shape, dt or ("f64" if name != "full" else None))
        if name in ("zeros_like", "ones_like", "full_like", "empty_like"):
            src = args[0] if args else UNKNOWN
            pos = 2 if name == "full_like" else 1
            dt = self._dtype_kw(node, env, base, pos=pos)
            if isinstance(src, Arr):
                return Arr(src.shape, dt or src.dtype)
            return UNKNOWN
        if name == "asarray" or name == "array":
            dt = self._dtype_kw(node, env, base, pos=1)
            src = args[0] if args else UNKNOWN
            if isinstance(src, Arr):
                return Arr(src.shape, dt or src.dtype)
            if isinstance(src, (DimV, StaticV)):
                if dt is None and isinstance(src, StaticV):
                    if isinstance(src.value, bool):
                        dt = "bool"
                return Arr((), dt)
            if isinstance(src, TupV):
                return Arr((len(src.items),), dt)
            return Arr(None, dt)
        if name == "arange":
            dt = self._dtype_kw(node, env, base)
            if len(node.args) == 1 and args:
                d = self.dim_of_value(args[0])
                return Arr((d,), dt or "i64")
            if len(args) >= 2:
                lo = self.dim_of_value(args[0])
                hi = self.dim_of_value(args[1])
                if lo is not None and hi is not None and len(args) == 2:
                    return Arr((dim_add(hi, lo, -1),), dt or "i64")
            return Arr((None,), dt or "i64")
        if name == "broadcast_to":
            shape = self.shape_from_value(args[1]) if len(args) > 1 else None
            dt = args[0].dtype if args and isinstance(args[0], Arr) else None
            return Arr(shape, dt)
        if name in ("concatenate", "stack"):
            seq = args[0] if args else UNKNOWN
            axv = self._keyword(node, "axis")
            axis = 0
            if axv is not None:
                a = self.eval(axv, env, base)
                if isinstance(a, StaticV) and isinstance(a.value, int):
                    axis = a.value
                else:
                    return UNKNOWN
            elif len(node.args) > 1:
                a = args[1]
                if isinstance(a, StaticV) and isinstance(a.value, int):
                    axis = a.value
                else:
                    return UNKNOWN
            if not isinstance(seq, TupV) or not seq.items:
                return UNKNOWN
            parts = [p for p in seq.items]
            if not all(isinstance(p, Arr) for p in parts):
                return UNKNOWN
            dts = [p.dtype for p in parts if p.dtype is not None]
            dt = dts[0] if dts and all(d == dts[0] for d in dts) else None
            shapes = [p.shape for p in parts]
            if any(s is None for s in shapes):
                return Arr(None, dt)
            if name == "stack":
                # all inputs must agree; check named mismatches pairwise
                joinshape = self.broadcast_shapes(shapes, node)
                rank = len(shapes[0])
                ax = axis % (rank + 1)
                if joinshape is None or len(joinshape) != rank:
                    return Arr(None, dt)
                out = list(joinshape)
                out.insert(ax, len(parts))
                return Arr(tuple(out), dt)
            rank = len(shapes[0])
            if any(len(s) != rank for s in shapes):
                return Arr(None, dt)
            ax = axis % rank if rank else 0
            out = []
            for i in range(rank):
                if i == ax:
                    tot = 0
                    for s in shapes:
                        tot = dim_add(tot, s[i])
                    out.append(tot)
                else:
                    dims = [s[i] for s in shapes]
                    cur = dims[0]
                    for d in dims[1:]:
                        if cur is None or d is None:
                            cur = None
                        elif not dim_eq(cur, d):
                            if dim_is_named(cur) and dim_is_named(d):
                                self._shape_flag(
                                    node,
                                    f"concatenate along axis {ax} aligns "
                                    f"{dim_str(cur)} with {dim_str(d)} on "
                                    f"axis {i}",
                                )
                            cur = None
                    out.append(cur)
            return Arr(tuple(out), dt)
        if name == "einsum":
            return self._einsum(node, args, env, base)
        if name in ("take",):
            arr = args[1] if len(args) > 1 and isinstance(args[0], StaticV) \
                else (args[0] if args else UNKNOWN)
            # jnp.take(arr, idx, axis=?) — axis None flattens; default 0? jnp
            # take without axis flattens; the tree always passes 1-D arrays
            if len(args) >= 2 and isinstance(args[0], Arr) and isinstance(
                    args[1], Arr):
                src, idx = args[0], args[1]
                if src.shape is not None and src.shape and dim_is_node_axis(
                        src.shape[0]):
                    self._shard_flag(
                        node, "implicit gather",
                        f"jnp.take from an {NODE_AXIS}-leading array",
                    )
                if src.shape is not None and len(src.shape) == 1:
                    return Arr(idx.shape, src.dtype)
            return UNKNOWN
        if name == "take_along_axis":
            if len(args) >= 2 and isinstance(args[0], Arr) and isinstance(
                    args[1], Arr):
                return Arr(args[1].shape, args[0].dtype)
            return UNKNOWN
        if name in _SAME_SHAPE_FNS:
            src = args[0] if args else UNKNOWN
            if isinstance(src, Arr):
                dt = src.dtype
                if name in _BOOL_RESULT_FNS:
                    dt = "bool"
                if name in ("argsort",):
                    dt = None
                return Arr(src.shape, dt)
            return UNKNOWN
        if name in _REDUCERS:
            src = args[0] if args else UNKNOWN
            if isinstance(src, Arr):
                return self._reduce_call(node, src, name, env, base)
            return UNKNOWN
        if name in _BROADCAST_FNS:
            arrs = [a for a in args if isinstance(a, Arr)]
            if not arrs:
                return UNKNOWN
            if name in ("multiply", "add", "subtract", "power", "mod",
                        "floor_divide"):
                self._arith_dtype_checks(
                    node,
                    ast.Mult() if name == "multiply" else ast.Add(),
                    args,
                )
            shape = self.broadcast_shapes([a.shape for a in arrs], node)
            if name in _BOOL_RESULT_FNS:
                dt = "bool"
            elif name == "where":
                branch = [a for a in args[1:] if isinstance(a, Arr)]
                dts = [b.dtype for b in branch if b.dtype is not None]
                dt = dts[0] if len(dts) == len(branch) and branch and all(
                    d == dts[0] for d in dts) else (
                        dts[0] if len(branch) == 1 and dts else None)
                if len(args) >= 3:
                    shape = self.broadcast_shapes(
                        [a.shape for a in args if isinstance(a, Arr)], node
                    )
            elif name == "clip":
                dt = arrs[0].dtype
            else:
                dts = [a.dtype for a in arrs]
                dt = dts[0] if len(dts) >= 1 and all(
                    d == dts[0] for d in dts if d is not None
                ) and dts[0] is not None else None
            return Arr(shape, dt)
        if name == "pad":
            src = args[0] if args else UNKNOWN
            if isinstance(src, Arr):
                return Arr(None, src.dtype)
            return UNKNOWN
        if name == "iinfo" or name == "finfo":
            return StaticV()
        if name in ("searchsorted", "bincount", "unique", "nonzero",
                    "digitize"):
            return UNKNOWN
        if name == "dot":
            return UNKNOWN
        if name in ("matmul", "tensordot"):
            return UNKNOWN
        if name == "expand_dims":
            if args and isinstance(args[0], Arr) and args[0].shape is not None:
                axv = args[1] if len(args) > 1 else None
                if isinstance(axv, StaticV) and isinstance(axv.value, int):
                    out = list(args[0].shape)
                    ax = axv.value % (len(out) + 1)
                    out.insert(ax, 1)
                    return Arr(tuple(out), args[0].dtype)
            return UNKNOWN
        if name == "squeeze":
            return UNKNOWN
        if name == "tile":
            return UNKNOWN
        if name == "roll":
            if args and isinstance(args[0], Arr):
                return Arr(args[0].shape, args[0].dtype)
            return UNKNOWN
        return UNKNOWN

    def _einsum(self, node, args, env, base):
        if not node.args or not isinstance(node.args[0], ast.Constant) or \
                not isinstance(node.args[0].value, str):
            return UNKNOWN
        spec = node.args[0].value.replace(" ", "")
        if "->" not in spec or "..." in spec:
            return UNKNOWN
        ins, out = spec.split("->")
        in_specs = ins.split(",")
        operands = args[1:]
        if len(in_specs) != len(operands):
            return UNKNOWN
        letter_dim: Dict[str, object] = {}
        for sp, op in zip(in_specs, operands):
            if not isinstance(op, Arr) or op.shape is None or \
                    len(op.shape) != len(sp):
                for ch in sp:
                    letter_dim.setdefault(ch, None)
                continue
            for ch, d in zip(sp, op.shape):
                if ch in letter_dim:
                    prev = letter_dim[ch]
                    if prev is not None and d is not None and \
                            not dim_eq(prev, d):
                        if dim_is_named(prev) and dim_is_named(d):
                            self._shape_flag(
                                node,
                                f"einsum '{spec}' binds '{ch}' to both "
                                f"{dim_str(prev)} and {dim_str(d)}",
                            )
                        letter_dim[ch] = None
                else:
                    letter_dim[ch] = d
        contracted = [ch for ch in letter_dim if ch not in out]
        for ch in contracted:
            d = letter_dim.get(ch)
            if d is not None and dim_is_node_axis(d):
                self._shard_flag(
                    node, "einsum contraction",
                    f"'{spec}' contracts '{ch}' = {NODE_AXIS}",
                )
        dts = [op.dtype for op in operands if isinstance(op, Arr)]
        dt = dts[0] if dts and all(d == dts[0] for d in dts) else None
        return Arr(tuple(letter_dim.get(ch) for ch in out), dt)

    def _lax_call(self, node, name, env, base):
        args = [self.eval(a, env, base) for a in node.args]
        if name == "scan":
            return self._scan(node, args, env, base)
        if name == "while_loop":
            return self._while_loop(node, args, env, base)
        if name == "fori_loop":
            return self._fori_loop(node, args, env, base)
        if name == "cond":
            return self._cond(node, args, env, base)
        if name in ("cummax", "cummin", "cumsum", "cumprod",
                    "associative_scan"):
            src = args[0] if args else UNKNOWN
            if isinstance(src, Arr):
                return Arr(src.shape, src.dtype)
            return UNKNOWN
        if name == "dynamic_slice":
            if len(args) >= 3:
                sizes = self.shape_from_value(args[2])
                dt = args[0].dtype if isinstance(args[0], Arr) else None
                src = args[0]
                if isinstance(src, Arr) and src.shape is not None and \
                        src.shape and dim_is_node_axis(src.shape[0]):
                    # dynamic_slice READS across shards only when the start
                    # is traced — which it always is here; flag it
                    self._shard_flag(
                        node, "dynamic_slice",
                        f"windowed read of an {NODE_AXIS}-leading array",
                    )
                return Arr(sizes, dt)
            return UNKNOWN
        if name == "dynamic_update_slice":
            if len(args) >= 2 and isinstance(args[0], Arr):
                dst, upd = args[0], args[1]
                if isinstance(upd, Arr) and dst.shape is not None and \
                        upd.shape is not None and \
                        len(dst.shape) != len(upd.shape):
                    self._shape_flag(
                        node,
                        "dynamic_update_slice rank mismatch: "
                        f"{shape_str(dst.shape)} vs {shape_str(upd.shape)}",
                    )
                if dst.shape is not None and dst.shape and \
                        dim_is_node_axis(dst.shape[0]):
                    self._shard_flag(
                        node, "dynamic_update_slice",
                        f"windowed write into an {NODE_AXIS}-leading array",
                    )
                return Arr(dst.shape, dst.dtype)
            return UNKNOWN
        if name == "dot_general":
            return self._dot_general(node, args, env, base)
        if name in ("bitcast_convert_type", "convert_element_type"):
            dt = self.dtype_from_expr(node.args[1], env, base) \
                if len(node.args) > 1 else None
            if args and isinstance(args[0], Arr):
                return Arr(None, dt)
            return UNKNOWN
        if name in ("with_sharding_constraint", "stop_gradient"):
            # layout/AD annotations: identity on shape and dtype
            return args[0] if args else UNKNOWN
        if name == "top_k":
            return UNKNOWN
        if name == "slice":
            return UNKNOWN
        if name == "select":
            arrs = [a for a in args if isinstance(a, Arr)]
            if arrs:
                shape = self.broadcast_shapes([a.shape for a in arrs], node)
                return Arr(shape, arrs[-1].dtype)
            return UNKNOWN
        return UNKNOWN

    def _dot_general(self, node, args, env, base):
        if len(node.args) < 3:
            return UNKNOWN
        try:
            dims = ast.literal_eval(node.args[2])
        except (ValueError, SyntaxError):
            return UNKNOWN
        lhs, rhs = args[0], args[1]
        if not (isinstance(lhs, Arr) and isinstance(rhs, Arr)) or \
                lhs.shape is None or rhs.shape is None:
            return UNKNOWN
        (lc, rc), (lb, rb) = dims
        for i, j in zip(lc, rc):
            dl, dr = lhs.shape[i], rhs.shape[j]
            if dl is not None and dr is not None and not dim_eq(dl, dr):
                if dim_is_named(dl) and dim_is_named(dr):
                    self._shape_flag(
                        node,
                        f"dot_general contracts {dim_str(dl)} against "
                        f"{dim_str(dr)}",
                    )
            if (dl is not None and dim_is_node_axis(dl)) or (
                    dr is not None and dim_is_node_axis(dr)):
                self._shard_flag(
                    node, "dot_general contraction",
                    f"contracts the {NODE_AXIS} axis",
                )
        batch = [lhs.shape[i] for i in lb]
        lfree = [d for i, d in enumerate(lhs.shape)
                 if i not in lc and i not in lb]
        rfree = [d for i, d in enumerate(rhs.shape)
                 if i not in rc and i not in rb]
        dt = None
        pet = self._keyword(node, "preferred_element_type")
        if pet is not None:
            dt = self.dtype_from_expr(pet, env, base)
        elif lhs.dtype == rhs.dtype:
            dt = lhs.dtype
        return Arr(tuple(batch + lfree + rfree), dt)

    def _segment_call(self, node, name, env, base):
        if name not in ("segment_sum", "segment_max", "segment_min",
                        "segment_prod"):
            return UNKNOWN
        args = [self.eval(a, env, base) for a in node.args]
        data = args[0] if args else UNKNOWN
        nseg = None
        kw = self._keyword(node, "num_segments")
        if kw is not None:
            nseg = self.dim_of_value(self.eval(kw, env, base))
        elif len(args) > 2:
            nseg = self.dim_of_value(args[2])
        if isinstance(data, Arr) and data.shape is not None and data.shape:
            d0 = data.shape[0]
            crossing = (d0 is not None and dim_is_node_axis(d0)) or (
                nseg is not None and dim_is_named(nseg)
                and dim_of_sym(NODE_AXIS)[1][0][0] in dict(_as_lin(nseg)[1])
            )
            if crossing:
                self._shard_flag(
                    node, f"{name} segment op",
                    f"segments cross the {NODE_AXIS} axis "
                    f"(data {shape_str(data.shape)}, "
                    f"num_segments {dim_str(nseg)})",
                )
            return Arr((nseg,) + data.shape[1:], data.dtype)
        return UNKNOWN

    def _random_call(self, node, name, env, base):
        for a in node.args:
            self.eval(a, env, base)
        if name in ("bits", "uniform", "normal", "randint"):
            shp = self._keyword(node, "shape")
            sv = None
            if shp is not None:
                sv = self.shape_from_value(self.eval(shp, env, base))
            elif len(node.args) > 1:
                sv = self.shape_from_value(self.eval(node.args[1], env, base))
            dt = self._dtype_kw(node, env, base)
            return Arr(sv, dt)
        return UNKNOWN

    # -- higher-order: vmap / scan / while / cond --------------------------

    def _strip_lead(self, v, node):
        """Remove axis 0 from every array leaf (vmap operand view).
        Returns (stripped value, lead dim or None)."""
        if isinstance(v, Arr):
            if v.shape is None or not v.shape:
                return Arr(None, v.dtype), None
            return Arr(v.shape[1:], v.dtype), v.shape[0]
        if isinstance(v, TupV):
            outs, leads = [], []
            for it in v.items:
                s, l = self._strip_lead(it, node)
                outs.append(s)
                leads.append(l)
            lead = next((l for l in leads if l is not None), None)
            return TupV(outs), lead
        if isinstance(v, RecV):
            fields, lead = {}, None
            for k, it in v.fields.items():
                s, l = self._strip_lead(it, node)
                fields[k] = s
                if lead is None:
                    lead = l
            return RecV(v.cls, fields), lead
        return UNKNOWN, None

    def _prepend_lead(self, v, lead):
        if isinstance(v, Arr):
            if v.shape is None:
                return Arr(None, v.dtype)
            return Arr((lead,) + v.shape, v.dtype)
        if isinstance(v, TupV):
            return TupV([self._prepend_lead(it, lead) for it in v.items])
        if isinstance(v, DictV):
            return DictV({
                k: self._prepend_lead(it, lead) for k, it in v.entries.items()
            })
        if isinstance(v, RecV):
            return RecV(v.cls, {
                k: self._prepend_lead(it, lead) for k, it in v.fields.items()
            })
        return UNKNOWN

    def _vmap(self, node, env, base):
        if node.keywords:
            # in_axes/out_axes beyond the default are not modeled
            fn = self.eval(node.args[0], env, base) if node.args else UNKNOWN
            return _MappedV(fn, self, modeled=False)
        fn = self.eval(node.args[0], env, base) if node.args else UNKNOWN
        return _MappedV(fn, self, modeled=True)

    def _call_mapped(self, node, mapped, env, base):
        args = [self.eval(a, env, base) for a in node.args
                if not isinstance(a, ast.Starred)]
        if not mapped.modeled or any(isinstance(a, Unknown) for a in args):
            return UNKNOWN
        stripped, leads = [], []
        for a in args:
            s, l = self._strip_lead(a, node)
            stripped.append(s)
            leads.append(l)
        lead = None
        for l in leads:
            if l is None:
                continue
            if lead is None:
                lead = l
            elif not dim_eq(lead, l):
                if dim_is_named(lead) and dim_is_named(l):
                    self._shape_flag(
                        node,
                        f"vmap maps mismatched leading axes: "
                        f"{dim_str(lead)} vs {dim_str(l)}",
                    )
                lead = None
                break
        out = self._call_value(node, mapped.fn, stripped, {}, base)
        return self._prepend_lead(out, lead)

    def _scan(self, node, args, env, base):
        # jax.lax.scan(f, init, xs[, length=])
        if len(args) < 2:
            return UNKNOWN
        fn, init = args[0], args[1]
        xs = args[2] if len(args) > 2 else NONE
        length = None
        lkw = self._keyword(node, "length")
        if lkw is not None:
            length = self.dim_of_value(self.eval(lkw, env, base))
        x_stripped, lead = (UNKNOWN, length)
        if isinstance(xs, (Arr, TupV, RecV)):
            x_stripped, xlead = self._strip_lead(xs, node)
            lead = xlead if xlead is not None else length
        out = self._call_value(node, fn, [init, x_stripped], {}, base)
        carry_out, ys = UNKNOWN, UNKNOWN
        if isinstance(out, TupV) and len(out.items) == 2:
            carry_out, ys = out.items
        self._check_carry(node, "scan carry", init, carry_out)
        self._check_accum(node, init)
        return TupV([
            join(init, carry_out) if not isinstance(carry_out, Unknown)
            else UNKNOWN,
            self._prepend_lead(ys, lead),
        ])

    def _while_loop(self, node, args, env, base):
        if len(args) < 3:
            return UNKNOWN
        cond, body, init = args[0], args[1], args[2]
        self._call_value(node, cond, [init], {}, base)
        out = self._call_value(node, body, [init], {}, base)
        self._check_carry(node, "while_loop carry", init, out)
        self._check_accum(node, init)
        if isinstance(out, Unknown):
            return init
        return join(init, out)

    def _fori_loop(self, node, args, env, base):
        if len(args) < 4:
            return UNKNOWN
        body, init = args[2], args[3]
        out = self._call_value(node, body, [Arr((), "i64"), init], {}, base)
        self._check_carry(node, "fori_loop carry", init, out)
        self._check_accum(node, init)
        if isinstance(out, Unknown):
            return init
        return join(init, out)

    def _cond(self, node, args, env, base):
        if len(args) < 3:
            return UNKNOWN
        tf, ff = args[1], args[2]
        operands = args[3:] if len(args) > 3 else []
        tv = self._call_value(node, tf, operands, {}, base)
        fv = self._call_value(node, ff, operands, {}, base)
        return join(tv, fv)

    def _check_carry(self, node, what, init, out):
        """Structural comparison of loop-carry init vs body output —
        NAMED drifts are exactly what jax cannot see (the concrete sizes
        coincide)."""
        if isinstance(init, Unknown) or isinstance(out, Unknown):
            return
        self._walk_carry(node, what, init, out, path="")

    def _walk_carry(self, node, what, a, b, path):
        if isinstance(a, Unknown) or isinstance(b, Unknown):
            return
        loc = f" at {path}" if path else ""
        if isinstance(a, Arr) and isinstance(b, Arr):
            if a.shape is None or b.shape is None:
                return
            if len(a.shape) != len(b.shape):
                self._shape_flag(
                    node,
                    f"{what} drift{loc}: rank {len(a.shape)} "
                    f"{shape_str(a.shape)} vs rank {len(b.shape)} "
                    f"{shape_str(b.shape)}",
                )
                return
            for i, (da, db_) in enumerate(zip(a.shape, b.shape)):
                if da is None or db_ is None:
                    continue
                if not dim_eq(da, db_) and dim_is_named(da) and \
                        dim_is_named(db_):
                    self._shape_flag(
                        node,
                        f"{what} drift{loc}: axis {i} enters as "
                        f"{dim_str(da)} and leaves as {dim_str(db_)} "
                        f"({shape_str(a.shape)} vs {shape_str(b.shape)})",
                    )
            if a.dtype is not None and b.dtype is not None and \
                    a.dtype != b.dtype:
                self._dtype_flag(
                    node,
                    f"{what} dtype drift{loc}: enters {a.dtype}, leaves "
                    f"{b.dtype}",
                )
            return
        if isinstance(a, TupV) and isinstance(b, TupV):
            if len(a.items) != len(b.items):
                self._shape_flag(
                    node,
                    f"{what} drift{loc}: {len(a.items)} elements in, "
                    f"{len(b.items)} out",
                )
                return
            for i, (x, y) in enumerate(zip(a.items, b.items)):
                self._walk_carry(node, what, x, y, f"{path}[{i}]")
            return
        if isinstance(a, DictV) and isinstance(b, DictV):
            for k in set(a.entries) & set(b.entries):
                self._walk_carry(node, what, a.entries[k], b.entries[k],
                                 f"{path}[{k!r}]")
            return
        if isinstance(a, RecV) and isinstance(b, RecV) and a.cls == b.cls:
            for k in set(a.fields) & set(b.fields):
                self._walk_carry(node, what, a.fields[k], b.fields[k],
                                 f"{path}.{k}")

    def _check_accum(self, node, init):
        """Root-declared accumulation-dtype contract over loop carries."""
        contract = self._accum[-1] if self._accum else None
        if not contract:
            return
        leaves: List[Tuple[str, Arr]] = []

        def walk(v, path):
            if isinstance(v, Arr):
                leaves.append((path, v))
            elif isinstance(v, TupV):
                for i, it in enumerate(v.items):
                    walk(it, f"{path}[{i}]")
            elif isinstance(v, DictV):
                for k, it in v.entries.items():
                    walk(it, f"{path}[{k!r}]")
            elif isinstance(v, RecV):
                for k, it in v.fields.items():
                    walk(it, f"{path}.{k}")

        walk(init, "carry")
        for path, arr in leaves:
            if arr.dtype is not None and arr.dtype not in contract:
                self._dtype_flag(
                    node,
                    f"loop carry {path} has dtype {arr.dtype}, outside the "
                    f"root's declared accum({', '.join(sorted(contract))}) "
                    "contract",
                )

    # -- user-function calls (context-sensitive summaries) -----------------

    def _aval_key(self, v):
        if isinstance(v, Arr):
            return ("A", v.shape, v.dtype)
        if isinstance(v, TupV):
            return ("T",) + tuple(self._aval_key(i) for i in v.items)
        if isinstance(v, DictV):
            return ("D",) + tuple(
                (k, self._aval_key(x)) for k, x in sorted(v.entries.items())
            )
        if isinstance(v, RecV):
            return ("R", v.cls) + tuple(
                (k, self._aval_key(x)) for k, x in sorted(v.fields.items())
            )
        if isinstance(v, DimV):
            return ("d", v.lin)
        if isinstance(v, StaticV):
            try:
                hash(v.value)
                return ("s", v.value if v.value is not _UNSET else "?")
            except TypeError:
                return ("s", "?")
        if isinstance(v, FuncV):
            return ("f", id(v.node))
        if isinstance(v, CtorV):
            return ("c", v.cls)
        if isinstance(v, DtypeV):
            return ("dt", v.dt)
        if isinstance(v, ModV):
            return ("m", v.base)
        return ("u",)

    def _call_value(self, node, fn, args, kwargs, base):
        if isinstance(fn, _MappedV):
            return UNKNOWN
        if isinstance(fn, FuncV):
            return self._call_funcv_direct(node, fn, args, kwargs)
        if isinstance(fn, CtorV):
            return self._construct_direct(fn, args, kwargs)
        return UNKNOWN

    def _call_funcv(self, node, fv: FuncV, env, base):
        args, kwargs = self._args_kwargs(node, env, base)
        return self._call_funcv_direct(node, fv, args, kwargs)

    def _call_funcv_direct(self, node, fv: FuncV, args, kwargs):
        if len(self._stack) >= self.MAX_DEPTH:
            return UNKNOWN
        fnode = fv.node
        if isinstance(fnode, ast.Lambda):
            params = [a.arg for a in fnode.args.args]
            inner = dict(fv.env) if fv.env is not None else {}
            for p, a in zip(params, args):
                inner[p] = a
            for i in range(len(args), len(params)):
                inner[params[i]] = UNKNOWN
            return self.eval(fnode.body, inner, fv.base)
        # a named def: summary-memoized per (func, args, roster coverage,
        # active accum contract) — both context bits change which findings
        # a body emits, so a summary computed under one must not be reused
        # under another
        covered = self._covered()
        accum = self._accum[-1] if self._accum else None
        key = None
        if fv.key is not None:
            key = (fv.key, covered,
                   frozenset(accum) if accum else None,
                   tuple(self._aval_key(a) for a in args),
                   tuple(sorted(
                       (k, self._aval_key(v)) for k, v in kwargs.items()
                   )))
            if key in self.summaries:
                hit = self.summaries[key]
                if hit is _IN_PROGRESS:
                    return UNKNOWN
                # shell copy: callers mutate returned dicts/records in
                # place (the wave step extends pod_step's state) — the
                # cached summary must stay pristine
                return _copy_shell(hit)
            self.summaries[key] = _IN_PROGRESS
        env = dict(fv.env) if fv.env is not None else {}
        self._bind_params(fnode, args, kwargs, env, fv.base)
        if fv.key is not None:
            self._stack.append(fv.key)
        try:
            rets: List[object] = []
            self.exec_block(fnode.body, env, fv.base, rets)
            out = UNKNOWN
            if rets:
                out = rets[0]
                for r in rets[1:]:
                    out = join(out, r)
            else:
                out = NONE
        finally:
            if fv.key is not None:
                self._stack.pop()
        if key is not None:
            self.summaries[key] = out
            return _copy_shell(out)
        return out

    def _bind_params(self, fnode, args, kwargs, env, base):
        a = fnode.args
        params = [p.arg for p in a.args]
        defaults = list(a.defaults)
        # positional
        for i, p in enumerate(params):
            if i < len(args):
                env[p] = args[i]
            elif p in kwargs:
                env[p] = kwargs.pop(p)
            else:
                di = i - (len(params) - len(defaults))
                if 0 <= di < len(defaults):
                    env[p] = self.eval(defaults[di], env, base)
                else:
                    env[p] = UNKNOWN
        for p, d in zip(a.kwonlyargs, a.kw_defaults):
            name = p.arg
            if name in kwargs:
                env[name] = kwargs.pop(name)
            elif d is not None:
                env[name] = self.eval(d, env, base)
            else:
                env[name] = UNKNOWN
        for k, v in kwargs.items():
            env.setdefault(k, v)

    def _construct(self, node, ctor: CtorV, env, base):
        args, kwargs = self._args_kwargs(node, env, base)
        return self._construct_direct(ctor, args, kwargs)

    def _construct_direct(self, ctor: CtorV, args, kwargs):
        fields = {}
        for name, v in zip(ctor.field_order, args):
            fields[name] = v
        for k, v in kwargs.items():
            if k in ctor.field_order or not ctor.field_order:
                fields[k] = v
        return RecV(ctor.cls, fields)

    # -- statements --------------------------------------------------------

    def bind_target(self, target, value, env):
        if isinstance(target, ast.Name):
            env[target.id] = value
        elif isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            starred_at = next(
                (i for i, e in enumerate(elts) if isinstance(e, ast.Starred)),
                None,
            )
            if isinstance(value, TupV) and starred_at is None and \
                    len(value.items) == len(elts):
                for el, v in zip(elts, value.items):
                    self.bind_target(el, v, env)
            elif isinstance(value, TupV) and starred_at is not None and \
                    len(value.items) >= len(elts) - 1:
                head = elts[:starred_at]
                tail = elts[starred_at + 1:]
                for el, v in zip(head, value.items[: len(head)]):
                    self.bind_target(el, v, env)
                mid = value.items[len(head): len(value.items) - len(tail)]
                self.bind_target(elts[starred_at].value, TupV(mid), env)
                for el, v in zip(tail, value.items[len(value.items)
                                                   - len(tail):]):
                    self.bind_target(el, v, env)
            else:
                for el in elts:
                    self.bind_target(
                        el.value if isinstance(el, ast.Starred) else el,
                        UNKNOWN, env,
                    )
        # attribute / subscript writes: model dict-entry assignment
        elif isinstance(target, ast.Subscript):
            pass  # handled by caller (needs env lookup of the container)

    def _assign_subscript(self, target: ast.Subscript, value, env, base):
        cont = self.eval(target.value, env, base)
        if isinstance(cont, DictV):
            kv = self.eval(target.slice, env, base)
            if isinstance(kv, StaticV) and isinstance(kv.value, str):
                cont.entries[kv.value] = value
        # list index writes (pads[axis] = ...) are not modeled

    def exec_block(self, stmts, env, base, rets) -> bool:
        """Execute statements; returns True if the block TERMINATES
        (return / raise on every path) — terminated branches are skipped
        by if/else joins."""
        for st in stmts:
            if isinstance(st, ast.FunctionDef):
                qual = self._qual_for(st, base)
                env[st.name] = FuncV(qual, st, env, base)
                continue
            if isinstance(st, ast.Return):
                v = self.eval(st.value, env, base) if st.value is not None \
                    else NONE
                rets.append(v)
                return True
            if isinstance(st, ast.Raise):
                return True
            if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self._exec_assign(st, env, base)
                continue
            if isinstance(st, ast.If):
                t = self.truthiness(st.test, env, base)
                if t is True:
                    if self.exec_block(st.body, env, base, rets):
                        return True
                    continue
                if t is False:
                    if st.orelse and self.exec_block(st.orelse, env, base,
                                                     rets):
                        return True
                    continue
                env_a = dict(env)
                env_b = dict(env)
                term_a = self.exec_block(st.body, env_a, base, rets)
                term_b = self.exec_block(st.orelse, env_b, base, rets) \
                    if st.orelse else False
                if term_a and term_b:
                    return True
                if term_a:
                    env.clear()
                    env.update(env_b)
                elif term_b:
                    env.clear()
                    env.update(env_a)
                else:
                    merged = {}
                    for k in set(env_a) | set(env_b):
                        if k in env_a and k in env_b:
                            merged[k] = join(env_a[k], env_b[k])
                        else:
                            merged[k] = env_a.get(k, env_b.get(k))
                    env.clear()
                    env.update(merged)
                continue
            if isinstance(st, ast.For):
                self._exec_for(st, env, base, rets)
                continue
            if isinstance(st, ast.While):
                self.eval(st.test, env, base)
                snap = dict(env)
                self.exec_block(st.body, env, base, rets)
                for k in set(env) | set(snap):
                    if k in env and k in snap:
                        env[k] = join(env[k], snap[k])
                self.exec_block(st.body, env, base, rets)
                continue
            if isinstance(st, ast.Expr):
                self.eval(st.value, env, base)
                continue
            if isinstance(st, (ast.Assert,)):
                self.eval(st.test, env, base)
                continue
            if isinstance(st, (ast.Global, ast.Nonlocal, ast.Pass,
                               ast.Import, ast.ImportFrom, ast.Delete,
                               ast.Break, ast.Continue)):
                continue
            if isinstance(st, ast.With):
                for item in st.items:
                    v = self.eval(item.context_expr, env, base)
                    if item.optional_vars is not None:
                        self.bind_target(item.optional_vars, UNKNOWN, env)
                self.exec_block(st.body, env, base, rets)
                continue
            if isinstance(st, ast.Try):
                self.exec_block(st.body, env, base, rets)
                for h in st.handlers:
                    self.exec_block(h.body, env, base, rets)
                self.exec_block(st.orelse, env, base, rets)
                self.exec_block(st.finalbody, env, base, rets)
                continue
            # anything else: walk sub-blocks conservatively
            for attr in ("body", "orelse", "finalbody"):
                sub = getattr(st, attr, None)
                if sub:
                    self.exec_block(sub, env, base, rets)
        return False

    def _qual_for(self, fnode, base):
        mi = self.mods.get(base)
        if mi is not None:
            for qual, rec in mi.funcs.items():
                if rec.node is fnode:
                    return rec.key
        return None

    def _exec_assign(self, st, env, base):
        if isinstance(st, ast.AugAssign):
            synthetic = ast.BinOp(
                left=st.target, op=st.op, right=st.value,
            )
            ast.copy_location(synthetic, st)
            ast.fix_missing_locations(synthetic)
            v = self.eval(synthetic, env, base)
            if isinstance(st.target, ast.Name):
                env[st.target.id] = v
            elif isinstance(st.target, ast.Subscript):
                self._assign_subscript(st.target, v, env, base)
            return
        value_node = st.value
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        if value_node is None:
            return
        v = self.eval(value_node, env, base)
        for t in targets:
            if isinstance(t, ast.Subscript):
                self._assign_subscript(t, v, env, base)
            else:
                self.bind_target(t, v, env)

    def _exec_for(self, st, env, base, rets):
        it = self.eval(st.iter, env, base)
        # literal-tuple iteration unrolls precisely (the reason_counts /
        # DIAG_KERNELS idiom builds fixed-length lists this way)
        if isinstance(it, TupV) and len(it.items) <= 32:
            for item in it.items:
                self.bind_target(st.target, item, env)
                self.exec_block(st.body, env, base, rets)
            self.exec_block(st.orelse, env, base, rets)
            return
        # symbolic ranges: two joined passes reach the accumulator fixpoint
        if isinstance(it, Arr) and it.shape is not None and it.shape:
            elem = Arr(it.shape[1:], it.dtype)
        else:
            elem = UNKNOWN
        self.bind_target(st.target, elem, env)
        snap = dict(env)
        self.exec_block(st.body, env, base, rets)
        for k in set(env) & set(snap):
            env[k] = join(env[k], snap[k])
        self.exec_block(st.body, env, base, rets)
        self.exec_block(st.orelse, env, base, rets)

    # -- roots -------------------------------------------------------------

    def _analyze_root(self, rec: _FuncRec, ann: RootAnnotation) -> None:
        fnode = rec.node
        params = [p.arg for p in fnode.args.args] + \
            [p.arg for p in fnode.args.kwonlyargs]
        for name in ann.axes:
            if name not in params:
                self.emit(
                    RULE_SHAPE, rec.mod, ann.line or fnode.lineno,
                    f"{rec.qual}: axes() names '{name}' but the root has no "
                    f"such parameter",
                )
        jd = _jit_decoration(fnode)
        static_names = jd[1] if jd else set()
        env: Dict[str, object] = {}
        all_args = fnode.args.args + fnode.args.kwonlyargs
        defaults = {}
        pos = fnode.args.args
        for p, d in zip(pos[len(pos) - len(fnode.args.defaults):],
                        fnode.args.defaults):
            defaults[p.arg] = d
        for p, d in zip(fnode.args.kwonlyargs, fnode.args.kw_defaults):
            if d is not None:
                defaults[p.arg] = d
        for p in all_args:
            name = p.arg
            if name in ann.axes:
                env[name] = spec_to_aval(
                    ann.axes[name], self.class_tables, ns=name
                )
            elif name in static_names:
                is_int = (
                    isinstance(p.annotation, ast.Name)
                    and p.annotation.id == "int"
                )
                sv = ann.static_values.get(name, _UNSET)
                if isinstance(sv, int) and not isinstance(sv, bool):
                    env[name] = DimV(dim_of_sym(name))
                elif sv is not _UNSET:
                    env[name] = StaticV(sv)
                elif is_int:
                    env[name] = DimV(dim_of_sym(name))
                elif name in defaults:
                    # a LITERAL default (True/False/tuples) prunes to the
                    # branch the runtime cross-check will trace
                    env[name] = self.eval(defaults[name], {}, rec.base)
                else:
                    env[name] = StaticV()
            elif name in defaults:
                env[name] = self.eval(defaults[name], {}, rec.base)
            else:
                env[name] = UNKNOWN
        self._stack.append(rec.key)
        self._accum.append(ann.accum)
        try:
            rets: List[object] = []
            self.exec_block(fnode.body, env, rec.base, rets)
            out: object = UNKNOWN
            if rets:
                out = rets[0]
                for r in rets[1:]:
                    out = join(out, r)
            self.root_returns[f"{rec.base}.{rec.qual}"] = out
        finally:
            self._accum.pop()
            self._stack.pop()


class _MappedV:
    """jax.vmap(fn) — callable wrapper carrying the mapped function."""

    __slots__ = ("fn", "engine", "modeled")

    def __init__(self, fn, engine, modeled):
        self.fn = fn
        self.engine = engine
        self.modeled = modeled


NOT_BUILTIN = object()
_IN_PROGRESS = object()


def _copy_shell(v):
    """Copy mutable containers (Arrs are immutable and shared)."""
    if isinstance(v, TupV):
        return TupV([_copy_shell(i) for i in v.items])
    if isinstance(v, DictV):
        return DictV({k: _copy_shell(x) for k, x in v.entries.items()})
    if isinstance(v, RecV):
        return RecV(v.cls, {k: _copy_shell(x) for k, x in v.fields.items()})
    return v

_PYOPS = {
    ast.Add: lambda a, b: a + b,
    ast.Sub: lambda a, b: a - b,
    ast.Mult: lambda a, b: a * b,
    ast.Div: lambda a, b: a / b,
    ast.FloorDiv: lambda a, b: a // b,
    ast.Mod: lambda a, b: a % b,
    ast.Pow: lambda a, b: a ** b,
    ast.LShift: lambda a, b: a << b,
    ast.RShift: lambda a, b: a >> b,
    ast.BitAnd: lambda a, b: a & b,
    ast.BitOr: lambda a, b: a | b,
    ast.BitXor: lambda a, b: a ^ b,
}
_PYCMP = {
    ast.Eq: lambda a, b: a == b,
    ast.NotEq: lambda a, b: a != b,
    ast.Lt: lambda a, b: a < b,
    ast.LtE: lambda a, b: a <= b,
    ast.Gt: lambda a, b: a > b,
    ast.GtE: lambda a, b: a >= b,
    ast.In: lambda a, b: a in b,
    ast.NotIn: lambda a, b: a not in b,
}


# ---------------------------------------------------------------------------
# checkers
# ---------------------------------------------------------------------------


def engine_for(mods: Sequence[SourceModule], cache: Optional[dict] = None):
    """Run (or reuse) the interpreter over a target set.  ``cache`` lets
    run_analysis share ONE interpretation across the shape/dtype/shard
    rule families (the per-rule wall time then lands on whichever family
    ran first — by construction the shape checker)."""
    key = tuple(m.path for m in mods)
    if cache is not None and key in cache:
        return cache[key]
    engine = ShapeEngine().run(mods)
    if cache is not None:
        cache[key] = engine
    return engine


class _EngineChecker(Checker):
    def run(self, mods: Sequence[SourceModule],
            engine_cache: Optional[dict] = None) -> None:
        engine = engine_for(mods, engine_cache)
        for rule, mod, line, msg in engine.raw_findings:
            if rule == self.rule:
                self.emit(mod, line, msg)


class ShapeChecker(_EngineChecker):
    rule = RULE_SHAPE


class DtypeChecker(_EngineChecker):
    rule = RULE_DTYPE


class ShardChecker(_EngineChecker):
    rule = RULE_SHARD


def collective_roster(mods: Sequence[SourceModule]) -> Dict[str, Dict]:
    """The parsed ``_KTPU_N_COLLECTIVES`` inventory across ``mods``:
    ``{module path: {qual: {reason, resolved, mechanism, line}}}`` — the
    machine-readable multichip burn-down (MULTICHIP.md inventory table,
    tests/test_static_analysis roster gate)."""
    engine = ShapeEngine()
    for m in mods:
        engine._index(m)
    out: Dict[str, Dict] = {}
    for mi in engine.mods.values():
        if not mi.roster:
            continue
        entries = {}
        for qual, reason in sorted(mi.roster.items()):
            m2 = RESOLVED_ROSTER_RE.match(reason)
            entries[qual] = {
                "reason": reason,
                "resolved": bool(m2),
                "mechanism": m2.group(1) if m2 else None,
                "line": mi.roster_lines.get(qual, 1),
            }
        out[mi.mod.path] = entries
    return out


# ---------------------------------------------------------------------------
# root summaries for the runtime cross-check (shapecheck.py)
# ---------------------------------------------------------------------------


# content-keyed engine cache for root_summaries: the runtime cross-check
# calls it once per size draw (the property test: 8+ draws per session),
# and the interpretation depends only on the SOURCES, not the sizes
_SUMMARY_CACHE: Dict[tuple, "ShapeEngine"] = {}


def root_summaries(mods: Sequence[SourceModule]):
    """[(root key 'module.qual', _FuncRec, RootAnnotation, inferred return
    aval)] for every annotated jit root — the static half the runtime
    eval_shape cross-check compares against."""
    key = tuple((m.path, hash(m.source)) for m in mods)
    engine = _SUMMARY_CACHE.get(key)
    if engine is None:
        engine = ShapeEngine().run(mods)
        if len(_SUMMARY_CACHE) > 8:
            _SUMMARY_CACHE.clear()
        _SUMMARY_CACHE[key] = engine
    out = []
    for rec, ann in engine.roots:
        out.append((
            f"{rec.base}.{rec.qual}",
            rec,
            ann,
            engine.root_returns.get(f"{rec.base}.{rec.qual}", UNKNOWN),
            engine,
        ))
    return out
