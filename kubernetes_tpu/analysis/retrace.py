"""Retrace-hygiene checker (rule: ``retrace``).

A jit root's compilation cache is keyed by the SIGNATURE of each call:
abstract shapes/dtypes of the traced arguments (where Python scalars
enter as weak-typed avals) plus the concrete values of the static ones.
Two habits quietly turn that cache into a recompile storm:

  * **weak-typed Python scalars as traced arguments** — a call site that
    passes a bare ``0``/``0.5``/``True`` to a traced parameter commits a
    weak-typed aval; the same root called elsewhere with a committed
    ``jnp`` array of the "same" value has a different signature, and the
    pair ping-pongs the cache.  Wrap the literal (``jnp.asarray(x,
    dtype)``) or make the parameter static.

  * **shape-derived static arguments** — a ``static_argnames`` parameter
    fed inline from ``len(...)``/``.shape`` recompiles once per distinct
    runtime size.  The sanctioned idiom is to BUCKET the size first
    (``bucket_cap(...)`` — a handful of shapes instead of one per batch).

Call sites INSIDE jit-decorated functions are exempt (they execute under
the outer trace; their cache behavior is the outer root's signature).

The static rules catch the two leak shapes visible in the AST; the
dynamic complement lives in ``sanitizer.py``: under ``KTPU_SANITIZE=1``
a jax compile-event hook sweeps every registered jit root's compilation
cache and counts POST-WARMUP growth as unexpected recompiles
(``scheduler_tpu_jit_recompiles_total{fn=}``), which is what catches
shape-dependent Python branching that static analysis cannot see.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from kubernetes_tpu.analysis.core import (
    RULE_RETRACE,
    Checker,
    ImportRefs,
    SourceModule,
    dotted_name,
    resolve_root,
)
from kubernetes_tpu.analysis.d2h import _module_base
from kubernetes_tpu.analysis.jit import _jit_decoration

# size-bucketing helpers: a static argument routed through one of these
# hits a handful of shapes, not one per call
BUCKET_FNS = {"bucket_cap"}


class _Root:
    def __init__(self, base: str, node: ast.FunctionDef, static: Set[str]):
        self.base = base
        self.name = node.name
        self.params = [a.arg for a in node.args.args]
        self.static = static


class RetraceChecker(Checker):
    rule = RULE_RETRACE

    def __init__(self) -> None:
        super().__init__()
        # module base → fn name → _Root (alias-table lookups), plus the
        # path-scoped view for each module's OWN bare names (two modules
        # sharing a basename must not resolve each other's)
        self.roots: Dict[str, Dict[str, _Root]] = {}
        self.roots_by_path: Dict[str, Dict[str, _Root]] = {}

    # ----- entry point ------------------------------------------------------

    def run(self, mods: Sequence[SourceModule]) -> None:
        for mod in mods:
            base = _module_base(mod.path)
            merged = self.roots.setdefault(base, {})
            per = self.roots_by_path.setdefault(mod.path, {})

            def index(container: ast.AST) -> None:
                for node in ast.iter_child_nodes(container):
                    if isinstance(node, ast.FunctionDef):
                        jd = _jit_decoration(node)
                        if jd is not None:
                            r = _Root(base, node, jd[1])
                            per[node.name] = r
                            merged[node.name] = r
                        index(node)
                    elif isinstance(node, (ast.ClassDef, ast.If, ast.Try)):
                        index(node)

            index(mod.tree)

        for mod in mods:
            refs = ImportRefs(mod.tree)
            self._check_module(
                mod, refs, self.roots_by_path.get(mod.path, {})
            )

    def _resolve_root(
        self, refs: ImportRefs, self_roots: Dict[str, _Root],
        func: ast.expr
    ) -> Optional[_Root]:
        return resolve_root(refs, self_roots, self.roots, func)

    # ----- call-site scan ---------------------------------------------------

    def _check_module(
        self, mod: SourceModule, refs: ImportRefs,
        self_roots: Dict[str, _Root],
    ) -> None:
        def walk_fns(container: ast.AST) -> None:
            for node in ast.iter_child_nodes(container):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if isinstance(node, ast.FunctionDef) and _jit_decoration(
                        node
                    ):
                        continue  # call sites under the outer trace
                    self._check_function(mod, refs, self_roots, node)
                    walk_fns(node)
                elif isinstance(node, ast.ClassDef):
                    walk_fns(node)

        walk_fns(mod.tree)

    def _check_function(
        self,
        mod: SourceModule,
        refs: ImportRefs,
        self_roots: Dict[str, _Root],
        fn: ast.FunctionDef,
    ) -> None:
        stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested defs visited by the module walk (pruned)
            stack.extend(ast.iter_child_nodes(node))
            if not isinstance(node, ast.Call):
                continue
            root = self._resolve_root(refs, self_roots, node.func)
            if root is None:
                continue
            bound: List[Tuple[str, ast.expr]] = []
            for i, a in enumerate(node.args):
                if i < len(root.params):
                    bound.append((root.params[i], a))
            for kw in node.keywords:
                if kw.arg is not None:
                    bound.append((kw.arg, kw.value))
            for pname, expr in bound:
                if pname in root.static:
                    bad = self._unbucketed_shape_use(expr)
                    if bad is not None:
                        self.emit(
                            mod,
                            expr.lineno,
                            f"static argument {pname!r} of {root.name}() is "
                            f"derived inline from {bad} — one recompile per "
                            "distinct size; bucket it (bucket_cap) first",
                        )
                else:
                    if isinstance(expr, ast.Constant) and isinstance(
                        expr.value, (int, float, bool)
                    ):
                        self.emit(
                            mod,
                            expr.lineno,
                            f"weak-typed Python scalar {expr.value!r} passed "
                            f"to traced parameter {pname!r} of {root.name}() "
                            "— commit the dtype (jnp.asarray) or make the "
                            "parameter static",
                        )

    def _unbucketed_shape_use(self, expr: ast.expr) -> Optional[str]:
        """'len(...)' / "'.shape'" when the expression derives a size from
        runtime data without routing it through a bucketing helper."""

        def scan(node: ast.expr) -> Optional[str]:
            if isinstance(node, ast.Call):
                dn = dotted_name(node.func)
                if dn is not None and dn.split(".")[-1] in BUCKET_FNS:
                    return None  # bucketed subtree — sanctioned
                if (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "len"
                ):
                    return "len(...)"
                for child in ast.iter_child_nodes(node):
                    hit = scan(child) if isinstance(child, ast.expr) else None
                    if hit:
                        return hit
                return None
            if isinstance(node, ast.Attribute) and node.attr == "shape":
                return "'.shape'"
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    hit = scan(child)
                    if hit:
                        return hit
            return None

        return scan(expr)
