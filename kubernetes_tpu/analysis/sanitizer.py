"""Runtime sanitizer mode (``KTPU_SANITIZE=1``).

The static checkers prove lock discipline for the code as written; the
sanitizer catches what statics can't — a caller reached through a path
the call-graph walk under-approximated, or cache↔mirror drift from a
delta-protocol bug.  It is a debug mode: every probe is a no-op unless
``KTPU_SANITIZE`` is set to a non-empty, non-"0" value, so production
drains pay one cached env lookup per process, not per call.

Violations raise ``AssertionError`` at the corrupting site AND bump both
the module counter (``violation_count()``, monotonic per process) and the
``scheduler_tpu_sanitizer_violations_total`` Prometheus counter of every
registered ``SchedulerMetrics`` — the raise can be swallowed by broad
``except`` layers above, the counter cannot.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Optional

_enabled_memo: Optional[bool] = None
_violations = 0
_violation_lock = threading.Lock()
# registered metrics Counters — weakly held, so a dead Scheduler's metrics
# registry is collectable even in long sanitize-mode processes (bench runs
# construct one Scheduler per config)
_counters: "weakref.WeakSet" = weakref.WeakSet()


def enabled() -> bool:
    global _enabled_memo
    if _enabled_memo is None:
        _enabled_memo = os.environ.get("KTPU_SANITIZE", "") not in ("", "0")
    return _enabled_memo


def reset_enabled_memo() -> None:
    """Re-read KTPU_SANITIZE (tests toggle it per-case)."""
    global _enabled_memo
    _enabled_memo = None


def register_counter(counter) -> None:
    """Wire a metrics Counter (scheduler_tpu_sanitizer_violations_total);
    idempotent per counter instance, weakly held."""
    if counter is not None:
        _counters.add(counter)


def violation_count() -> int:
    return _violations


def _record(kind: str) -> None:
    global _violations
    with _violation_lock:
        _violations += 1
    for c in list(_counters):
        try:
            c.inc(kind=kind)
        except Exception:  # noqa: BLE001 — accounting must never mask the raise
            pass


def violation(kind: str, message: str) -> None:
    _record(kind)
    raise AssertionError(f"ktpu-sanitize[{kind}]: {message}")


def assert_owned(lock, what: str = "guarded state") -> None:
    """Assert the calling thread owns ``lock`` (RLock ownership probe).

    ``lock`` may be None (e.g. a Cache used standalone in unit tests with
    no scheduler attached) — then there is no discipline to enforce.
    """
    if lock is None or not enabled():
        return
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is None:
        return  # non-RLock stand-in (tests may inject mocks)
    if not is_owned():
        violation(
            "lock",
            f"{what} mutated without holding the guarding lock "
            f"(thread {threading.current_thread().name})",
        )


def check_mirror_consistency(cache, mirror) -> None:
    """Snapshot↔mirror drift probe, run after each drain.

    Verifies the per-node usage rows the device kernels read (requested /
    nonzero_req / num_pods) against a fresh recomputation from the
    authoritative cache.  Only meaningful when the mirror has packed at
    least once and its watermark covers the cache (callers run it right
    after a drain's final repack); nodes added after the last pack are
    skipped rather than misreported.
    """
    if not enabled():
        return
    nt = mirror.nodes
    if nt is None:
        return
    import numpy as np

    from kubernetes_tpu.snapshot.schema import MEM_UNIT, ResourceLanes

    lanes = ResourceLanes(mirror.vocab)
    R = nt.allocatable.shape[1]
    for cn in cache.real_nodes():
        idx = nt.name_to_idx.get(cn.node.name)
        if idx is None or cn.generation > mirror.generation:
            continue  # not packed yet / legitimately newer than the mirror
        want_req = np.asarray(lanes.request_row(cn.requested, R))
        got_req = np.asarray(nt.requested[idx])
        if not np.array_equal(want_req, got_req):
            violation(
                "mirror",
                f"node {cn.node.name!r} requested row drifted: "
                f"cache={want_req.tolist()} mirror={got_req.tolist()}",
            )
        want_nz = (
            cn.non_zero_requested.milli_cpu,
            -(-cn.non_zero_requested.memory // MEM_UNIT),
        )
        got_nz = (int(nt.nonzero_req[idx, 0]), int(nt.nonzero_req[idx, 1]))
        if want_nz != got_nz:
            violation(
                "mirror",
                f"node {cn.node.name!r} nonzero_req drifted: "
                f"cache={want_nz} mirror={got_nz}",
            )
        if int(nt.num_pods[idx]) != len(cn.pods):
            violation(
                "mirror",
                f"node {cn.node.name!r} num_pods drifted: "
                f"cache={len(cn.pods)} mirror={int(nt.num_pods[idx])}",
            )
