"""Runtime sanitizer mode (``KTPU_SANITIZE=1``).

The static checkers prove lock discipline for the code as written; the
sanitizer catches what statics can't — a caller reached through a path
the call-graph walk under-approximated, or cache↔mirror drift from a
delta-protocol bug.  It is a debug mode: every probe is a no-op unless
``KTPU_SANITIZE`` is set to a non-empty, non-"0" value, so production
drains pay one cached env lookup per process, not per call.

Violations raise ``AssertionError`` at the corrupting site AND bump both
the module counter (``violation_count()``, monotonic per process) and the
``scheduler_tpu_sanitizer_violations_total`` Prometheus counter of every
registered ``SchedulerMetrics`` — the raise can be swallowed by broad
``except`` layers above, the counter cannot.
"""

from __future__ import annotations

import os
import threading
import weakref
from typing import Optional

_enabled_memo: Optional[bool] = None
_violations = 0
_violation_lock = threading.Lock()
# registered metrics Counters — weakly held, so a dead Scheduler's metrics
# registry is collectable even in long sanitize-mode processes (bench runs
# construct one Scheduler per config)
_counters: "weakref.WeakSet" = weakref.WeakSet()


def enabled() -> bool:
    global _enabled_memo
    if _enabled_memo is None:
        _enabled_memo = os.environ.get("KTPU_SANITIZE", "") not in ("", "0")
    return _enabled_memo


def reset_enabled_memo() -> None:
    """Re-read KTPU_SANITIZE (tests toggle it per-case)."""
    global _enabled_memo
    _enabled_memo = None


def register_counter(counter) -> None:
    """Wire a metrics Counter (scheduler_tpu_sanitizer_violations_total);
    idempotent per counter instance, weakly held."""
    if counter is not None:
        _counters.add(counter)


def violation_count() -> int:
    return _violations


def _record(kind: str) -> None:
    global _violations
    with _violation_lock:
        _violations += 1
    for c in list(_counters):
        try:
            c.inc(kind=kind)
        except Exception:  # noqa: BLE001 — accounting must never mask the raise
            pass


def violation(kind: str, message: str) -> None:
    _record(kind)
    raise AssertionError(f"ktpu-sanitize[{kind}]: {message}")


def assert_owned(lock, what: str = "guarded state") -> None:
    """Assert the calling thread owns ``lock`` (RLock ownership probe).

    ``lock`` may be None (e.g. a Cache used standalone in unit tests with
    no scheduler attached) — then there is no discipline to enforce.
    """
    if lock is None or not enabled():
        return
    is_owned = getattr(lock, "_is_owned", None)
    if is_owned is None:
        return  # non-RLock stand-in (tests may inject mocks)
    if not is_owned():
        violation(
            "lock",
            f"{what} mutated without holding the guarding lock "
            f"(thread {threading.current_thread().name})",
        )


# ----- retrace hook (jit recompile accounting) -------------------------------
#
# The static `retrace` rule catches the leak shapes visible in the AST;
# this is the dynamic complement: under KTPU_SANITIZE=1 a jax compile
# event triggers a sweep of every known jit root's compilation-cache
# size.  Growth past the `mark_jit_warm()` watermark is an UNEXPECTED
# recompile (steady state re-used a signature that should have been
# warm) and bumps scheduler_tpu_jit_recompiles_total{fn=} on every
# registered metrics counter.  Cache sizes are swept (not inferred from
# the event alone) because jax's compile events carry no function name.

_jit_roots: dict = {}
_warm_sizes: Optional[dict] = None
_recompile_counts: dict = {}
_recompile_counters: "weakref.WeakSet" = weakref.WeakSet()
# jit-root listeners (the dispatch ledger's coverage feed,
# observability/kernels.py): called with (name, fn) for every root that
# arrives through register_jit_root, so runtime-created roots join the
# per-kernel accounting roster without a second discovery pass
_root_listeners: list = []
_retrace_hook_installed = False
_retrace_lock = threading.Lock()
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"


def _discover_jit_roots() -> dict:
    """Module-level jit roots of the shipped kernels (objects exposing
    jax's per-jit ``_cache_size``), keyed ``module.fn``.  Import errors
    are skipped — discovery must work on partial trees."""
    import importlib

    from kubernetes_tpu.analysis import JIT_MODULES

    rels = list(JIT_MODULES) + [os.path.join("cache", "device_mirror.py")]
    roots: dict = {}
    for rel in rels:
        modname = "kubernetes_tpu." + rel[:-3].replace(os.sep, ".")
        try:
            mod = importlib.import_module(modname)
        except Exception:  # noqa: BLE001 — partial trees analyze fine
            continue
        short = modname.rsplit(".", 1)[-1]
        for attr, obj in vars(mod).items():
            if attr.startswith("__"):
                continue
            if callable(getattr(obj, "_cache_size", None)):
                roots[f"{short}.{attr}"] = obj
    return roots


def register_recompile_counter(counter) -> None:
    """Wire a metrics Counter (scheduler_tpu_jit_recompiles_total{fn=});
    idempotent per instance, weakly held."""
    if counter is not None:
        _recompile_counters.add(counter)


def add_jit_root_listener(cb) -> None:
    """Subscribe to runtime jit-root registrations (idempotent per
    callback identity); already-registered runtime roots replay so a
    late subscriber misses nothing."""
    with _retrace_lock:
        if cb in _root_listeners:
            return
        _root_listeners.append(cb)
        existing = list(_jit_roots.items())
    for name, fn in existing:
        try:
            cb(name, fn)
        except Exception:  # noqa: BLE001 — accounting only
            pass


def register_jit_root(name: str, fn) -> None:
    """Track an extra jit root (one created at runtime rather than at
    module scope).  If a warm watermark is already set, the root joins it
    at its CURRENT cache size — its history so far counts as warmup."""
    if not callable(getattr(fn, "_cache_size", None)):
        return
    with _retrace_lock:
        _jit_roots[name] = fn
        if _warm_sizes is not None:
            _warm_sizes.setdefault(name, fn._cache_size())
        listeners = list(_root_listeners)
    for cb in listeners:
        try:
            cb(name, fn)
        except Exception:  # noqa: BLE001 — accounting only
            pass


def install_retrace_hook() -> None:
    """Register the jax compile-event listener (once per process).  A
    no-op unless KTPU_SANITIZE is on — the listener itself costs nothing
    when no warm watermark is set."""
    global _retrace_hook_installed
    if not enabled() or _retrace_hook_installed:
        return
    from jax import monitoring

    monitoring.register_event_duration_secs_listener(_on_compile_event)
    _retrace_hook_installed = True


def _on_compile_event(event: str, duration: float, **kw) -> None:
    if event != _COMPILE_EVENT or _warm_sizes is None:
        return
    _sweep_recompiles()


def mark_jit_warm() -> None:
    """Snapshot every jit root's compilation-cache size as the warm
    watermark: compiles after this point count as unexpected recompiles.
    Call it after the warmup drain, before the steady-state window."""
    global _warm_sizes
    install_retrace_hook()
    with _retrace_lock:
        _jit_roots.update(_discover_jit_roots())
        _warm_sizes = {
            name: fn._cache_size() for name, fn in _jit_roots.items()
        }
        _recompile_counts.clear()


def _sweep_recompiles() -> None:
    with _retrace_lock:
        if _warm_sizes is None:
            return
        for name, fn in _jit_roots.items():
            base = _warm_sizes.get(name)
            if base is None:
                continue
            try:
                cur = fn._cache_size()
            except Exception:  # noqa: BLE001 — a torn-down backend is fine
                continue
            seen = _recompile_counts.get(name, 0)
            delta = cur - base - seen
            if delta > 0:
                _recompile_counts[name] = seen + delta
                for c in list(_recompile_counters):
                    try:
                        c.inc(delta, fn=name)
                    except Exception:  # noqa: BLE001 — accounting only
                        pass


def unexpected_recompiles() -> dict:
    """{``module.fn`` → post-warmup recompile count}; empty before
    ``mark_jit_warm()``.  Sweeps before reporting (the compile event
    fires while the new executable is still being installed, so the
    event-driven count can trail by one until the next compile)."""
    if _warm_sizes is None:
        return {}
    _sweep_recompiles()
    with _retrace_lock:
        return {k: v for k, v in _recompile_counts.items() if v}


def reset_retrace() -> None:
    """Drop the warm watermark (tests re-arm per case)."""
    global _warm_sizes
    with _retrace_lock:
        _warm_sizes = None
        _recompile_counts.clear()


# ----- shape cross-check (eval_shape vs the symbolic interpreter) ------------
#
# The static `shape` rule trusts the interpreter's op models and the
# axes annotations; this is the dynamic complement: under KTPU_SANITIZE=1
# the first drain triggers ONE cross-validation of every instantiable
# jit root against jax.eval_shape (analysis/shapecheck.py — abstract
# tracing only, no compiles).  Mismatches bump
# scheduler_tpu_shape_check_failures_total{fn=} on every registered
# counter, so a drifted annotation or a mis-modelled op cannot pass a
# sanitized run silently.

_shape_counters: "weakref.WeakSet" = weakref.WeakSet()
_shape_check_result: Optional[dict] = None
_shape_lock = threading.Lock()


def register_shape_counter(counter) -> None:
    """Wire a metrics Counter (scheduler_tpu_shape_check_failures_total);
    idempotent per instance, weakly held."""
    if counter is not None:
        _shape_counters.add(counter)


def check_root_shapes() -> dict:
    """Run (once per process) the eval_shape cross-check; returns
    {root → [mismatches]} and feeds the failure counters.  No-op when
    the sanitizer is off."""
    global _shape_check_result
    if not enabled():
        return {}
    with _shape_lock:
        if _shape_check_result is not None:
            return _shape_check_result
        try:
            from kubernetes_tpu.analysis import shapecheck

            result = shapecheck.cross_check()
        except Exception:  # noqa: BLE001 — a broken checker must not
            # kill the drain; an empty-but-armed result would hide it, so
            # surface the breakage as a synthetic failure entry instead
            result = {"<shapecheck>": ["cross-check harness raised"]}
        _shape_check_result = result
    for fn, problems in result.items():
        for c in list(_shape_counters):
            try:
                c.inc(len(problems), fn=fn)
            except Exception:  # noqa: BLE001 — accounting only
                pass
    return result


def reset_shape_check() -> None:
    """Drop the memoized cross-check result (tests re-run per case)."""
    global _shape_check_result
    with _shape_lock:
        _shape_check_result = None


def check_mirror_consistency(cache, mirror) -> None:
    """Snapshot↔mirror drift probe, run after each drain.

    Verifies the per-node usage rows the device kernels read (requested /
    nonzero_req / num_pods) against a fresh recomputation from the
    authoritative cache.  Only meaningful when the mirror has packed at
    least once and its watermark covers the cache (callers run it right
    after a drain's final repack); nodes added after the last pack are
    skipped rather than misreported.
    """
    if not enabled():
        return
    nt = mirror.nodes
    if nt is None:
        return
    import numpy as np

    from kubernetes_tpu.snapshot.schema import MEM_UNIT, ResourceLanes

    lanes = ResourceLanes(mirror.vocab)
    R = nt.allocatable.shape[1]
    for cn in cache.real_nodes():
        idx = nt.name_to_idx.get(cn.node.name)
        if idx is None or cn.generation > mirror.generation:
            continue  # not packed yet / legitimately newer than the mirror
        want_req = np.asarray(lanes.request_row(cn.requested, R))
        got_req = np.asarray(nt.requested[idx])
        if not np.array_equal(want_req, got_req):
            violation(
                "mirror",
                f"node {cn.node.name!r} requested row drifted: "
                f"cache={want_req.tolist()} mirror={got_req.tolist()}",
            )
        want_nz = (
            cn.non_zero_requested.milli_cpu,
            -(-cn.non_zero_requested.memory // MEM_UNIT),
        )
        got_nz = (int(nt.nonzero_req[idx, 0]), int(nt.nonzero_req[idx, 1]))
        if want_nz != got_nz:
            violation(
                "mirror",
                f"node {cn.node.name!r} nonzero_req drifted: "
                f"cache={want_nz} mirror={got_nz}",
            )
        if int(nt.num_pods[idx]) != len(cn.pods):
            violation(
                "mirror",
                f"node {cn.node.name!r} num_pods drifted: "
                f"cache={len(cn.pods)} mirror={int(nt.num_pods[idx])}",
            )
