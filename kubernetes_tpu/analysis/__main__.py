"""CLI: ``python -m kubernetes_tpu.analysis [--json] [--rule R] [paths…]``.

Exit status: 0 when clean, 1 when any finding survives suppression
filtering (CI gates on this), 2 on usage/internal errors.

With no paths, the shipped tree is analyzed (each checker over its
registered modules).  Explicit paths are handed to ALL checkers — the
fixture-driven mode the tier-1 test uses (a fixture file declares its own
``_KTPU_GUARDED`` / ``pre_filter_spec_pure`` / ``jax.jit`` markers, so
only the relevant checker fires on it).

``--json`` prints a machine-readable report (findings + per-rule counts)
for the bench tooling instead of the line-per-finding text form.
"""

from __future__ import annotations

import argparse
import sys
from typing import List

from kubernetes_tpu.analysis import (
    render_json,
    render_text,
    run_analysis,
)
from kubernetes_tpu.analysis.core import ALL_RULES


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="Static invariant analysis (lock-discipline, "
        "plugin-purity, jit-boundary, d2h-leak, donation, slice-clamp, "
        "retrace).",
    )
    ap.add_argument("paths", nargs="*", help="files to analyze (default: shipped tree)")
    ap.add_argument("--json", action="store_true", help="JSON report on stdout")
    ap.add_argument(
        "--rule",
        action="append",
        choices=sorted(ALL_RULES),
        help="restrict output to RULE (repeatable)",
    )
    args = ap.parse_args(argv)

    try:
        if args.paths:
            targets = {
                "locks": args.paths,
                "purity": args.paths,
                "jit": args.paths,
                "d2h": args.paths,
                "donation": args.paths,
                "clamp": args.paths,
                "retrace": args.paths,
            }
            findings = run_analysis(targets)
        else:
            findings = run_analysis()
    except (OSError, SyntaxError) as e:
        print(f"kubernetes_tpu.analysis: error: {e}", file=sys.stderr)
        return 2

    if args.rule:
        findings = [f for f in findings if f.rule in set(args.rule)]

    print(render_json(findings) if args.json else render_text(findings))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
