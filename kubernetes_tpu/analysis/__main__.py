"""CLI: ``python -m kubernetes_tpu.analysis [--json] [--rule R]
[--baseline FILE | --write-baseline FILE] [paths…]``.

Exit status: 0 when clean, 1 when any finding survives suppression (and,
with ``--baseline``, baseline) filtering — CI gates on this; 2 on
usage/internal errors.

With no paths, the shipped tree is analyzed (each checker over its
registered modules).  Explicit paths are handed to ALL checkers — the
fixture-driven mode the tier-1 test uses (a fixture file declares its own
``_KTPU_GUARDED`` / ``pre_filter_spec_pure`` / ``jax.jit`` markers, so
only the relevant checker fires on it).

``--json`` prints a machine-readable report (findings + per-rule counts
and wall times) for the bench tooling instead of the line-per-finding
text form.

Baselines let a BRANCH gate on *new* findings while main stays strict on
zero: ``--write-baseline FILE`` snapshots the current findings;
``--baseline FILE`` reports only findings absent from the snapshot.
Matching is (rule, repo-relative path, message) as a multiset —
line-number churn neither hides nor resurrects a baselined finding, and
fixing one of two identical findings still surfaces the other.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter as _Counter
from typing import List

from kubernetes_tpu.analysis import (
    _REPO_ROOT,
    default_targets,
    last_rule_seconds,
    render_json,
    render_text,
    run_analysis,
)
from kubernetes_tpu.analysis.core import ALL_RULES, Finding


def _finding_key(f: Finding):
    path = f.path
    try:
        rel = os.path.relpath(os.path.abspath(path), _REPO_ROOT)
        if not rel.startswith(".."):
            path = rel
    except ValueError:
        pass
    return (f.rule, path.replace(os.sep, "/"), f.message)


def write_baseline(findings: List[Finding], path: str) -> None:
    doc = {
        "version": 1,
        "findings": [
            {"rule": r, "path": p, "message": m}
            for (r, p, m) in sorted(_finding_key(f) for f in findings)
        ],
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: List[Finding], path: str):
    """(new findings, suppressed count) — multiset subtraction on
    (rule, relpath, message)."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    budget = _Counter(
        (e["rule"], e["path"], e["message"])
        for e in doc.get("findings", ())
    )
    out: List[Finding] = []
    suppressed = 0
    for f in findings:
        key = _finding_key(f)
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            suppressed += 1
        else:
            out.append(f)
    return out, suppressed


def main(argv: List[str] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.analysis",
        description="Static invariant analysis — eleven rule families: "
        "lock-discipline, plugin-purity, jit-boundary, d2h-leak, "
        "donation, slice-clamp, retrace, shape, dtype, shard, breaker.",
    )
    ap.add_argument("paths", nargs="*", help="files to analyze (default: shipped tree)")
    ap.add_argument("--json", action="store_true", help="JSON report on stdout")
    ap.add_argument(
        "--rule",
        action="append",
        choices=sorted(ALL_RULES),
        help="restrict output to RULE (repeatable)",
    )
    ap.add_argument(
        "--baseline",
        metavar="FILE",
        help="report only findings NOT present in this baseline snapshot",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="FILE",
        help="snapshot current findings to FILE and exit 0",
    )
    args = ap.parse_args(argv)
    if args.baseline and args.write_baseline:
        print(
            "kubernetes_tpu.analysis: --baseline and --write-baseline are "
            "mutually exclusive",
            file=sys.stderr,
        )
        return 2

    try:
        if args.paths:
            # every checker key run_analysis knows about — derived, so a
            # new rule family cannot silently miss fixture-mode runs
            targets = {key: args.paths for key in default_targets()}
            findings = run_analysis(targets)
        else:
            findings = run_analysis()
    except (OSError, SyntaxError) as e:
        print(f"kubernetes_tpu.analysis: error: {e}", file=sys.stderr)
        return 2

    if args.write_baseline:
        try:
            write_baseline(findings, args.write_baseline)
        except OSError as e:
            print(f"kubernetes_tpu.analysis: error: {e}", file=sys.stderr)
            return 2
        print(
            f"kubernetes_tpu.analysis: baseline of {len(findings)} "
            f"finding(s) written to {args.write_baseline}"
        )
        return 0

    suppressed = None
    if args.baseline:
        try:
            findings, suppressed = apply_baseline(findings, args.baseline)
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            print(f"kubernetes_tpu.analysis: error: {e}", file=sys.stderr)
            return 2

    if args.rule:
        findings = [f for f in findings if f.rule in set(args.rule)]

    if args.json:
        print(render_json(findings, rule_seconds=dict(last_rule_seconds),
                          baseline_suppressed=suppressed))
    else:
        print(render_text(findings))
        if suppressed:
            print(
                f"kubernetes_tpu.analysis: {suppressed} baselined "
                "finding(s) suppressed"
            )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
