"""Device-boundary fetch checker (rule: ``d2h-leak``).

PR 6 made ``Scheduler._d2h`` the choke point for every BLOCKING
device→host fetch: it wraps ``jax.device_get`` with round-trip accounting
(``scheduler_tpu_host_roundtrips_total`` / ``scheduler_tpu_d2h_bytes_total``)
— the quantity the resident drain loop exists to minimize.  A fetch that
bypasses the choke point undercounts the very metric used to judge that
work, and usually marks an accidental sync on the hot path.

The checker runs a small DEVICE-RESIDENCE taint analysis over the host
modules that handle device values (the harvest half of the scheduler,
the fast-path glue, the snapshot mirrors, debug explain):

  * sources — calls into the jit roots indexed from ``ops/`` (resolved
    through import aliases, the same reachability the jit checker uses),
    ``jnp.*`` constructors, ``jax.device_put`` / ``jax.random.*``,
    ``DeviceCluster.from_host``-style packers, and the repo's ``*_dev``
    naming convention (names, attributes, and dict keys);
  * propagation — through arithmetic, subscripts, tuple unpacking, and
    methods of device values; if/else branches merge by union;
  * cleanser — ``…._d2h(x)`` results are host values.

Violations (all ``d2h-leak``): ``jax.device_get`` anywhere outside
``Scheduler._d2h``; ``np.asarray``/``np.array`` (any host-numpy call) on
a device value; ``.item()`` / ``.tolist()`` / ``.block_until_ready()``;
``int()/float()/bool()`` coercions; and implicit truthiness (``if x:``,
``while x:``, ``assert x``, ``not x``, ``and``/``or``) of a device value
— each of those blocks on the device and dodges the accounting.
``x is None`` identity checks and ``.copy_to_host_async()`` (the
non-blocking prefetch) are exempt by design.

Bench/debug harnesses with no Scheduler (hence no counters to feed) are
allowlisted by basename — today only ``ops/pipeline.py``, the standalone
parity pipeline.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set

from kubernetes_tpu.analysis.core import (
    RULE_D2H,
    Checker,
    ImportRefs,
    SourceModule,
    dotted_name,
)
from kubernetes_tpu.analysis.jit import _jit_decoration

NEUTRAL_ATTRS = {"shape", "ndim", "dtype", "size", "nbytes", "itemsize"}
BLOCKING_METHODS = {"item", "tolist", "block_until_ready"}
NONBLOCKING_METHODS = {"copy_to_host_async"}
CAST_BUILTINS = {"int", "float", "bool"}
CHOKE_POINT = "_d2h"
DEVICE_SUFFIX = "_dev"
DEVICE_KEYS = {"dev"}
# standalone bench/debug harnesses: no Scheduler exists there, so there
# are no counters a routed fetch could feed
ALLOW_BASENAMES = frozenset({"pipeline.py"})


def _module_base(path: str) -> str:
    return os.path.basename(path).rsplit(".", 1)[0]


class D2HChecker(Checker):
    rule = RULE_D2H

    def __init__(self, allow_basenames: frozenset = ALLOW_BASENAMES):
        super().__init__()
        self.allow_basenames = frozenset(allow_basenames)
        self.roots: Dict[str, Set[str]] = {}  # module base → jit-root names
        # path-scoped view for each module's OWN bare names: two target
        # modules sharing a basename (ops/explain.py and
        # observability/explain.py) must not resolve each other's
        self.roots_by_path: Dict[str, Set[str]] = {}
        self._base = ""
        self._path = ""
        self._refs: Optional[ImportRefs] = None

    # ----- entry point ------------------------------------------------------

    def run(
        self,
        mods: Sequence[SourceModule],
        root_mods: Sequence[SourceModule] = (),
    ) -> None:
        seen = set()
        for mod in list(mods) + list(root_mods):
            if mod.path in seen:
                continue
            seen.add(mod.path)
            self._index_roots(mod)
        for mod in mods:
            if os.path.basename(mod.path) in self.allow_basenames:
                continue
            self._base = _module_base(mod.path)
            self._path = mod.path
            self._refs = ImportRefs(mod.tree)
            self._check_module(mod)

    def _index_roots(self, mod: SourceModule) -> None:
        base = _module_base(mod.path)
        merged = self.roots.setdefault(base, set())
        per = self.roots_by_path.setdefault(mod.path, set())

        def walk(fn: ast.AST) -> None:
            for node in ast.iter_child_nodes(fn):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if isinstance(node, ast.FunctionDef) and _jit_decoration(
                        node
                    ):
                        merged.add(node.name)
                        per.add(node.name)
                    walk(node)
                elif isinstance(node, (ast.ClassDef, ast.If, ast.Try)):
                    walk(node)

        walk(mod.tree)

    # ----- module / function walk -------------------------------------------

    def _check_module(self, mod: SourceModule) -> None:
        for node in mod.tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self._check_function(mod, item)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(mod, node)

    def _check_function(self, mod: SourceModule, fn: ast.FunctionDef) -> None:
        if fn.name == CHOKE_POINT:
            return  # the choke point itself is where the fetch belongs
        if isinstance(fn, ast.FunctionDef) and _jit_decoration(fn):
            return  # traced bodies are the jit-boundary checker's domain
        env: Dict[str, bool] = {}
        for a in fn.args.args + fn.args.kwonlyargs:
            env[a.arg] = a.arg.endswith(DEVICE_SUFFIX)
        self._walk_block(mod, fn.body, env)

    def _walk_block(
        self, mod: SourceModule, stmts: List[ast.stmt], env: Dict[str, bool]
    ) -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._check_function(mod, st)
                env[st.name] = False
                continue
            self._scan_stmt(mod, st, env)
            if isinstance(st, ast.Assign):
                self._bind(st.targets, st.value, env)
            elif isinstance(st, ast.AnnAssign):
                if st.value is not None:
                    self._bind([st.target], st.value, env)
            elif isinstance(st, ast.AugAssign):
                if isinstance(st.target, ast.Name):
                    env[st.target.id] = env.get(
                        st.target.id, False
                    ) or self._device(st.value, env)
            elif isinstance(st, ast.If):
                e1, e2 = dict(env), dict(env)
                self._walk_block(mod, st.body, e1)
                self._walk_block(mod, st.orelse, e2)
                for k in set(e1) | set(e2):
                    env[k] = e1.get(k, False) or e2.get(k, False)
            elif isinstance(st, (ast.For, ast.While)):
                e1 = dict(env)
                if isinstance(st, ast.For):
                    self._bind([st.target], st.iter, e1)
                self._walk_block(mod, st.body, e1)
                self._walk_block(mod, st.orelse, e1)
                for k in set(e1):
                    env[k] = env.get(k, False) or e1.get(k, False)
            else:
                for attr in ("body", "orelse", "finalbody"):
                    sub = getattr(st, attr, None)
                    if sub:
                        self._walk_block(mod, sub, env)
                for handler in getattr(st, "handlers", ()) or ():
                    self._walk_block(mod, handler.body, env)

    def _bind(
        self,
        targets: List[ast.expr],
        value: ast.expr,
        env: Dict[str, bool],
    ) -> None:
        dev = self._device(value, env)
        for t in targets:
            if isinstance(t, (ast.Tuple, ast.List)):
                if isinstance(value, (ast.Tuple, ast.List)) and len(
                    value.elts
                ) == len(t.elts):
                    for el, v in zip(t.elts, value.elts):
                        self._bind([el], v, env)
                else:
                    for el in t.elts:
                        if isinstance(el, ast.Name):
                            env[el.id] = dev
            elif isinstance(t, ast.Name):
                env[t.id] = dev
            # attribute/subscript stores: tracked via the *_dev / ["dev"]
            # naming convention on the read side

    # ----- sinks ------------------------------------------------------------

    def _scan_stmt(
        self, mod: SourceModule, st: ast.stmt, env: Dict[str, bool]
    ) -> None:
        if isinstance(st, (ast.If, ast.While)):
            self._check_truthiness(mod, st.test, env)
            self._scan_expr(mod, st.test, env)
            return  # bodies are statements — handled by _walk_block
        if isinstance(st, ast.Assert):
            self._check_truthiness(mod, st.test, env)
            self._scan_expr(mod, st.test, env)
            return
        for child in ast.iter_child_nodes(st):
            if isinstance(child, ast.expr):
                self._scan_expr(mod, child, env)
            elif isinstance(child, ast.withitem):
                # `with f(x_dev):` — withitems are not exprs, and a fetch
                # hiding in a context header blocks like any other
                self._scan_expr(mod, child.context_expr, env)

    def _check_truthiness(
        self, mod: SourceModule, test: ast.expr, env: Dict[str, bool]
    ) -> None:
        if self._device(test, env):
            self.emit(
                mod,
                test.lineno,
                "implicit truthiness of a device value blocks on the device "
                "(and bypasses Scheduler._d2h accounting)",
            )

    def _scan_expr(
        self, mod: SourceModule, expr: ast.expr, env: Dict[str, bool]
    ) -> None:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                self._check_call(mod, node, env)
            elif isinstance(node, ast.IfExp):
                self._check_truthiness(mod, node.test, env)
            elif isinstance(node, ast.BoolOp):
                for v in node.values:
                    self._check_truthiness(mod, v, env)
            elif isinstance(node, ast.UnaryOp) and isinstance(
                node.op, ast.Not
            ):
                self._check_truthiness(mod, node.operand, env)

    def _check_call(
        self, mod: SourceModule, node: ast.Call, env: Dict[str, bool]
    ) -> None:
        refs = self._refs
        func = node.func
        dn = dotted_name(func)
        if dn is not None:
            parts = dn.split(".")
            root, last = parts[0], parts[-1]
            if root in refs.jax_roots and last == "device_get":
                self.emit(
                    mod,
                    node.lineno,
                    "blocking jax.device_get outside Scheduler._d2h — "
                    "route the fetch through _d2h so "
                    "host_roundtrips_total/d2h_bytes_total see it",
                )
                return
            if root in refs.np_roots and any(
                self._device(a, env)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            ):
                self.emit(
                    mod,
                    node.lineno,
                    f"{dn}(...) coerces a device value through host numpy — "
                    "a blocking fetch outside Scheduler._d2h",
                )
                return
        if isinstance(func, ast.Attribute):
            if func.attr in BLOCKING_METHODS and self._device(
                func.value, env
            ):
                self.emit(
                    mod,
                    node.lineno,
                    f".{func.attr}() on a device value is a blocking fetch "
                    "outside Scheduler._d2h",
                )
                return
        elif isinstance(func, ast.Name):
            if (
                func.id in CAST_BUILTINS
                and func.id not in env  # not shadowed
                and node.args
                and self._device(node.args[0], env)
            ):
                self.emit(
                    mod,
                    node.lineno,
                    f"{func.id}() on a device value is a blocking fetch "
                    "outside Scheduler._d2h",
                )

    # ----- device-residence taint -------------------------------------------

    def _device(self, node: ast.expr, env: Dict[str, bool]) -> bool:
        if isinstance(node, (ast.Constant, ast.JoinedStr)):
            return False
        if isinstance(node, ast.Name):
            return env.get(node.id, node.id.endswith(DEVICE_SUFFIX))
        if isinstance(node, ast.Attribute):
            if node.attr in NEUTRAL_ATTRS:
                return False
            if node.attr.endswith(DEVICE_SUFFIX):
                return True
            return self._device(node.value, env)
        if isinstance(node, ast.Subscript):
            sl = node.slice
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                if sl.value.endswith(DEVICE_SUFFIX) or sl.value in DEVICE_KEYS:
                    return True
            return self._device(node.value, env)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._device(el, env) for el in node.elts)
        if isinstance(node, ast.BinOp):
            return self._device(node.left, env) or self._device(
                node.right, env
            )
        if isinstance(node, ast.UnaryOp):
            return self._device(node.operand, env)
        if isinstance(node, ast.BoolOp):
            return any(self._device(v, env) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity check — no __bool__, no sync
            return self._device(node.left, env) or any(
                self._device(c, env) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return self._device(node.body, env) or self._device(
                node.orelse, env
            )
        if isinstance(node, ast.Starred):
            return self._device(node.value, env)
        if isinstance(node, ast.Call):
            return self._device_call(node, env)
        return False

    def _device_call(self, node: ast.Call, env: Dict[str, bool]) -> bool:
        refs = self._refs
        func = node.func
        dn = dotted_name(func)
        if dn is not None:
            parts = dn.split(".")
            root, last = parts[0], parts[-1]
            if last == CHOKE_POINT:
                return False  # routed fetch → host value
            if root in refs.jnp_roots:
                return True
            if root in refs.np_roots:
                return False
            if root in refs.jax_roots:
                if last == "device_put":
                    return True
                if len(parts) >= 2 and parts[1] == "random":
                    return True
                return False  # device_get and friends return host values
            if "device_put" in last:
                return True
            if (
                last == "from_host"
                and len(parts) == 2
                and parts[0] in refs.sym_alias
            ):
                return True  # DeviceCluster.from_host / DeviceBatch.from_host
            # jit-root resolution through the alias tables
            if len(parts) == 2 and root in refs.mod_alias:
                if last in self.roots.get(refs.mod_alias[root], ()):
                    return True
            if len(parts) == 1:
                if dn in refs.sym_alias:
                    m, s = refs.sym_alias[dn]
                    if s in self.roots.get(m, ()):
                        return True
                if dn in self.roots_by_path.get(self._path, ()):
                    return True
        if isinstance(func, ast.Attribute):
            if func.attr in NONBLOCKING_METHODS | BLOCKING_METHODS:
                return False
            if (
                func.attr == "get"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
                and (
                    node.args[0].value.endswith(DEVICE_SUFFIX)
                    or node.args[0].value in DEVICE_KEYS
                )
            ):
                return True  # rec.get("rstats_dev")
            # a method of a device value yields a device value
            if func.attr not in NEUTRAL_ATTRS and self._device(
                func.value, env
            ):
                return True
        return False
