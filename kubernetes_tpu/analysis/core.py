"""Shared machinery for the invariant analyzers.

The suite is AST-only: no analyzed module is ever imported, so fixture
files with deliberately broken concurrency and framework modules with
heavyweight imports analyze identically.  Each checker consumes
``SourceModule`` objects and emits ``Finding``s; suppression comments
(``# ktpu: allow(<rule>) — <reason>``) are resolved here, uniformly, so
a checker never needs to know it was silenced.

A suppression without a reason is itself a finding (``bare-suppression``)
— the suppression syntax exists to FORCE the justification into the
diff, not to provide an escape hatch from it.
"""

from __future__ import annotations

import ast
import json
import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence

RULE_LOCK = "lock-discipline"
RULE_PURITY = "plugin-purity"
RULE_JIT = "jit-boundary"
RULE_D2H = "d2h-leak"
RULE_DONATION = "donation"
RULE_CLAMP = "slice-clamp"
RULE_RETRACE = "retrace"
RULE_SHAPE = "shape"
RULE_DTYPE = "dtype"
RULE_SHARD = "shard"
RULE_BREAKER = "breaker"
RULE_BARE_SUPPRESSION = "bare-suppression"

ALL_RULES = (
    RULE_LOCK,
    RULE_PURITY,
    RULE_JIT,
    RULE_D2H,
    RULE_DONATION,
    RULE_CLAMP,
    RULE_RETRACE,
    RULE_SHAPE,
    RULE_DTYPE,
    RULE_SHARD,
    RULE_BREAKER,
    RULE_BARE_SUPPRESSION,
)

# `# ktpu: allow(rule[, rule...]) — reason`  (em/en/double/single dash or
# colon all accepted as the reason separator; the reason is mandatory)
_SUPPRESS_RE = re.compile(
    r"#\s*ktpu:\s*allow\(\s*([a-zA-Z0-9_,\- ]+?)\s*\)\s*(?:(?:—|–|--|-|:)\s*(\S.*))?$"
)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def as_dict(self) -> Dict[str, object]:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
        }


@dataclass
class Suppression:
    rules: List[str]
    line: int
    reason: str  # "" when bare
    used: bool = False


class SourceModule:
    """One parsed file: source lines, AST, and its suppression table."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        # line → suppressions; comments alone on their lines (STACKABLE —
        # one per rule with its own reason) cover the next non-comment
        # line, a trailing comment covers its own line.
        self.suppressions: Dict[int, List[Suppression]] = {}
        self.bare_suppressions: List[int] = []
        self._scan_suppressions()

    @classmethod
    def load(cls, path: str) -> "SourceModule":
        with open(path, "r", encoding="utf-8") as f:
            return cls(path, f.read())

    def _scan_suppressions(self) -> None:
        pending: List[Suppression] = []
        for i, raw in enumerate(self.lines, start=1):
            stripped = raw.strip()
            m = _SUPPRESS_RE.search(raw)
            if m:
                rules = [r.strip() for r in m.group(1).split(",") if r.strip()]
                reason = (m.group(2) or "").strip()
                sup = Suppression(rules=rules, line=i, reason=reason)
                if not reason:
                    self.bare_suppressions.append(i)
                if stripped.startswith("#"):
                    pending.append(sup)  # standalone → covers next code line
                else:
                    self.suppressions.setdefault(i, []).append(sup)
                continue
            if pending and stripped and not stripped.startswith("#"):
                self.suppressions.setdefault(i, []).extend(pending)
                pending = []

    def suppressed(self, rule: str, line: int) -> bool:
        for sup in self.suppressions.get(line, ()):
            if rule in sup.rules and sup.reason:
                sup.used = True
                return True
        return False


# Process-level parse cache: every rule family reads the same shipped-tree
# files, and the tier-1 gate runs the whole suite dozens of times per
# session (tree gate + every fixture case + the CLI tests + bench
# preflight).  One parse per (path, content digest) serves all of them;
# a touched file (fixtures written to tmp dirs, editor saves between
# runs) misses on content and reparses.  Suppression hit-tracking is the
# only mutable state on a SourceModule and is monotonic, so sharing
# instances across rule families and runs is safe.
_SOURCE_CACHE: Dict[str, tuple] = {}


def load_source(path: str) -> SourceModule:
    """Content-keyed cached parse — the single AST share point for all
    rule families (each checker used to load its own copy).  Keyed on a
    digest of the bytes, not mtime: a rewrite within the filesystem
    timestamp granularity (write→analyze→write→analyze loops in one
    process) must never serve the stale AST.  The read+hash is the cheap
    part; it's the ast.parse the cache amortizes."""
    import hashlib
    import os

    key = os.path.abspath(path)
    with open(key, "rb") as f:
        raw = f.read()
    digest = hashlib.blake2b(raw, digest_size=16).digest()
    hit = _SOURCE_CACHE.get(key)
    if hit is not None and hit[0] == digest:
        return hit[1]
    mod = SourceModule(key, raw.decode("utf-8"))
    _SOURCE_CACHE[key] = (digest, mod)
    return mod


def dotted_name(node: ast.AST) -> Optional[str]:
    """Flatten Name/Attribute chains to 'a.b.c' (None for anything else)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted_name(node.func)


class ImportRefs:
    """Module-wide import tables (module-level AND function-local imports —
    the scheduler defers most ops imports into the methods that use them).

    ``mod_alias`` maps a local name to an in-package MODULE's base name
    (``from kubernetes_tpu.ops import fastpath as ops_fp`` → ``ops_fp`` →
    ``'fastpath'``); ``sym_alias`` maps a local name to ``(module base,
    symbol)`` for direct symbol imports.  Module-vs-symbol is decided by
    the package's own convention: modules are lowercase and imported from
    a package path at most two levels deep.
    """

    def __init__(self, tree: ast.Module):
        self.mod_alias: Dict[str, str] = {}
        self.sym_alias: Dict[str, tuple] = {}
        self.np_roots: set = set()
        self.jnp_roots: set = set()
        self.jax_roots: set = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        self.np_roots.add(local)
                    elif a.name == "jax.numpy" and a.asname:
                        self.jnp_roots.add(a.asname)
                    elif a.name == "jax" or a.name.startswith("jax."):
                        self.jax_roots.add(local)
            elif isinstance(node, ast.ImportFrom):
                m = node.module or ""
                for a in node.names:
                    local = a.asname or a.name
                    if m == "numpy":
                        self.np_roots.add(local)
                    elif m == "jax" and a.name == "numpy":
                        self.jnp_roots.add(local)
                    elif m == "jax":
                        self.jax_roots.add(local)
                    elif m == "kubernetes_tpu" or m.startswith("kubernetes_tpu."):
                        if a.name[:1].islower() and m.count(".") <= 1:
                            self.mod_alias[local] = a.name
                        else:
                            self.sym_alias[local] = (m.rsplit(".", 1)[-1], a.name)


def resolve_root(refs: ImportRefs, self_roots: dict, roots_by_base: dict,
                 func: ast.AST):
    """Resolve a call target to a registered root through the import
    alias tables — shared by the donation and retrace checkers.

    ``self_roots`` is the CURRENT module's own name→root table, scoped by
    PATH (two target modules sharing a basename — ops/explain.py and
    observability/explain.py — must not resolve each other's bare names);
    ``roots_by_base`` is the module-base-keyed table the sym/mod alias
    lookups go through (import paths only carry the base)."""
    dn = dotted_name(func)
    if dn is None:
        return None
    parts = dn.split(".")
    if len(parts) == 1:
        r = self_roots.get(parts[0])
        if r is not None:
            return r
        if parts[0] in refs.sym_alias:
            m, s = refs.sym_alias[parts[0]]
            return roots_by_base.get(m, {}).get(s)
        return None
    if len(parts) == 2 and parts[0] in refs.mod_alias:
        return roots_by_base.get(refs.mod_alias[parts[0]], {}).get(parts[1])
    return None


def module_literal(tree: ast.Module, name: str):
    """Evaluate a module-level literal assignment (the annotation registry
    pattern: ``_KTPU_GUARDED = {...}``) without importing the module."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    try:
                        return ast.literal_eval(node.value)
                    except (ValueError, SyntaxError):
                        return None
    return None


class Checker:
    """Base: run() yields raw findings; filter_findings applies suppressions
    from the owning module."""

    rule: str = ""

    def __init__(self) -> None:
        self.findings: List[Finding] = []

    def emit(self, mod: SourceModule, line: int, message: str, rule: Optional[str] = None) -> None:
        r = rule or self.rule
        if not mod.suppressed(r, line):
            self.findings.append(Finding(r, mod.path, line, message))


def collect_bare_suppressions(mods: Iterable[SourceModule]) -> List[Finding]:
    out = []
    for mod in mods:
        for line in mod.bare_suppressions:
            out.append(
                Finding(
                    RULE_BARE_SUPPRESSION,
                    mod.path,
                    line,
                    "suppression without a justification — write "
                    "`# ktpu: allow(<rule>) — <reason>`",
                )
            )
    return out


def render_text(findings: Sequence[Finding]) -> str:
    if not findings:
        return "kubernetes_tpu.analysis: no findings"
    lines = [f.format() for f in findings]
    lines.append(f"kubernetes_tpu.analysis: {len(findings)} finding(s)")
    return "\n".join(lines)


def render_json(
    findings: Sequence[Finding],
    rule_seconds: Optional[Dict[str, float]] = None,
    baseline_suppressed: Optional[int] = None,
) -> str:
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    doc: Dict[str, object] = {
        "findings": [f.as_dict() for f in findings],
        "count": len(findings),
        "by_rule": by_rule,
    }
    if rule_seconds is not None:
        # per-rule wall time; the shape/dtype/shard families share one
        # symbolic interpretation whose cost lands on whichever ran
        # first ('shape' — see run_analysis)
        doc["rule_seconds"] = {
            k: round(v, 4) for k, v in rule_seconds.items()
        }
    if baseline_suppressed is not None:
        doc["baseline_suppressed"] = baseline_suppressed
    return json.dumps(doc, indent=2, sort_keys=True)
