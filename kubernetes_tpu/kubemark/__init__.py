"""Kubemark tier: hollow kubelets (mocked node agents) for scale testing.

Reference: cmd/kubemark/hollow-node.go + pkg/kubemark/hollow_kubelet.go.
"""

from kubernetes_tpu.kubemark.hollow import HollowFleet, HollowKubelet

__all__ = ["HollowFleet", "HollowKubelet"]
