"""Hollow kubelets: node agents with mocked runtimes (hollow_kubelet.go:87).

A HollowKubelet does what the scheduler-relevant slice of a kubelet does,
against the HTTP API tier:

  * registers its Node;
  * HEARTBEATS — periodic node-status writes (Ready condition +
    lastHeartbeatTime) over the status subresource, the signal the
    node-lifecycle controller watches;
  * POD STATUS — pods bound to it get their phase patched to Running (a
    real kubelet would start containers first; the hollow runtime reports
    success immediately, like kubemark's mocked CRI).

``HollowFleet`` runs many kubelets off ONE shared pods watcher and a
small heartbeat thread pool — per-node watch streams would need thousands
of sockets at kubemark scale, and the fan-in matches how hollow nodes
share infrastructure in the reference's kubemark deployments.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from kubernetes_tpu.api.resource import Resource
from kubernetes_tpu.api.types import Node


class HollowKubelet:
    """One hollow node's agent state (registration + heartbeat payload)."""

    def __init__(self, name: str, node: Node):
        self.name = name
        self.node = node
        self.alive = True  # stop_heartbeats() simulates a dead kubelet


class HollowFleet:
    """N hollow kubelets sharing one client, one pods watcher, and one
    heartbeat loop."""

    def __init__(
        self,
        endpoint: str,
        heartbeat_interval_s: float = 10.0,
        report_pod_status: bool = True,
        codec: str = "binary",
    ):
        from kubernetes_tpu.client import ApiClient, Reflector

        self.client = ApiClient(endpoint, codec=codec)
        self.heartbeat_interval_s = heartbeat_interval_s
        self.report_pod_status = report_pod_status
        self.kubelets: Dict[str, HollowKubelet] = {}
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._pods_reflector: Optional[Reflector] = None
        self._reported: set = set()

    # ----- registration ----------------------------------------------------

    def register(self, nodes: List[Node]) -> None:
        """Bulk-register hollow nodes and start agent loops for them."""
        self.client.create_nodes(nodes)
        self.adopt(nodes)

    def adopt(self, nodes: List[Node]) -> None:
        """Run agent loops for nodes registered elsewhere (e.g. by the
        scale driver's per-node registration storm).  Server-side
        last_heartbeat stays 0 (= never-stale to the lifecycle controller)
        until the first beat, which the heartbeat loop sends immediately
        on start()."""
        for n in nodes:
            self.kubelets[n.name] = HollowKubelet(n.name, n)

    def start(self) -> "HollowFleet":
        self._hb_thread = threading.Thread(target=self._heartbeat_loop, daemon=True)
        self._hb_thread.start()
        if self.report_pod_status:
            from kubernetes_tpu.client import Reflector

            self._pods_reflector = Reflector(
                self.client,
                "pods",
                self._on_pod,
                lambda old, new: self._on_pod(new),
                lambda pod: self._reported.discard(pod.uid),
            ).start()
        return self

    def _on_pod(self, pod) -> None:
        """A pod bound to one of OUR nodes gets its status reported —
        phase Running, exactly once (the hollow runtime 'starts' it)."""
        if (
            pod.node_name in self.kubelets
            and self.kubelets[pod.node_name].alive
            and pod.phase == "Pending"
            and pod.uid not in self._reported
        ):
            self._reported.add(pod.uid)
            try:
                self.client.patch_pod_phase(pod.uid, "Running")
            except Exception:  # noqa: BLE001 — pod may be gone already
                self._reported.discard(pod.uid)

    def _heartbeat_loop(self) -> None:
        first = True
        while first or not self._stop.wait(self.heartbeat_interval_s):
            first = False  # beat immediately, then every interval
            now = time.time()
            for kl in list(self.kubelets.values()):
                if not kl.alive:
                    continue
                try:
                    self.client.patch_node_status(kl.name, True, now)
                except Exception:  # noqa: BLE001 — server restarting
                    pass

    # ----- failure injection ----------------------------------------------

    def stop_heartbeats(self, names: List[str]) -> None:
        """Simulate dead kubelets: their nodes stop renewing Ready."""
        for n in names:
            kl = self.kubelets.get(n)
            if kl is not None:
                kl.alive = False

    def stop(self) -> None:
        self._stop.set()
        if self._hb_thread is not None:
            self._hb_thread.join(timeout=5)
        if self._pods_reflector is not None:
            self._pods_reflector.stop()
