"""Span tracer: Chrome trace-event JSON for one scheduling process.

The distributed-tracing role the reference scheduler gets from component
tracing (utiltrace + the kube-scheduler's OpenTelemetry spans) rebuilt for
the batched hot loop: spans cover a whole drain, each batch's dispatch and
harvest halves, the per-phase breakdown (queue_pop/pack/h2d/device/d2h/
commit/bind — fed by metrics.PhaseAccumulator), and the binding workers'
chunks, each on its own thread track.  The export is the Chrome trace-event
format ("traceEvents" complete/instant events with microsecond ts/dur), so
``chrome://tracing`` and Perfetto load it directly.

Spans carry scheduler context in ``args``: pod uids (small batches), batch
ids, pod counts — and, when a chaos journal is attached
(``JournalRecorder.attach`` wires ``tracer.logical_time``), the journal's
logical timestamp ``lt``, so a wall-clock span can be located in the
replayable journal stream.

Cost model: when ``enabled`` is False every instrumentation site reduces to
one attribute load and a branch — no locks, no clock reads, no allocation,
and ZERO device-path involvement (nothing here touches jax).  When enabled,
each span is one lock acquisition + one dict append; the buffer is bounded
(``max_events``), overflow increments a drop counter instead of growing.

Black-box mode (``blackbox_start``): the same recorder as an ALWAYS-ON
bounded rolling ring — overflow evicts the OLDEST event (counted) instead
of dropping the newest, so the buffer always holds the trailing window of
spans.  An SLO breach (observability/slo.py) freezes the ring
(``blackbox_freeze``) and exports it, so the trace of the bad window
exists *after* the incident without anyone having started a capture.
The hot-path discipline is identical: off is one attribute read per site.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Lock-discipline registry (kubernetes_tpu.analysis): the scheduling loop,
# binding workers, and HTTP debug handlers all record/export concurrently.
_KTPU_GUARDED = {
    "Tracer": {
        "lock": "_mu",
        "guards": {
            "_trace_events": None,
            "_trace_dropped": None,
            "_trace_evicted": None,
            "_ring_mode": None,
            "_ring_cap": None,
            "_tid_names": None,
            "_track_tids": None,
            "_overhead_s": None,
        },
    },
}

DEFAULT_MAX_EVENTS = 200_000
# black-box ring default: deep enough that a multi-second bad window of
# batch/phase spans survives until the breach evaluator fires, small
# enough (~15 MB of dicts) to sit resident in a serving process forever
DEFAULT_BLACKBOX_EVENTS = 65_536


class Tracer:
    """Bounded in-memory span recorder with Chrome trace-event export.

    ``enabled`` is the single hot-path gate: instrumentation sites read it
    as a plain attribute before doing any work.  ``start()`` resets the
    buffer and enables; ``stop()`` disables but keeps events for export.
    """

    def __init__(
        self,
        max_events: int = DEFAULT_MAX_EVENTS,
        clock=time.perf_counter,
    ):
        self.enabled = False
        self.max_events = max_events
        self._clock = clock
        self._mu = threading.Lock()
        self._trace_events: deque = deque()
        self._trace_dropped = 0
        self._trace_evicted = 0
        # black-box ring mode: overflow evicts OLDEST instead of dropping
        # the newest — the buffer becomes a rolling trailing window
        self._ring_mode = False
        self._ring_cap = DEFAULT_BLACKBOX_EVENTS
        self._tid_names: Dict[int, str] = {}
        # synthetic tracks (device-side spans from the dispatch ledger):
        # track name → synthetic tid, far above any OS thread ident so
        # Perfetto renders them as their own named rows
        self._track_tids: Dict[str, int] = {}
        self._overhead_s = 0.0
        self._t0 = clock()
        # optional journal logical-time source (JournalRecorder.attach sets
        # it to Journal.now) — sampled into every span's args as "lt"
        self.logical_time = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        """Begin a MANUAL capture (drop-newest on overflow).  Overrides an
        active black-box ring until ``blackbox_start`` re-arms it."""
        with self._mu:
            self._trace_events = deque()
            self._trace_dropped = 0
            self._trace_evicted = 0
            self._ring_mode = False
            self._tid_names = {}
            self._track_tids = {}
            self._overhead_s = 0.0
            self._t0 = self._clock()
        self.enabled = True

    def stop(self) -> None:
        self.enabled = False

    def blackbox_start(self, capacity: int = DEFAULT_BLACKBOX_EVENTS) -> None:
        """Arm (or re-arm after a freeze/dump) the always-on black-box
        ring: recording on, evict-oldest at ``capacity`` events."""
        with self._mu:
            self._trace_events = deque()
            self._trace_dropped = 0
            self._trace_evicted = 0
            self._ring_mode = True
            self._ring_cap = max(int(capacity), 1)
            self._tid_names = {}
            self._track_tids = {}
            self._overhead_s = 0.0
            self._t0 = self._clock()
        self.enabled = True

    def blackbox_freeze(self) -> Optional[dict]:
        """Freeze the black-box ring (stop recording, keep events) and
        return ``{"trace": <export>, "freeze_offset_us": <ring-relative
        freeze time>}`` — None when the ring isn't armed.  The caller
        (the SLO breach handler) dumps the trace and calls
        ``blackbox_start`` again to resume recording."""
        with self._mu:
            if not self._ring_mode:
                return None
            # armed-check, recording stop, freeze stamp, and ring snapshot
            # in ONE critical section: a concurrent manual start() (the
            # /debug/trace HTTP thread) serializes either before us (ring
            # disarmed — we return None, the operator's capture survives)
            # or after us (it swaps in a fresh buffer — our snapshot is
            # still the bad window, and its capture keeps recording)
            self.enabled = False
            freeze_offset_us = (self._clock() - self._t0) * 1e6
            events = list(self._trace_events)
            names = dict(self._tid_names)
            dropped = self._trace_dropped
        return {
            "trace": self._build_trace(events, names, dropped),
            "freeze_offset_us": freeze_offset_us,
        }

    def now(self) -> float:
        return self._clock()

    # -- recording -----------------------------------------------------------

    def _append(self, name, cat, ph, t0, t1, args, track=None) -> None:
        """Finalize and buffer one event.  The origin read, the clamp, and
        the buffer append all happen under ONE lock hold: start() swaps
        the buffer and the origin atomically, so a concurrent recorder can
        never stamp a stale origin into the fresh buffer.  A span whose
        work STARTED before the capture renders only its in-capture part —
        an unclamped t0 would paint pre-trace time as a fat span at the
        origin.  ``track`` routes the event onto a named SYNTHETIC track
        (a tid above any OS thread ident) instead of the calling thread's
        — the device-side spans' own row in Perfetto."""
        t_in = self._clock()
        if track is None:
            tid = threading.get_ident()
            tname = threading.current_thread().name
        else:
            tid = None
            tname = track
        with self._mu:
            if tid is None:
                tid = self._track_tids.get(track)
                if tid is None:
                    tid = self._track_tids[track] = (1 << 40) + len(
                        self._track_tids
                    )
            if tid not in self._tid_names:
                self._tid_names[tid] = tname
            origin = self._t0
            if t0 < origin:
                t0 = origin
            if t1 < t0:
                t1 = t0
            ev = {
                "name": name,
                "cat": cat,
                "ph": ph,
                "ts": (t0 - origin) * 1e6,
                "pid": 1,
                "tid": tid,
                "args": args,
            }
            if ph == "X":
                ev["dur"] = (t1 - t0) * 1e6
            else:
                ev["s"] = "t"
            if self._ring_mode:
                # black-box ring: recent history always wins
                if len(self._trace_events) >= self._ring_cap:
                    self._trace_events.popleft()
                    self._trace_evicted += 1
                self._trace_events.append(ev)
            elif len(self._trace_events) >= self.max_events:
                self._trace_dropped += 1
            else:
                self._trace_events.append(ev)
            self._overhead_s += self._clock() - t_in

    def complete(self, name: str, t0: float, cat: str = "sched", **args) -> None:
        """Record a complete ('X') event spanning [t0, now).  ``t0`` is a
        reading of ``self.now()`` taken when the work started."""
        if not self.enabled:
            return
        t1 = self._clock()
        self._record_x(name, t0, t1, cat, args)

    def complete_tail(
        self, name: str, dur_s: float, cat: str = "phase", **args
    ) -> None:
        """Record a complete event of ``dur_s`` seconds ENDING now — the
        shape PhaseAccumulator.add has (it learns the duration after the
        fact, at the accumulate call)."""
        if not self.enabled:
            return
        t1 = self._clock()
        self._record_x(name, t1 - dur_s, t1, cat, args)

    def _record_x(self, name, t0, t1, cat, args) -> None:
        lt = self.logical_time
        if lt is not None:
            try:
                args = dict(args, lt=lt())
            except Exception:  # noqa: BLE001 — journal detached mid-trace
                pass
        self._append(name, cat, "X", t0, t1, args)

    def complete_track(
        self, track: str, name: str, t0: float, t1: float,
        cat: str = "device", **args,
    ) -> None:
        """Record a complete event spanning [t0, t1) on the named
        synthetic track (the dispatch ledger's device-side kernel spans,
        rendered alongside the host thread tracks).  Carries the journal
        logical time like every other span when one is attached."""
        if not self.enabled:
            return
        lt = self.logical_time
        if lt is not None:
            try:
                args = dict(args, lt=lt())
            except Exception:  # noqa: BLE001 — journal detached mid-trace
                pass
        self._append(name, cat, "X", t0, t1, args, track=track)

    def instant(self, name: str, cat: str = "sched", **args) -> None:
        if not self.enabled:
            return
        lt = self.logical_time
        if lt is not None:
            try:
                args = dict(args, lt=lt())
            except Exception:  # noqa: BLE001
                pass
        now = self._clock()
        self._append(name, cat, "i", now, now, args)

    def span(self, name: str, cat: str = "sched", **args) -> "_Span":
        """Context manager form; a no-op singleton when disabled."""
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name, cat, args)

    # -- export --------------------------------------------------------------

    def export(self) -> dict:
        """Perfetto/chrome://tracing-loadable trace object."""
        with self._mu:
            events = list(self._trace_events)
            names = dict(self._tid_names)
            dropped = self._trace_dropped
        return self._build_trace(events, names, dropped)

    @staticmethod
    def _build_trace(events, names, dropped) -> dict:
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "pid": 1,
                "tid": 0,
                "args": {"name": "kubernetes-tpu-scheduler"},
            }
        ]
        for tid, tname in sorted(names.items()):
            meta.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": 1,
                    "tid": tid,
                    "args": {"name": tname},
                }
            )
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": dropped},
        }

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "mode": "blackbox" if self._ring_mode else "capture",
                "events": len(self._trace_events),
                "dropped": self._trace_dropped,
                "evicted": self._trace_evicted,
                "overhead_s": self._overhead_s,
                "max_events": (
                    self._ring_cap if self._ring_mode else self.max_events
                ),
            }


class _Span:
    __slots__ = ("tr", "name", "cat", "args", "_t0")

    def __init__(self, tr: Tracer, name: str, cat: str, args: dict):
        self.tr = tr
        self.name = name
        self.cat = cat
        self.args = args

    def __enter__(self):
        self._t0 = self.tr.now()
        return self

    def __exit__(self, *exc):
        if self.tr.enabled:
            self.tr._record_x(
                self.name, self._t0, self.tr.now(), self.cat, self.args
            )
        return False


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
