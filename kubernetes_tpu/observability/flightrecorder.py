"""Per-pod flight recorder: a bounded ring of pod lifecycle events.

The per-pod diagnosis surface the reference scheduler spreads over
Diagnosis/NodeToStatusMap, FailedScheduling events, and scheduler logs,
collapsed into one queryable ring buffer: every pod's journey through the
queue and the batched hot loop leaves a breadcrumb trail —

    enqueue      informer add reached the scheduling queue (or gated)
    pop          popped into a gang batch (attempt N)
    assumed      scheduling cycle chose a node (assume + reserve/permit ok)
    verdict      an extension point rejected the pod (plugin + code)
    unschedulable  filter failure with the per-plugin diagnosis counts
    nominated    PostFilter nominated a node (preemption in flight)
    requeue      parked (backoff/unschedulable) after a failure
    bound        binding cycle wrote the binding
    bind_failed  binding cycle failed (unwound + requeued)

Querying by uid answers "where is pod X and why" without logs or replay;
the /debug/flightrecorder endpoint serves it over HTTP.

Cost model: one lock + one deque append per event; events are plain tuples.
The ring is bounded (``capacity``) — overflow evicts the OLDEST event and
counts it, so memory is fixed and recent history always wins.  ``enabled``
gates every producer site with a plain attribute read.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Lock-discipline registry (kubernetes_tpu.analysis): the scheduling loop,
# binding workers, informer threads, and HTTP handlers all touch the ring.
_KTPU_GUARDED = {
    "FlightRecorder": {
        "lock": "_mu",
        "guards": {"_ring": None, "_fr_seq": None, "_fr_evicted": None},
    },
}

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=time.time):
        self.enabled = True
        self.capacity = max(int(capacity), 1)
        self._clock = clock
        self._mu = threading.Lock()
        self._ring: deque = deque()
        self._fr_seq = 0
        self._fr_evicted = 0

    def record(self, uid: str, kind: str, detail: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        now = self._clock()
        with self._mu:
            self._fr_seq += 1
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self._fr_evicted += 1
            self._ring.append((self._fr_seq, now, uid, kind, detail))

    def record_many(self, events) -> None:
        """Bulk-path record: one clock read + one lock acquisition for a
        whole run of ``(uid, kind, detail)`` events — the per-event cost
        of the hot bulk paths (pop/assume/bind runs) is a deque append.
        Events share one timestamp; sequence numbers stay per-event."""
        if not self.enabled:
            return
        now = self._clock()
        ring = self._ring
        cap = self.capacity
        with self._mu:
            seq = self._fr_seq
            evicted = self._fr_evicted
            for uid, kind, detail in events:
                seq += 1
                if len(ring) >= cap:
                    ring.popleft()
                    evicted += 1
                ring.append((seq, now, uid, kind, detail))
            self._fr_seq = seq
            self._fr_evicted = evicted

    # -- queries -------------------------------------------------------------

    def events_for(self, uid: str) -> List[dict]:
        """All retained events for one pod uid, oldest first."""
        with self._mu:
            hits = [e for e in self._ring if e[2] == uid]
        return [self._as_dict(e) for e in hits]

    def tail(self, n: int = 100) -> List[dict]:
        with self._mu:
            hits = list(self._ring)[-n:]
        return [self._as_dict(e) for e in hits]

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "events": len(self._ring),
                "capacity": self.capacity,
                "recorded_total": self._fr_seq,
                "evicted_total": self._fr_evicted,
            }

    @staticmethod
    def _as_dict(e) -> dict:
        seq, ts, uid, kind, detail = e
        out = {"seq": seq, "ts": ts, "pod": uid, "kind": kind}
        if detail:
            out["detail"] = detail
        return out
