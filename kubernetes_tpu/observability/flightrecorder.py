"""Per-pod flight recorder: a bounded ring of pod lifecycle events.

The per-pod diagnosis surface the reference scheduler spreads over
Diagnosis/NodeToStatusMap, FailedScheduling events, and scheduler logs,
collapsed into one queryable ring buffer: every pod's journey through the
queue and the batched hot loop leaves a breadcrumb trail —

    enqueue      informer add reached the scheduling queue (or gated)
    pop          popped into a gang batch (attempt N)
    assumed      scheduling cycle chose a node (assume + reserve/permit ok)
    verdict      an extension point rejected the pod (plugin + code)
    unschedulable  filter failure with the per-plugin diagnosis counts
    nominated    PostFilter nominated a node (preemption in flight)
    requeue      parked (backoff/unschedulable) after a failure
    bind_start   binding worker picked the pod up (sink write imminent)
    bound        binding cycle wrote the binding
    bind_failed  binding cycle failed (unwound + requeued)

Querying by uid answers "where is pod X and why" without logs or replay;
the /debug/flightrecorder endpoint serves it over HTTP.

Every event is stamped with a (wall, monotonic) clock PAIR: durations
(the SLO tier's per-stage attribution, observability/slo.py) derive from
the monotonic stamp so a wall-clock jump — NTP step, chaos clock-skew
scenario — can never skew a latency; the wall stamp stays for display.

Cost model: one lock + one deque append per event; events are plain tuples.
The ring is bounded (``capacity``) — overflow evicts the OLDEST event and
counts it, so memory is fixed and recent history always wins.  ``enabled``
gates every producer site with a plain attribute read.  An optional
``sink`` (the SLO evaluator's ``ingest_async``) receives ``(mono,
events)`` after the ring append — the shared monotonic stamp plus the
ORIGINAL ``(uid, kind, detail)`` tuples, so the hot path never rebuilds
per-event tuples; one extra attribute check when unset.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Lock-discipline registry (kubernetes_tpu.analysis): the scheduling loop,
# binding workers, informer threads, and HTTP handlers all touch the ring.
_KTPU_GUARDED = {
    "FlightRecorder": {
        "lock": "_mu",
        "guards": {"_ring": None, "_fr_seq": None, "_fr_evicted": None},
    },
}

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    def __init__(
        self,
        capacity: int = DEFAULT_CAPACITY,
        clock=time.time,
        mono_clock=time.monotonic,
    ):
        self.enabled = True
        self.capacity = max(int(capacity), 1)
        self._clock = clock
        self._mono = mono_clock
        self._mu = threading.Lock()
        self._ring: deque = deque()
        self._fr_seq = 0
        self._fr_evicted = 0
        # optional streaming consumer
        # (observability.slo.SLOEvaluator.ingest_async): called with
        # (mono, [(uid, kind, detail), ...]) AFTER the ring append, so
        # per-pod attribution joins the same breadcrumbs the ring retains
        # without a second set of producer sites.  The sink does its own
        # locking; per-uid causal order holds because consecutive lifecycle
        # stages of one pod are separated by Scheduler._mu acquisitions.
        self.sink = None

    def record(self, uid: str, kind: str, detail: Optional[dict] = None) -> None:
        if not self.enabled:
            return
        wall = self._clock()
        mono = self._mono()
        with self._mu:
            self._fr_seq += 1
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self._fr_evicted += 1
            self._ring.append((self._fr_seq, wall, mono, uid, kind, detail))
        sink = self.sink
        if sink is not None:
            sink(mono, ((uid, kind, detail),))

    def record_many(self, events) -> None:
        """Bulk-path record: one clock read + one lock acquisition for a
        whole run of ``(uid, kind, detail)`` events — the per-event cost
        of the hot bulk paths (pop/assume/bind runs) is a deque append.
        Events share one timestamp; sequence numbers stay per-event."""
        if not self.enabled:
            return
        wall = self._clock()
        mono = self._mono()
        sink = self.sink
        if sink is not None:
            events = list(events)
            if not events:  # caller's generator yielded nothing
                return
        ring = self._ring
        cap = self.capacity
        with self._mu:
            seq = self._fr_seq
            evicted = self._fr_evicted
            for uid, kind, detail in events:
                seq += 1
                if len(ring) >= cap:
                    ring.popleft()
                    evicted += 1
                ring.append((seq, wall, mono, uid, kind, detail))
            self._fr_seq = seq
            self._fr_evicted = evicted
        if sink is not None:
            sink(mono, events)

    # -- queries -------------------------------------------------------------

    def events_for(self, uid: str) -> List[dict]:
        """All retained events for one pod uid, oldest first."""
        with self._mu:
            hits = [e for e in self._ring if e[3] == uid]
        return [self._as_dict(e) for e in hits]

    def tail(self, n: int = 100) -> List[dict]:
        with self._mu:
            hits = list(self._ring)[-n:]
        return [self._as_dict(e) for e in hits]

    def stats(self) -> dict:
        with self._mu:
            return {
                "enabled": self.enabled,
                "events": len(self._ring),
                "capacity": self.capacity,
                "recorded_total": self._fr_seq,
                "evicted_total": self._fr_evicted,
            }

    @staticmethod
    def _as_dict(e) -> dict:
        seq, wall, mono, uid, kind, detail = e
        out = {"seq": seq, "ts": wall, "mono": mono, "pod": uid, "kind": kind}
        if detail:
            out["detail"] = detail
        return out
