"""Control-plane observability: end-to-end pipeline tracing, per-hop lag
attribution, and the snapshot-staleness sentinel.

Every tier built before this one (tracer, flight recorder, SLO
attribution, dispatch ledger) watches the scheduler and device side; the
L0–L4 watch path — `client/api_server.py`'s watch caches, the
`client/client.py` reflectors, the informer handlers, the queue, the
bind sink — was dark.  This module lights it up as ONE monitor with
three surfaces:

  * CAUSAL PIPELINE STITCHING — every pod carries a chain of
    (resourceVersion, monotonic ts) breadcrumbs across

        api_write → watch_delivery → informer_handler → enqueue
                  → pop → assumed → bind_start → bound

    The first three hops are stamped by the serving/client tier through
    ``note_api_write`` / ``note_delivery`` / ``note_pod_handled``; the
    scheduler-side hops ride the PR 7 flight-recorder breadcrumb stream
    (the monitor chains in front of the SLO evaluator's sink), so the
    hot loop grows ZERO new producer sites.  A chain closes on the
    ``bound`` breadcrumb: consecutive stamps become named hop durations
    (the waterfall ``/debug/pipeline?pod=`` serves), aggregate into the
    ``scheduler_tpu_pipeline_hop_seconds`` histogram, and — when the
    tracer is capturing — land as spans on a synthetic "controlplane"
    track, ``lt``-stamped from the attached chaos journal so a replay
    reconstructs byte-identical chains.

  * PER-REQUEST APISERVER ACCOUNTING — ``attach_api_server`` wires the
    HTTP handler's verb/resource/status latencies, watch-cache window
    occupancy, compaction/410 counters, and per-watcher fanout lag into
    the scheduler's registry, synced on scrape (the serving hot path
    never touches a registry lock).

  * SNAPSHOT-STALENESS SENTINEL — ``scheduler_tpu_snapshot_staleness_
    seconds``: at each batch dispatch, the gap between the newest event
    the watch stream DELIVERED and the newest event the informer
    handlers APPLIED.  A sustained breach (N consecutive dispatches over
    the threshold) files a ``snapshot_staleness`` verdict through
    ``SLOEvaluator.external_breach`` — the same freeze→dump→re-arm
    black-box machinery objective breaches and kernel regressions use.

Cost model: the monitor is None until ``Scheduler.install_controlplane``
— every producer site is one attribute read + None check when off.
Installed, the flight-recorder sink defers: it appends the raw batch
(plus a logical-time stamp) to a deque and returns, so the scheduling
loop and bind workers never pay for chain stitching.  Stamping, hop
bucketing, and span emission run in ``_drain_pending`` on the next read
(scrape, /debug/pipeline, snapshot) — or inline only past the
``max_pending_batches`` backlog bound.
"""

from __future__ import annotations

import bisect
import threading
import time
import weakref
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from kubernetes_tpu.metrics import bucket_quantile, wide_duration_buckets

# Lock-discipline registry (kubernetes_tpu.analysis): reflector threads,
# apiserver handler threads, the scheduling loop, binding workers, and
# HTTP debug handlers all stamp into the monitor.  ``_delivered_mono`` /
# ``_applied_mono`` are deliberately NOT guarded — single float stores
# read by the dispatch sentinel (GIL-atomic, the _slo_buf discipline) —
# and neither is ``_pending``: deque append/popleft are GIL-atomic, and
# batch PROCESSING order is serialized by taking _mu around the whole
# popleft loop in ``_drain_pending``.
_KTPU_GUARDED = {
    "ControlPlaneMonitor": {
        "lock": "_mu",
        "guards": {
            "_open": None,
            "_done": None,
            "_hops": None,
            "_hops_synced": None,
            "_rv_stamp": None,
            "_rv_order": None,
            "_req_pending": None,
            "_lag_pending": None,
            "_cache_synced": None,
            "_stale_last": None,
            "_stale_peak": None,
            "_stale_hits": None,
            "_stale_breaches": None,
            "_cp_evicted": None,
        },
    },
}

# The watch-path hops stamped by the serving/client tier (everything
# after ``enqueue`` rides the flight recorder's breadcrumb kinds).
CHAIN_KINDS = (
    "api_write",
    "watch_delivery",
    "informer_handler",
    "enqueue",
    "pop",
    "assumed",
    "bind_start",
    "bound",
    "requeue",
)
_FLIGHT_KINDS = frozenset(
    ("enqueue", "pop", "assumed", "bind_start", "bound", "requeue")
)

# Canonical names for consecutive-stamp segments; an unmapped pair keeps
# the raw "a→b" form so the waterfall still telescopes to the e2e span.
SEGMENTS: Dict[Tuple[str, str], str] = {
    ("api_write", "watch_delivery"): "watch_fanout",
    ("watch_delivery", "informer_handler"): "informer_deliver",
    ("informer_handler", "enqueue"): "handler",
    ("enqueue", "pop"): "queue_wait",
    ("requeue", "pop"): "backoff",
    ("pop", "assumed"): "dispatch",
    ("pop", "requeue"): "dispatch",
    ("assumed", "bind_start"): "commit",
    ("assumed", "requeue"): "commit",
    ("bind_start", "bound"): "bind",
    ("bind_start", "requeue"): "bind",
}


@dataclass
class ControlPlaneConfig:
    # staleness sentinel: breach after `staleness_consecutive` dispatches
    # in a row observe newest-delivered − newest-applied > threshold
    staleness_threshold_s: float = 1.0
    staleness_consecutive: int = 3
    # chain retention: open chains (pods in flight) and closed chains
    # (bound pods the waterfall can still serve) are both LRU-bounded
    max_open_chains: int = 8192
    max_done_chains: int = 1024
    # deferred-ingest backlog bound: the flight-recorder sink only
    # appends raw batches; stitching happens on the next read (scrape,
    # /debug/pipeline, snapshot).  Past this many queued batches the
    # sink drains inline so an unscraped monitor can't grow unbounded.
    max_pending_batches: int = 8192
    # rv → write-timestamp ring per resource (delivery-lag join window)
    rv_window: int = 8192
    track: str = "controlplane"


def _hist_new(nb: int) -> list:
    """[bucket counts (+overflow), sum, n] — the off-registry accumulator
    shape Histogram.merge_counts drains on scrape."""
    return [[0] * (nb + 1), 0.0, 0]


class ControlPlaneMonitor:
    """One monitor per Scheduler (``sched.controlplane``); built by
    ``Scheduler.install_controlplane``."""

    def __init__(
        self,
        config: Optional[ControlPlaneConfig] = None,
        tracer=None,
        slo_getter: Optional[Callable] = None,
        mono_clock=time.monotonic,
    ):
        self.config = config or ControlPlaneConfig()
        self.enabled = True
        self.tracer = tracer
        # chaos-journal logical time (``Journal.now`` while a
        # JournalRecorder is attached; the replayer drives a cursor) —
        # chain stamps carry it so live and replayed chains compare
        # byte-for-byte on (kind, rv, lt)
        self.logical_time: Optional[Callable[[], int]] = None
        self._slo = slo_getter or (lambda: None)
        self._mono = mono_clock
        self._mu = threading.Lock()
        self._buckets = wide_duration_buckets()
        nb = len(self._buckets)
        # uid → [[kind, mono, rv, lt], ...] (insertion-ordered for LRU)
        self._open: "OrderedDict[str, List[list]]" = OrderedDict()
        self._done: "OrderedDict[str, dict]" = OrderedDict()
        self._cp_evicted = 0
        # per-hop durations, CUMULATIVE (hop_summary reads them; scrape
        # syncs deltas against _hops_synced): hop → [counts, sum, n]
        self._hops: Dict[str, list] = {}
        self._hops_synced: Dict[str, list] = {}
        self._hops_nb = nb
        # rv → api-write mono stamp, per resource (bounded join window)
        self._rv_stamp: Dict[str, Dict[int, float]] = {}
        self._rv_order: Dict[str, Deque[int]] = {}
        # apiserver request accounting pending sync:
        # (verb, resource, status) → [counts, sum, n]
        self._req_pending: Dict[Tuple[str, str, str], list] = {}
        # reflector delivery lag pending sync: resource → [counts, sum, n]
        self._lag_pending: Dict[str, list] = {}
        # last-synced snapshots of the api server's monotonic counters
        self._cache_synced: Dict[Tuple[str, str], int] = {}
        # staleness sentinel state (mutated by the scheduling loop only,
        # read by scrape under the same lock)
        self._stale_last = 0.0
        self._stale_peak = 0.0
        self._stale_hits = 0
        self._stale_breaches = 0
        # newest-delivered / newest-applied stamps: plain float stores
        # (GIL-atomic), written per event on the watch/handler paths —
        # a lock there would serialize reflector threads against drains
        self._delivered_mono: Optional[float] = None
        self._applied_mono: Optional[float] = None
        # deferred sink batches, (mono, lt, events) — appended lock-free
        # from the scheduling/bind paths (deque.append is GIL-atomic; lt
        # is captured at sink time so replayed chains stay byte-equal)
        # and stitched into chains under _mu by the next reader
        self._pending: Deque[tuple] = deque()
        self._api = None  # weakref to the attached ApiServer

    # ----- wiring -----------------------------------------------------------

    def attach_api_server(self, server) -> None:
        """In-process wiring: the server stamps api_write breadcrumbs
        through ``server.cp`` and scrape pulls its watch-cache counters."""
        server.cp = self
        self._api = weakref.ref(server)

    def attach_source(self, source) -> None:
        """Hook the RemoteClusterSource's reflectors so every delivered
        watch event stamps the newest-delivered clock + pod chains."""
        for inf in source.informers.values():
            inf._reflector.cp = self

    def make_sink(self, downstream=None):
        """Chain in front of the flight recorder's existing sink (the SLO
        evaluator's ingest_async) — one breadcrumb stream feeds both.

        The sink itself is deliberately almost free: one logical-time
        read plus a deque append per flight-recorder flush.  Chain
        stitching, hop bucketing, and span emission all happen in
        ``_drain_pending`` on the next *read* (scrape, /debug/pipeline,
        snapshot), so the scheduling and bind hot paths never pay for
        them — that is how the full tier stays inside its ≤2% drain
        budget even on a single core."""

        def sink(mono: float, events) -> None:
            if self.enabled:
                pend = self._pending
                pend.append((mono, self._lt(), events))
                if len(pend) > self.config.max_pending_batches:
                    self._drain_pending()
            if downstream is not None:
                downstream(mono, events)

        return sink

    # ----- producer sites (each gated by the caller on .enabled) ------------

    def _lt(self) -> Optional[int]:
        lt = self.logical_time
        if lt is None:
            return None
        try:
            return lt()
        except Exception:  # noqa: BLE001 — journal detached mid-stamp
            return None

    def _stamp_locked(self, uid: str, kind: str, rv, mono, lt) -> None:
        chain = self._open.get(uid)
        if chain is None:
            if len(self._open) >= self.config.max_open_chains:
                self._open.popitem(last=False)
                self._cp_evicted += 1
            chain = self._open[uid] = []
        chain.append([kind, mono, rv, lt])

    def note_api_write(self, res: str, rv: int, obj) -> None:
        """ApiServer._record: the event entered the watch cache at rv."""
        mono = self._mono()
        lt = self._lt()
        uid = getattr(obj, "uid", None)  # pods chain; nodes only join rv
        with self._mu:
            stamps = self._rv_stamp.get(res)
            if stamps is None:
                stamps = self._rv_stamp[res] = {}
                self._rv_order[res] = deque()
            order = self._rv_order[res]
            if len(order) >= self.config.rv_window:
                stamps.pop(order.popleft(), None)
            stamps[rv] = mono
            order.append(rv)
            if uid is not None:
                self._stamp_locked(uid, "api_write", rv, mono, lt)

    def note_delivery(self, res: str, rv: int, obj) -> None:
        """Reflector watch loop: the event reached this process (decoded,
        about to hit the informer handlers)."""
        mono = self._mono()
        lt = self._lt()
        self._delivered_mono = mono
        uid = getattr(obj, "uid", None)
        with self._mu:
            wrote = self._rv_stamp.get(res, {}).get(rv)
            if wrote is not None:
                acc = self._lag_pending.get(res)
                if acc is None:
                    acc = self._lag_pending[res] = _hist_new(self._hops_nb)
                self._observe_locked(acc, mono - wrote)
            if uid is not None:
                self._stamp_locked(uid, "watch_delivery", rv, mono, lt)

    def note_pod_handled(self, uid: str) -> None:
        """Scheduler.on_pod_add (unscheduled branch), under Scheduler._mu:
        the informer handler is applying the pod, enqueue imminent."""
        mono = self._mono()
        lt = self._lt()
        with self._mu:
            self._stamp_locked(uid, "informer_handler", None, mono, lt)

    def note_applied(self) -> None:
        """Entry of every scheduler informer handler (under Scheduler._mu
        — the apply completes before any dispatch can interleave)."""
        self._applied_mono = self._mono()

    def note_request(self, verb: str, res: str, status: int, dur_s: float) -> None:
        """ApiServer handler: one request served."""
        with self._mu:
            key = (verb, res, str(status))
            acc = self._req_pending.get(key)
            if acc is None:
                acc = self._req_pending[key] = _hist_new(self._hops_nb)
            self._observe_locked(acc, dur_s)

    def note_dispatch(self, bid: int) -> None:
        """Scheduling loop, at the batch-id stamp: sample the staleness
        sentinel.  Breach filing happens OUTSIDE the monitor lock — the
        evaluator takes its own lock and dumps to disk."""
        delivered = self._delivered_mono
        applied = self._applied_mono
        staleness = 0.0
        if delivered is not None and applied is not None:
            staleness = max(0.0, delivered - applied)
        cfg = self.config
        record = None
        with self._mu:
            self._stale_last = staleness
            if staleness > self._stale_peak:
                self._stale_peak = staleness
            if staleness > cfg.staleness_threshold_s:
                self._stale_hits += 1
            else:
                self._stale_hits = 0
            if self._stale_hits >= cfg.staleness_consecutive:
                self._stale_hits = 0
                self._stale_breaches += 1
                record = {
                    "objective": "snapshot_staleness",
                    "staleness_s": staleness,
                    "threshold_s": cfg.staleness_threshold_s,
                    "consecutive": cfg.staleness_consecutive,
                    "bid": bid,
                }
        if record is not None:
            slo = self._slo()
            if slo is not None:
                slo.external_breach(record)

    # ----- breadcrumb ingest (the flight-recorder sink chain) ---------------

    def _observe_locked(self, acc: list, dur: float) -> None:
        acc[0][bisect.bisect_left(self._buckets, dur)] += 1
        acc[1] += dur
        acc[2] += 1

    def _drain_pending(self) -> None:
        """Stitch every deferred sink batch into chains.  Runs at the top
        of each read path; batches are popped and processed under one _mu
        acquisition so cross-thread arrival order is preserved."""
        pend = self._pending
        if not pend:
            return
        kinds = _FLIGHT_KINDS
        spans: List[tuple] = []
        with self._mu:
            while True:
                try:
                    mono, lt, events = pend.popleft()
                except IndexError:
                    break
                for uid, kind, _detail in events:
                    if kind not in kinds:
                        continue
                    self._stamp_locked(uid, kind, None, mono, lt)
                    if kind == "bound":
                        spans.extend(self._finalize_locked(uid))
        if spans:
            self._emit_spans(spans)

    def _finalize_locked(self, uid: str) -> List[tuple]:
        chain = self._open.pop(uid, None)
        if not chain:
            return []
        hops = []
        for prev, cur in zip(chain, chain[1:]):
            name = SEGMENTS.get((prev[0], cur[0]), f"{prev[0]}→{cur[0]}")
            dur = cur[1] - prev[1]
            hops.append((name, prev[1], cur[1], dur))
            acc = self._hops.get(name)
            if acc is None:
                acc = self._hops[name] = _hist_new(self._hops_nb)
            self._observe_locked(acc, max(dur, 0.0))
        first_enq = next((e[1] for e in chain if e[0] == "enqueue"), None)
        self._done[uid] = {
            "chain": chain,
            "hops": hops,
            "e2e_s": (chain[-1][1] - first_enq) if first_enq is not None else None,
        }
        if len(self._done) > self.config.max_done_chains:
            self._done.popitem(last=False)
        tr = self.tracer
        if tr is not None and tr.enabled:
            return [(uid, hops, chain[-1][3])]
        return []

    def _emit_spans(self, spans: List[tuple]) -> None:
        """Per-hop spans on the synthetic control-plane track; mono stamps
        convert to the tracer's clock with one offset per flush."""
        tr = self.tracer
        if tr is None:
            return
        off = tr.now() - self._mono()
        track = self.config.track
        for uid, hops, _lt in spans:
            for name, t0, t1, _dur in hops:
                tr.complete_track(
                    track, name, t0 + off, t1 + off, cat="controlplane", pod=uid
                )

    # ----- queries ----------------------------------------------------------

    @staticmethod
    def _chain_dicts(chain: List[list]) -> List[dict]:
        return [
            {"kind": kind, "mono": mono, "rv": rv, "lt": lt}
            for kind, mono, rv, lt in chain
        ]

    def chain_signature(self, uid: str) -> Optional[List[list]]:
        """The replay-comparable projection of a chain: (kind, rv, lt)
        only — no wall/monotonic stamps, so a live recording and its
        journal replay serialize byte-identically."""
        self._drain_pending()
        with self._mu:
            rec = self._done.get(uid)
            chain = rec["chain"] if rec is not None else self._open.get(uid)
            if chain is None:
                return None
            return [[kind, rv, lt] for kind, _mono, rv, lt in chain]

    def pipeline_for(self, uid: str) -> Optional[dict]:
        """The per-hop lag waterfall /debug/pipeline?pod= serves."""
        self._drain_pending()
        with self._mu:
            rec = self._done.get(uid)
            if rec is not None:
                chain, hops, e2e = rec["chain"], rec["hops"], rec["e2e_s"]
                complete = True
            else:
                chain = self._open.get(uid)
                if chain is None:
                    return None
                hops = [
                    (
                        SEGMENTS.get((p[0], c[0]), f"{p[0]}→{c[0]}"),
                        p[1],
                        c[1],
                        c[1] - p[1],
                    )
                    for p, c in zip(chain, chain[1:])
                ]
                e2e, complete = None, False
            out = {
                "pod": uid,
                "complete": complete,
                "e2e_s": e2e,
                "chain": self._chain_dicts(chain),
                "hops": [
                    {"hop": name, "t0": t0, "t1": t1, "duration_s": dur}
                    for name, t0, t1, dur in hops
                ],
            }
        return out

    def hop_summary(self) -> Dict[str, dict]:
        """Aggregate per-hop decomposition over every chain closed so far
        (bench's config16_pipeline_* source; /debug/pipeline default)."""
        self._drain_pending()
        with self._mu:
            rows = {
                name: (list(acc[0]), acc[1], acc[2])
                for name, acc in self._hops.items()
            }
        out = {}
        for name, (counts, sum_, n) in rows.items():
            p50, _ = bucket_quantile(self._buckets, counts, 0.5)
            p99, _ = bucket_quantile(self._buckets, counts, 0.99)
            out[name] = {
                "count": n,
                "sum_s": sum_,
                "mean_s": (sum_ / n) if n else 0.0,
                "p50_s": p50,
                "p99_s": p99,
            }
        return out

    def staleness(self) -> dict:
        with self._mu:
            return {
                "last_s": self._stale_last,
                "peak_s": self._stale_peak,
                "threshold_s": self.config.staleness_threshold_s,
                "breaches": self._stale_breaches,
            }

    def snapshot(self) -> dict:
        """/debug/pipeline without ?pod= — the tier's status surface."""
        self._drain_pending()
        with self._mu:
            open_n, done_n, evicted = (
                len(self._open),
                len(self._done),
                self._cp_evicted,
            )
        return {
            "enabled": self.enabled,
            "open_chains": open_n,
            "done_chains": done_n,
            "evicted_chains": evicted,
            "staleness": self.staleness(),
            "hops": self.hop_summary(),
        }

    # ----- scrape sync ------------------------------------------------------

    def sync_registry(self, prom) -> None:
        """Drain pending accumulators into the scheduler's registry and
        refresh the serving-tier gauges — scrape-time only, so neither
        the apiserver handlers nor the reflectors ever touch a registry
        lock (the PR 7 merge_counts discipline)."""
        self._drain_pending()
        with self._mu:
            hops = []
            for name, acc in self._hops.items():
                prev = self._hops_synced.get(name)
                if prev is None:
                    prev = self._hops_synced[name] = _hist_new(self._hops_nb)
                dn = acc[2] - prev[2]
                if dn:
                    dcounts = [a - b for a, b in zip(acc[0], prev[0])]
                    hops.append((name, (dcounts, acc[1] - prev[1], dn)))
                    prev[0] = list(acc[0])
                    prev[1], prev[2] = acc[1], acc[2]
            reqs = list(self._req_pending.items())
            self._req_pending = {}
            lags = list(self._lag_pending.items())
            self._lag_pending = {}
            stale = self._stale_last
        for name, (counts, sum_, n) in hops:
            prom.pipeline_hop_duration.merge_counts(counts, sum_, n, hop=name)
        for (verb, res, status), (counts, sum_, n) in reqs:
            prom.apiserver_request_duration.merge_counts(
                counts, sum_, n, verb=verb, resource=res, status=status
            )
        for res, (counts, sum_, n) in lags:
            prom.informer_delivery_lag.merge_counts(counts, sum_, n, resource=res)
        prom.snapshot_staleness.set(stale)
        api = self._api() if self._api is not None else None
        if api is None:
            return
        for res, cache in api.caches.items():
            with cache.cond:
                occupancy = len(cache.events)
                head_rv = cache.rv
                compactions = cache.compactions
                gone = cache.gone_total
                watcher_rvs = list(cache.watchers.values())
            prom.watch_window_events.set(occupancy, resource=res)
            lag = max((head_rv - rv for rv in watcher_rvs), default=0)
            prom.watch_fanout_lag.set(lag, resource=res)
            with self._mu:
                dc = compactions - self._cache_synced.get((res, "compact"), 0)
                dg = gone - self._cache_synced.get((res, "gone"), 0)
                self._cache_synced[(res, "compact")] = compactions
                self._cache_synced[(res, "gone")] = gone
            if dc:
                prom.watch_compactions.inc(dc, resource=res)
            if dg:
                prom.watch_relists.inc(dg, resource=res)
        with api._wire_mu:
            wire = dict(api.wire_bytes)
        for (codec, direction), total in wire.items():
            with self._mu:
                key = ("wire", codec, direction)
                d = total - self._cache_synced.get(key, 0)
                self._cache_synced[key] = total
            if d:
                prom.wire_bytes_total.inc(d, codec=codec, direction=direction)
