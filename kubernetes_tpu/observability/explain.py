"""Explain mode: per-node, per-plugin rejection reasons for a pod.

Harvests the per-plugin feasibility masks the batched filter kernel
already computes (``ops.explain.explain_masks`` — the Diagnosis /
NodeToStatusMap surface the hot loop throws away on device) and renders
them as per-node plugin verdicts, merged with host-backed Filter plugin
results (the volumebinding class, which never had kernels) and the
PreFilter result narrowing.

Gating / cost model: nothing here runs on the scheduling hot path.  The
device dispatch and its d2h happen only when an operator (or test) asks
about a specific pod — ``/debug/explain?pod=`` — so the "extra" transfer
is strictly per diagnosed pod.  Unschedulable OUTCOMES get their
aggregate per-plugin counts for free (the reason_counts the kernels
already fetch), recorded in the flight recorder; this module is the
full-resolution drill-down.

``oracle_explain`` produces the same node → rejecting-plugins map from
the serial host oracle (``oracle.pipeline.feasible_nodes``) — the
validation surface: tests assert the kernel masks and the oracle agree
plugin-for-plugin on mixed feasible/infeasible batches.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set

from kubernetes_tpu.api.types import Pod
from kubernetes_tpu.oracle import filters as OF
from kubernetes_tpu.oracle.pipeline import feasible_nodes

# gang.DIAG_KERNELS row order — kernel index → plugin name
DIAG_PLUGINS = (
    "NodeUnschedulable",
    "NodeName",
    "TaintToleration",
    "NodeAffinity",
    "NodePorts",
    "HostFilters",
    "NodeResourcesFit",
    "PodTopologySpread",
    "InterPodAffinity",
)

# oracle reason string → plugin name (exact matches; prefixes below)
_REASON_PLUGIN_EXACT = {
    OF.REASON_NODE_NAME: "NodeName",
    OF.REASON_UNSCHEDULABLE: "NodeUnschedulable",
    OF.REASON_AFFINITY: "NodeAffinity",
    OF.REASON_PORTS: "NodePorts",
    OF.REASON_PODS_LIMIT: "NodeResourcesFit",
    OF.REASON_EXISTING_ANTI: "InterPodAffinity",
    OF.REASON_POD_AFFINITY: "InterPodAffinity",
    OF.REASON_POD_ANTI: "InterPodAffinity",
    OF.REASON_SPREAD: "PodTopologySpread",
    OF.REASON_SPREAD_LABEL: "PodTopologySpread",
}
_REASON_PLUGIN_PREFIX = (
    (OF.REASON_TAINT, "TaintToleration"),
    ("Insufficient ", "NodeResourcesFit"),
)


def reason_to_plugin(reason: str) -> str:
    """Map an oracle Filter reason string to its plugin (kernel) name."""
    hit = _REASON_PLUGIN_EXACT.get(reason)
    if hit is not None:
        return hit
    for prefix, plugin in _REASON_PLUGIN_PREFIX:
        if reason.startswith(prefix):
            return plugin
    return reason  # host-plugin reasons pass through verbatim


def oracle_explain(
    pod: Pod, state, enabled: frozenset
) -> Dict[str, Set[str]]:
    """node name → rejecting-plugin set, from the serial host oracle."""
    fit = feasible_nodes(pod, state, enabled=enabled)
    return {
        node: {reason_to_plugin(r) for r in reasons}
        for node, reasons in fit.reasons.items()
    }


def find_pod(sched, ref: str) -> Optional[Pod]:
    """Resolve a pod by uid, key (ns/name#uid prefix), or bare name across
    the scheduling queue's sub-queues and the cache."""
    with sched._mu:
        pools = sched.queue.pending_pods()
        for pods in pools.values():
            for p in pods:
                if ref in (p.uid, p.name, p.key):
                    return p
        ps = sched.cache.pod_states.get(ref)
        if ps is not None:
            return ps.pod
        for ps in sched.cache.pod_states.values():
            if ref in (ps.pod.name, ps.pod.key):
                return ps.pod
    return None


def explain_pod(
    sched, pod: Pod, max_nodes: int = 500
) -> dict:
    """Per-node, per-plugin verdicts for ``pod`` against the scheduler's
    CURRENT snapshot.  Runs one explain-kernel dispatch + one gated d2h.

    Locking: host-side prep (mirror sync, packing, host-filter sweep)
    holds the scheduler lock for a consistent snapshot; the device
    dispatch and its d2h — including any first-shape XLA compile, which
    can take seconds — run OUTSIDE the lock against the already-built
    immutable arrays, so a debug query never stalls the scheduling loop
    behind a compile.  The hot loop's chained/delta-cached device state is
    never touched (a fresh upload); the shared vocab/mirror ARE touched —
    packing the pod interns its labels exactly as scheduling it would, so
    a never-before-packed label key can widen the key bucket for the next
    drain's repack (the same cost scheduling that pod would pay).

    ``max_nodes`` caps the per-node detail in the result; the summary
    counts always cover every node."""
    import numpy as np

    from kubernetes_tpu.framework.interface import CycleState
    from kubernetes_tpu.ops import explain as ops_explain
    from kubernetes_tpu.ops import gang
    from kubernetes_tpu.ops.common import DeviceBatch, DeviceCluster
    from kubernetes_tpu.snapshot.interner import PAD
    from kubernetes_tpu.snapshot.schema import bucket_cap, pack_pod_batch

    # DIAG_PLUGINS is declared without importing ops (keeps this module
    # importable AST-light); it must mirror the kernel row order exactly
    assert DIAG_PLUGINS == gang.DIAG_KERNELS, (
        "observability.DIAG_PLUGINS diverged from gang.DIAG_KERNELS"
    )
    fwk = sched.profiles.get(
        pod.scheduler_name, next(iter(sched.profiles.values()))
    )
    out: dict = {
        "pod": {"uid": pod.uid, "name": pod.name, "namespace": pod.namespace},
        "profile": fwk.profile_name,
    }
    with sched._mu:
        vocab = sched.mirror.vocab
        for k, v in pod.labels.items():
            vocab.intern_label(k, v)
        sched._repack_mirror()
        nt = sched.mirror.nodes
        if nt is None or not any(nt.valid):
            out["error"] = "no nodes in snapshot"
            return out

        state = CycleState()
        pf_failures = fwk.run_pre_filter(state, [pod])
        s = pf_failures.get(pod.uid)
        if s is not None:
            out["pre_filter"] = {
                "plugin": s.plugin,
                "reasons": list(s.reasons),
            }
            out["nodes"] = {}
            out["summary"] = {s.plugin or "PreFilter": int(np.sum(nt.valid))}
            out["feasible"] = []
            out["n_feasible"] = 0
            return out
        allowed = state.read(("pre_filter_result", pod.uid))

        enabled = fwk.device_enabled()
        pb = pack_pod_batch(
            [pod],
            vocab,
            k_cap=nt.k_cap,
            p_cap=bucket_cap(1, 1),
            namespace_labels=sched.namespace_labels,
        )
        from kubernetes_tpu.oracle.scores import HOSTNAME_LABEL

        tables = dict(
            gang.batch_tables(
                pb.tsc_topo_key,
                pb.aff_topo_key,
                nt.label_vals,
                vocab.label_keys.lookup(HOSTNAME_LABEL),
            )
        )
        tables.pop("d_cap", None)
        has_interpod = bool(
            (pb.aff_kind != PAD).any()
            or (sched.mirror.existing.term_kind != PAD).any()
        )
        has_spread = bool((pb.tsc_topo_key != PAD).any())
        has_ports = bool(
            (pb.want_ppk != PAD).any() or (nt.used_ppk != PAD).any()
        )
        # a fresh device view, independent of the hot loop's chained /
        # delta-cached cluster state (explain never perturbs device caches)
        dc = DeviceCluster.from_host(nt, sched.mirror.existing, vocab)
        db = DeviceBatch.from_host(pb)
        hostname_dev = sched._hostname_dev(vocab)
        v_cap = bucket_cap(len(vocab.label_vals))

        # host-backed Filter plugins (no kernels — judged host-side here,
        # replacing the kernel stack's all-true HostFilters row; needs the
        # shared oracle view, so it stays under the lock)
        host_active = [
            p
            for p in fwk.host_filter_plugins()
            if not state.is_filter_skipped(pod.uid, p.name)
            and p.maybe_relevant(pod)
        ]
        host_verdicts: Dict[str, List[str]] = {}
        if host_active:
            st = sched.oracle_view()
            for name, ns in st.nodes.items():
                hs = fwk.run_host_filters(state, pod, ns)
                if not hs.ok:
                    host_verdicts[name] = [hs.plugin or "HostFilters"]

        names = list(nt.names)
        valid = np.asarray(nt.valid).copy()

    # device dispatch + the gated d2h OUTSIDE the lock: the arrays built
    # above are immutable, and a first-shape XLA compile here must not
    # stall the scheduling loop or informer handlers
    stack, feasible = ops_explain.explain_masks(
        dc,
        db,
        hostname_dev,
        v_cap,
        has_interpod=has_interpod,
        has_spread=has_spread,
        has_ports=has_ports,
        enabled=enabled,
        check_fit="NodeResourcesFit" in enabled,
        **tables,
    )
    # one accounted fetch for both artifacts: explain IS a host round
    # trip, and it must show up in host_roundtrips_total/d2h_bytes_total
    # like every other blocking fetch (Scheduler._d2h choke point)
    fetched = sched._d2h((stack, feasible), kernel="explain.explain_masks")
    stack = np.asarray(fetched[0])[:, 0, :]  # [N_DIAG, N]
    feasible = np.asarray(fetched[1])[0]  # [N]

    allowed_set = frozenset(allowed) if allowed is not None else None
    nodes: Dict[str, List[str]] = {}
    summary: Dict[str, int] = {}
    feasible_names: List[str] = []
    n_rejected = 0
    hf_row = DIAG_PLUGINS.index("HostFilters")
    for ni, name in enumerate(names):
        if ni >= valid.shape[0] or not valid[ni]:
            continue
        rejecting: List[str] = []
        if allowed_set is not None and name not in allowed_set:
            rejecting.append("PreFilterResult")
        for k, plugin in enumerate(DIAG_PLUGINS):
            if k == hf_row:
                continue  # replaced by host_verdicts below
            if not stack[k, ni]:
                rejecting.append(plugin)
        rejecting.extend(host_verdicts.get(name, ()))
        if rejecting:
            n_rejected += 1
            if len(nodes) < max_nodes:
                nodes[name] = rejecting
            for plugin in rejecting:
                summary[plugin] = summary.get(plugin, 0) + 1
        elif feasible[ni]:
            feasible_names.append(name)
    out["nodes"] = nodes
    out["truncated"] = n_rejected > len(nodes)
    out["summary"] = summary
    out["n_feasible"] = len(feasible_names)
    out["feasible"] = feasible_names[:max_nodes]

    # wave-dispatch history: a pod whose speculative placement was
    # invalidated by the wave's conflict-resolution pass carries
    # ``wave_demoted`` flight-recorder events — surface them so the
    # drill-down answers "why did this pod not land where the wave first
    # put it" alongside the per-node verdicts
    demotions = [
        {
            "kind": e.get("detail", {}).get("kind"),
            "term": e.get("detail", {}).get("term"),
            "spec_node": e.get("detail", {}).get("spec_node"),
            "node": e.get("detail", {}).get("node"),
        }
        for e in sched.flight.events_for(pod.uid)
        if e.get("kind") == "wave_demoted"
    ]
    if demotions:
        last = demotions[-1]
        out["wave"] = {
            "demoted": True,
            "reason": "demoted by wave conflict",
            "conflict_kind": last["kind"],
            "conflict_term": last["term"],
            "events": demotions[-8:],
        }
    return out


def explain_whatif(sched, pod: Pod, node_name: str) -> dict:
    """Preemption what-if: which victims would free ``node_name`` for
    ``pod`` — the existing preemption dry-run machinery
    (framework/preemption.Evaluator.select_victims_on_node, the same code
    PostFilter runs) restricted to one node, served read-only: the dry run
    works on a working copy and restores the shared view before returning.

    Returns eligibility, the victim list (what PostFilter would evict
    there, importance-ordered), and the PDB-violation count — "what would
    it take" without nominating anything or touching the queue."""
    from kubernetes_tpu.framework.interface import CycleState

    fwk = sched.profiles.get(
        pod.scheduler_name, next(iter(sched.profiles.values()))
    )
    out: dict = {
        "pod": {"uid": pod.uid, "name": pod.name, "namespace": pod.namespace},
        "node": node_name,
    }
    ev = next(
        (
            p.evaluator
            for p in fwk.post_filter_plugins()
            if hasattr(p, "evaluator")
        ),
        None,
    )
    if ev is None:
        out["error"] = "profile has no preemption evaluator"
        return out
    with sched._mu:
        state = sched.oracle_view()
        if node_name not in state.nodes:
            out["error"] = f"unknown node {node_name!r}"
            return out
        ok, msg = ev.pod_eligible(pod, state)
        out["eligible"] = ok
        if not ok:
            out["reason"] = msg
            return out
        cs = CycleState()
        failures = fwk.run_pre_filter(cs, [pod]) or {}
        s = failures.get(pod.uid)
        if s is not None:
            out["eligible"] = False
            out["reason"] = "; ".join(s.reasons) or "PreFilter rejected"
            return out
        # the same host-filter / extension context preempt() arms, saved
        # and restored so a live PostFilter's state never leaks
        prev = (ev._hf_fwk, ev._hf_state, ev._ext_fwk, ev._ext_state)
        prev_fast = getattr(ev, "_fast_fit", False)
        ev._hf_fwk = ev._hf_state = ev._ext_fwk = ev._ext_state = None
        ev._fast_fit = False  # one node: always run the full fit check
        if fwk.has_host_filters() and fwk.active_host_filters(cs, [pod]):
            ev._hf_fwk, ev._hf_state = fwk, cs
        if fwk.has_pre_filter_extensions():
            ev._ext_fwk, ev._ext_state = fwk, cs
        try:
            victims = ev.select_victims_on_node(
                pod, state, node_name, sched.pdb_lister()
            )
        finally:
            ev._hf_fwk, ev._hf_state, ev._ext_fwk, ev._ext_state = prev
            ev._fast_fit = prev_fast
        lower_uids = [
            p.uid
            for p in state.nodes[node_name].pods
            if p.priority < pod.priority
        ]
        out["lower_priority_pods"] = len(lower_uids)
        if victims is None:
            out["feasible_after_preemption"] = False
            out["reason"] = (
                "no lower-priority pods on the node"
                if not lower_uids
                else "pod still does not fit after removing every "
                "lower-priority pod"
            )
            evict_uids = lower_uids
        else:
            out["feasible_after_preemption"] = True
            out["num_pdb_violations"] = victims.num_pdb_violations
            out["victims"] = [
                {
                    "uid": v.uid,
                    "name": v.name,
                    "namespace": v.namespace,
                    "priority": v.priority,
                }
                for v in victims.pods
            ]
            evict_uids = [v.uid for v in victims.pods]

    # K=1 planner-kernel reroute (outside the lock — device dispatch +
    # compile must not stall the scheduling loop): the single
    # counterfactual and the batched /debug/plan tier share ONE
    # implementation (ops/counterfactual.py), so they cannot drift; the
    # host dry run above stays as the parity reference.
    from kubernetes_tpu.planner.plan import whatif_after_evictions

    try:
        k = whatif_after_evictions(sched, pod, node_name, evict_uids)
    except Exception as e:  # noqa: BLE001 — debug surface must not 500
        k = {"error": str(e)}
    out["kernel"] = k
    if "feasible" in k:
        host_verdict = out["feasible_after_preemption"]
        out["feasible_after_preemption"] = k["feasible"]
        out["host_feasible_after_preemption"] = host_verdict
        out["parity"] = k["feasible"] == host_verdict
    return out
