"""Steady-state SLO tier: streaming latency attribution + breach handling.

The always-on layer the capture-on-demand observability tier (tracer /
flight recorder / explain) deliberately isn't: in production the question
is *are we meeting the SLO, and which stage is burning it* — the
`pod_scheduling_sli_duration_seconds` role the reference scheduler's
operability story is built around, plus the per-stage decomposition the
batched hot loop needs to attack its control-plane ceiling.

Three pieces:

  * **Attribution join.**  The evaluator consumes the flight recorder's
    breadcrumbs (``FlightRecorder.sink = evaluator.ingest_async``) and
    joins each pod's monotonic event stream into per-stage durations:

        queue_wait   enqueue → first pop        (time in the activeQ)
        backoff      requeue → re-pop           (parked after a failure)
        dispatch     pop → assumed              (device dispatch + harvest
                                                 + assume/reserve/permit)
        commit       assumed → bind_start       (commit tail, bind buffer,
                                                 worker pickup)
        bind         bind_start → bound         (sink write + post-bind)
        e2e          enqueue → bound            (the reference's SLI)

    Durations derive ONLY from the monotonic stamps (wall time is
    display-only).  They accumulate in plain bucket arrays and sync as
    deltas into the registry-exposed
    ``scheduler_tpu_slo_stage_duration_seconds{stage=}`` histogram on
    scrape (widened buckets — the +Inf overflow sentinel of
    ``Histogram.percentile`` instead of a silent clamp).

  * **Objectives + burn rate.**  ``SLOConfig.objectives`` declare
    quantile targets over any series (default: p99 bind ≤ 1 s, p99 e2e ≤
    30 s).  Each objective tracks its windowed quantile estimate and its
    error-budget burn rate (bad-fraction ÷ allowed-fraction: 1.0 = burning
    exactly the budget, >1 = on track to exhaust it).

  * **Breach → black-box dump.**  When a windowed quantile exceeds its
    threshold (with ``min_samples``), the evaluator freezes the tracer's
    black-box ring, exports it (optionally to ``dump_dir`` as a
    Perfetto-loadable JSON artifact), records a breach record pointing at
    the artifact, and re-arms the ring — the trace of the bad window
    exists after the incident with nobody at the keyboard.  A cooldown
    bounds dump storms.

Served live at ``GET /debug/slo`` (``SchedulerServer``); installed with
``Scheduler.install_slo``.

Cost model (the ≤~2%-of-a-25k-drain budget; every line here was paid for
by a measurement):

  * producers (``ingest_async``) pay one LOCKLESS deque append per
    flight-recorder batch — the shared mono stamp plus the recorder's
    ORIGINAL event-tuple list.  No per-event tuples, no joining, no
    metric locks, and (critically) no worker wakeup on the hot path: a
    per-event ``Event.set`` is a cross-thread notify + GIL handoff that
    measured ~15% of a 25k drain all by itself.  The worker POLLS.
  * the join itself is VECTORIZED: per-pod open-attempt state lives in
    numpy column arrays indexed by interned uid slots, consecutive
    same-kind breadcrumbs (the shape the bulk paths and the enqueue feed
    produce) coalesce into one gather → mask → ``searchsorted`` +
    ``bincount`` pass, and only short or exotic segments take the scalar
    loop.  A pure-python join measured ~1.3 µs/event — 0.16 s of a
    1.75 s drain, unhideable on a host-dominated loop; the vector path
    leaves only the per-event uid→slot dict lookup.
  * evaluation/rotation/gc are per-drain-cycle and cadence-throttled,
    never per event.

With the tier uninstalled the producer cost is one ``sink is None`` check
inside an already-paying flight-recorder call.  ``ingest`` (synchronous)
joins inline through the scalar loop — the deterministic reference path
the tests reconcile the vector path against.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
import weakref
from bisect import bisect_left
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from kubernetes_tpu.analysis import sanitizer
from kubernetes_tpu.metrics import (
    Histogram,
    bucket_quantile,
    wide_duration_buckets,
)

# Lock-discipline registry (kubernetes_tpu.analysis): ``ingest_async`` is
# called from every flight-recorder producer thread (scheduling loop,
# binding workers, informer handlers) and must stay cheap — it appends to
# a lock-free deque; the join/evaluation state under ``_mu`` is owned by
# the worker (or a synchronous ``ingest`` caller) and read by HTTP
# handlers via ``snapshot``.
_KTPU_GUARDED = {
    "SLOEvaluator": {
        "lock": "_mu",
        "guards": {
            "_slo_idx": None,
            "_slo_uids": None,
            "_slo_st": None,
            "_slo_free": None,
            "_slo_alloc": None,
            "_slo_cum": None,
            "_win_cur": None,
            "_win_prev": None,
            "_slo_objs": None,
            "_slo_rotated_at": None,
            "_slo_last_eval": None,
            "_slo_last_dump": None,
            "_slo_last_gc": None,
            "_slo_breaches": None,
            "_slo_breaches_total": None,
            "_slo_last_trace": None,
            "_slo_dump_seq": None,
            "_slo_synced": None,
        },
    },
    # NOTE: _slo_buf is deliberately NOT here — it is a deque whose
    # append/popleft are atomic under the GIL, so producers never take a
    # lock.  _buf_mu only covers worker startup + the error counter.
    "SLOIngestBuffer": {
        "lock": "_buf_mu",
        "guards": {"_slo_errors": None, "_worker": None},
    },
}

# the joined per-pod stages, plus the end-to-end SLI
STAGES = ("queue_wait", "backoff", "dispatch", "commit", "bind")
SERIES = STAGES + ("e2e",)

# columns of the per-slot open-attempt state matrix (NaN = unset)
_ENQ, _POP, _REQ, _ASSUMED, _BINDSTART, _LAST = range(6)
_NCOL = 6

# breadcrumb kinds the join consumes; everything else (verdict /
# unschedulable / nominated / bind_failed / wave_*) carries diagnosis,
# not stage boundaries — the requeue that follows them closes the attempt
_JOIN_KINDS = frozenset(
    ("enqueue", "pop", "requeue", "assumed", "bind_start", "bound")
)

# segments shorter than this take the scalar loop: numpy setup costs more
# than it saves on tiny gathers
_VEC_MIN = 32

# producers run the join inline once this many events have buffered —
# amortized to a few ms every couple of device batches, it beats a
# concurrent worker whose GIL contention taxes the host loop ~2× the
# join's own CPU
_INLINE_JOIN_EVERY = 8192


@dataclass
class SLOObjective:
    """One objective: '``quantile`` of ``series`` stays ≤ ``threshold_s``'
    — e.g. p99 bind latency ≤ 1 s.  ``series`` is any of SERIES."""

    name: str
    series: str
    quantile: float = 0.99
    threshold_s: float = 1.0

    def validate(self) -> None:
        if self.series not in SERIES:
            raise ValueError(
                f"objective {self.name!r}: unknown series {self.series!r} "
                f"(expected one of {SERIES})"
            )
        if not 0.0 < self.quantile < 1.0:
            raise ValueError(f"objective {self.name!r}: quantile must be in (0,1)")
        if self.threshold_s <= 0:
            raise ValueError(f"objective {self.name!r}: threshold must be positive")


def default_objectives() -> List[SLOObjective]:
    return [
        SLOObjective("bind_p99", "bind", 0.99, 1.0),
        SLOObjective("e2e_p99", "e2e", 0.99, 30.0),
    ]


@dataclass
class SLOConfig:
    objectives: List[SLOObjective] = field(default_factory=default_objectives)
    # rolling evaluation window: quantiles/burn are estimated over the
    # current + previous window generation (covers [window, 2·window])
    window_s: float = 60.0
    # a quantile judged from too few samples is noise, not a breach
    min_samples: int = 100
    # breach evaluation cadence (0 = every ingest batch — tests)
    eval_interval_s: float = 1.0
    # arm the tracer's black-box ring when the tier installs
    blackbox: bool = True
    blackbox_capacity: int = 65_536
    # where breach dumps land; None keeps the frozen export in memory
    # only (served at /debug/slo?action=trace)
    dump_dir: Optional[str] = None
    # minimum seconds between breach dumps (storm bound)
    breach_cooldown_s: float = 30.0
    # per-pod open attempts idle longer than this are swept (pods deleted
    # mid-flight, stranded unschedulables)
    gc_age_s: float = 600.0

    def validate(self) -> None:
        if self.window_s <= 0:
            raise ValueError("window_s must be positive")
        for o in self.objectives:
            o.validate()


class _ObjState:
    """Windowed good/bad accounting + last evaluation results for one
    objective."""

    __slots__ = ("obj", "n_cur", "n_prev", "bad_cur", "bad_prev",
                 "current_s", "burn_rate", "samples", "breached")

    def __init__(self, obj: SLOObjective):
        self.obj = obj
        self.n_cur = 0
        self.n_prev = 0
        self.bad_cur = 0
        self.bad_prev = 0
        self.current_s = 0.0
        self.burn_rate = 0.0
        self.samples = 0
        self.breached = False

    def rotate(self) -> None:
        self.n_prev, self.bad_prev = self.n_cur, self.bad_cur
        self.n_cur = self.bad_cur = 0


def _json_num(v: float) -> Optional[float]:
    """inf → None so /debug/slo stays strict-JSON parseable."""
    if v is None or math.isinf(v) or math.isnan(v):
        return None
    return round(float(v), 6)


def _run_worker(ref: "weakref.ref") -> None:
    """The evaluation-cadence backstop thread: joins idle tails the inline
    threshold never reaches, evaluates objectives, handles breaches.
    Polls — never notified per event (a per-event ``Event.set`` is a
    cross-thread notify whose GIL handoff measured ~15% of a drain).
    Holds only a WEAKREF to its evaluator and re-derefs every cycle, so a
    dropped evaluator gets collected and the thread exits instead of
    pinning the join state for the life of the process."""
    while True:
        ev = ref()
        if ev is None:
            return
        poll = min(max(ev.config.eval_interval_s, 0.05), 1.0)
        ev = None  # don't pin the evaluator across the sleep
        time.sleep(poll)
        ev = ref()
        if ev is None:
            return
        ev._worker_tick()
        ev = None


class SLOEvaluator:
    """The steady-state SLO tier: attribution join + objectives + breach
    handling.  Install with ``Scheduler.install_slo``; feed with
    ``FlightRecorder.sink = evaluator.ingest_async``."""

    def __init__(
        self,
        config: Optional[SLOConfig] = None,
        prom=None,
        tracer=None,
        mono_clock=time.monotonic,
        wall_clock=time.time,
    ):
        self.config = config or SLOConfig()
        self.config.validate()
        self.enabled = True
        self.prom = prom
        self.tracer = tracer
        self._mono = mono_clock
        self._wall = wall_clock
        self._mu = threading.Lock()
        # registry-exposed cumulative histogram: fed by DELTA sync on
        # scrape/snapshot, never per observation
        if prom is not None:
            self._stage_hist: Histogram = prom.slo_stage_duration
        else:
            self._stage_hist = Histogram(
                "scheduler_tpu_slo_stage_duration_seconds",
                label_names=("stage",),
                buckets=wide_duration_buckets(),
            )
        self._bounds = self._stage_hist.buckets  # python list: bisect
        self._bounds_arr = np.asarray(self._bounds)  # searchsorted
        nb = len(self._bounds) + 1
        self._nb = nb
        # per-pod open-attempt state, interned: uid → slot in the [cap, 6]
        # stamp matrix (NaN = unset).  Slots recycle through _slo_free;
        # _slo_uids/_slo_alloc are the reverse map + liveness mask the
        # vectorized gc sweep walks.
        self._slo_idx: Dict[str, int] = {}
        self._slo_uids = np.empty(0, object)  # slot → uid (reverse map)
        self._slo_st = np.empty((0, _NCOL), np.float64)
        self._slo_free: List[int] = []
        self._slo_alloc = np.zeros(0, np.bool_)
        # cumulative per-series accounting: [bucket counts, sum, n]
        self._slo_cum: Dict[str, list] = {
            s: [np.zeros(nb, np.int64), 0.0, 0] for s in SERIES
        }
        # what of _slo_cum has already been merged into the registry hist
        self._slo_synced: Dict[str, list] = {
            s: [np.zeros(nb, np.int64), 0.0, 0] for s in SERIES
        }
        # two-generation rolling window counts per series
        self._win_cur: Dict[str, np.ndarray] = {
            s: np.zeros(nb, np.int64) for s in SERIES
        }
        self._win_prev: Dict[str, np.ndarray] = {
            s: np.zeros(nb, np.int64) for s in SERIES
        }
        self._slo_objs: List[_ObjState] = [
            _ObjState(o) for o in self.config.objectives
        ]
        self._by_series: Dict[str, List[_ObjState]] = {s: [] for s in SERIES}
        for st in self._slo_objs:
            self._by_series[st.obj.series].append(st)
        now = mono_clock()
        self._slo_rotated_at = now
        self._slo_last_eval = now
        self._slo_last_dump = -math.inf
        self._slo_last_gc = now
        self._slo_breaches: List[dict] = []
        self._slo_breaches_total = 0
        self._slo_last_trace: Optional[dict] = None
        self._slo_dump_seq = 0
        # async ingest plumbing: producers append LOCKLESSLY (deque
        # appends are atomic under the GIL) and run the join inline at an
        # amortized threshold; the lazy daemon worker is only the
        # evaluation-cadence backstop.  _buf_mu serializes worker startup
        # and the error counter.
        self._buf_mu = threading.Lock()
        self._slo_buf: deque = deque()
        self._slo_pending = 0  # advisory event count since last inline join
        self._slo_errors = 0
        self._worker: Optional[threading.Thread] = None
        self._sanitize = sanitizer.enabled()

    # -- ingestion ------------------------------------------------------------

    def ingest_async(self, mono, events) -> None:
        """The FlightRecorder.sink entry: ``(shared mono stamp, [(uid,
        kind, detail), ...])`` — ``events`` must be a re-iterable,
        KIND-HOMOGENEOUS sequence (every ``record_many`` site passes one
        literal kind; the recorder hands over its already-built tuples).

        Cost discipline, each clause bought by a measurement: one LOCKLESS
        deque append per call (no per-event tuples); no worker wakeup (a
        per-event ``Event.set`` is a cross-thread notify whose GIL handoff
        measured ~15% of a drain by itself); and the join runs INLINE on
        the producer at an amortized threshold rather than on the worker —
        a concurrently-running join thread competes with the host loop for
        the GIL, and the contention tax measured ~2× the join's own CPU.
        Inline, the cost is the join itself, cache-local, a few ms per
        ~8k events."""
        if not self.enabled:
            return
        if self._sanitize and len(events) > 1:
            kinds = {e[1] for e in events}
            assert len(kinds) == 1, f"mixed-kind sink batch: {kinds}"
        self._slo_buf.append((mono, events))  # deque append: GIL-atomic
        self._slo_pending += len(events)  # advisory (racy is fine)
        if self._worker is None:
            with self._buf_mu:
                if self._worker is None:
                    # the thread holds only a WEAKREF to the evaluator —
                    # a dropped evaluator (scheduler torn down, bench
                    # rep finished) gets collected and its worker exits,
                    # instead of pinning the join state forever
                    self._worker = threading.Thread(
                        target=_run_worker,
                        args=(weakref.ref(self),),
                        name="slo-eval",
                        daemon=True,
                    )
                    self._worker.start()
        if self._slo_pending >= _INLINE_JOIN_EVERY:
            self._slo_pending = 0
            self._drain_join(blocking=False)

    def _drain_join(self, blocking: bool = True) -> None:
        """Pop everything buffered and join it (no objective evaluation —
        that stays on the worker's cadence so a breach's freeze/dump I/O
        never runs on a producer thread).  Safe from any thread.

        The buffer is popped UNDER ``_mu`` so concurrent drains consume
        the stream in one global order (popping first would let two
        threads join their halves out of order — a pod's pop ahead of its
        enqueue).  Inline producer calls pass ``blocking=False``: when
        another thread is already mid-join, stalling a binding worker on
        the lock just to find an empty buffer afterwards measured as a
        producer PILE-UP (every worker that crossed the threshold queued
        up behind one join); leaving the buffer to the in-flight drainer
        (plus the worker-cadence backstop) costs nothing."""
        if not self._mu.acquire(blocking):
            return
        try:
            buf = self._slo_buf
            pairs = []
            while True:
                try:
                    pairs.append(buf.popleft())
                except IndexError:
                    break
            if pairs:
                try:
                    # ktpu: allow(lock-discipline) — _mu IS held: the
                    # non-blocking acquire above returned True (the
                    # checker only models `with` blocks, not try-lock)
                    self._join_pairs_locked(pairs)
                except Exception:
                    # a join bug must not wedge the tier (unjoined
                    # buffer growth, hung flush): drop the cycle,
                    # count it
                    with self._buf_mu:
                        self._slo_errors += 1
        finally:
            self._mu.release()

    def _worker_tick(self) -> None:
        """One worker-cadence pass: join whatever the inline threshold
        hasn't (idle tails), evaluate objectives, handle breaches.  Fully
        exception-proof: a bug anywhere here must not kill the worker
        thread (there is no respawn — _worker is never reset)."""
        if self._slo_buf:
            self._drain_join()
        breach = None
        try:
            with self._mu:
                breach = self._post_join_locked()
        except Exception:
            with self._buf_mu:
                self._slo_errors += 1
        if breach is not None:
            try:
                self._handle_breach(breach)
            except Exception:
                with self._buf_mu:
                    self._slo_errors += 1

    def flush(self) -> None:
        """Read-your-writes barrier (snapshot() takes it before
        reporting): ONE blocking drain pass suffices.  Pops happen under
        ``_mu``, so by the time our acquire succeeds every event buffered
        before this call has been popped — by us or by whichever drainer
        we waited behind — and joined.  Events appended after the call
        are post-flush by definition; NOT waiting for a buffer-empty
        state keeps /debug/slo bounded under sustained load, where the
        buffer refills every few hundred microseconds and an empty-check
        loop would spin forever."""
        self._drain_join()

    def ingest(self, events) -> None:
        """Synchronously join a batch of ``(mono, uid, kind, detail)``
        breadcrumbs through the scalar loop; runs the cadence-throttled
        objective evaluation.  The deterministic reference path — the
        worker's vectorized path is property-tested against it."""
        if not self.enabled:
            return
        breach = None
        with self._mu:
            self._join_scalar_locked(events)
            breach = self._post_join_locked()
        if breach is not None:
            self._handle_breach(breach)

    def _post_join_locked(self) -> Optional[dict]:
        """Cadence-throttled rotation / evaluation / gc — per join CYCLE
        (one worker drain or one sync ingest), never per event."""
        cfg = self.config
        breach = None
        now = self._mono()
        if now - self._slo_rotated_at >= cfg.window_s:
            self._slo_rotated_at = now
            self._rotate_locked()
        if now - self._slo_last_eval >= cfg.eval_interval_s:
            self._slo_last_eval = now
            breach = self._evaluate_locked(now)
        if now - self._slo_last_gc >= cfg.window_s:
            self._slo_last_gc = now
            self._gc_locked(now - cfg.gc_age_s)
        return breach

    # -- the join: slot management -------------------------------------------

    def _grow_locked(self, need: int) -> None:
        old = self._slo_st.shape[0]
        new = max(1024, old * 2, old + need)
        st = np.full((new, _NCOL), np.nan)
        st[:old] = self._slo_st
        self._slo_st = st
        alloc = np.zeros(new, np.bool_)
        alloc[:old] = self._slo_alloc
        self._slo_alloc = alloc
        uids = np.empty(new, object)
        uids[:old] = self._slo_uids
        self._slo_uids = uids
        # LIFO free list: recently-freed (cache-warm) slots reuse first
        self._slo_free.extend(range(new - 1, old - 1, -1))

    def _alloc_slot_locked(self, uid: str) -> int:
        """Claim a slot for ``uid``.  The CALLER resets the row (slots
        recycle with stale stamps): scalar sites nan the row directly,
        vector sites batch one ``st[idxs] = nan`` scatter — a per-alloc
        row broadcast here measured ~half the whole join."""
        free = self._slo_free
        if not free:
            self._grow_locked(1)
        i = free.pop()
        self._slo_idx[uid] = i
        self._slo_uids[i] = uid
        self._slo_alloc[i] = True
        return i

    def _free_slot_locked(self, uid: str, i: int) -> None:
        del self._slo_idx[uid]
        self._slo_uids[i] = None
        self._slo_alloc[i] = False
        self._slo_free.append(i)

    def _gc_locked(self, cut: float) -> None:
        stale = np.nonzero(
            self._slo_alloc & (self._slo_st[:, _LAST] < cut)
        )[0]
        for i in stale:
            i = int(i)
            self._free_slot_locked(self._slo_uids[i], i)

    # -- the join: scalar loop (sync path + short/exotic segments) -----------

    def _join_scalar_locked(self, events) -> None:
        """Reference join over ``(mono, uid, kind, detail)`` tuples.
        NaN-kept per-slot stamps (``x == x`` is the not-NaN test); one
        bisect buckets each observation."""
        idx = self._slo_idx
        obs = self._obs_scalar_locked
        for mono, uid, kind, _detail in events:
            if kind not in _JOIN_KINDS:
                continue
            i = idx.get(uid)
            # NOTE: the state matrix is re-read per event, not hoisted —
            # _alloc_slot_locked may REPLACE self._slo_st when it grows
            if kind == "enqueue":
                if i is None:
                    i = self._alloc_slot_locked(uid)
                row = self._slo_st[i]
                row[:] = np.nan
                row[_ENQ] = mono
                row[_LAST] = mono
                continue
            if i is None:
                if kind == "pop":
                    # joined mid-flight (tier armed after the enqueue):
                    # start partial — later stages still attribute
                    i = self._alloc_slot_locked(uid)
                    self._slo_st[i] = np.nan
                else:
                    continue
            row = self._slo_st[i]
            if kind == "bound":
                start = row[_BINDSTART]
                if start != start:
                    start = row[_ASSUMED]
                if start == start:
                    obs("bind", mono - start)
                enq = row[_ENQ]
                if enq == enq:
                    obs("e2e", mono - enq)
                self._free_slot_locked(uid, i)
            elif kind == "bind_start":
                assumed = row[_ASSUMED]
                if assumed == assumed:
                    obs("commit", mono - assumed)
                row[_BINDSTART] = mono
                row[_LAST] = mono
            elif kind == "assumed":
                pop = row[_POP]
                if pop == pop:
                    obs("dispatch", mono - pop)
                row[_ASSUMED] = mono
                row[_LAST] = mono
            elif kind == "pop":
                req = row[_REQ]
                if req == req:
                    obs("backoff", mono - req)
                    row[_REQ] = np.nan
                elif row[_POP] != row[_POP] and row[_ENQ] == row[_ENQ]:
                    obs("queue_wait", mono - row[_ENQ])
                row[_POP] = mono
                row[_ASSUMED] = np.nan
                row[_BINDSTART] = np.nan
                row[_LAST] = mono
            else:  # requeue
                row[_REQ] = mono
                row[_ASSUMED] = np.nan
                row[_BINDSTART] = np.nan
                row[_LAST] = mono

    def _obs_scalar_locked(self, series: str, dur: float) -> None:
        if dur < 0.0:
            dur = 0.0
        b = bisect_left(self._bounds, dur)
        c = self._slo_cum[series]
        c[0][b] += 1
        c[1] += dur
        c[2] += 1
        self._win_cur[series][b] += 1
        for st in self._by_series[series]:
            st.n_cur += 1
            if dur > st.obj.threshold_s:
                st.bad_cur += 1

    # -- the join: vectorized path (the worker) ------------------------------

    def _join_pairs_locked(self, pairs) -> None:
        """Join ``(mono, [(uid, kind, detail), ...])`` pairs.  Consecutive
        same-kind breadcrumbs — whole bulk pop/assume/bind runs, and the
        enqueue feed's singleton stream — coalesce into one vectorized
        segment; short or exotic runs take the scalar loop.  Per-uid
        event order is preserved (only ADJACENT same-kind events merge,
        and a vector segment never holds two events for one uid: the
        producers interleave a requeue between re-attempts)."""
        segs: List[tuple] = []
        k_cur: Optional[str] = None
        monos: List[float] = []
        uids: List[str] = []
        for mono, events in pairs:
            if not events:  # a bulk site whose generator yielded nothing
                continue
            # bulk pairs are kind-homogeneous (the record_many contract,
            # sanitizer-checked at the sink): one C-speed extend per pair
            # instead of a per-event python pass
            k = events[0][1]
            if k not in _JOIN_KINDS:
                continue
            if k != k_cur:
                k_cur = k
                monos = []
                uids = []
                segs.append((k, monos, uids))
            n = len(events)
            if n == 1:
                monos.append(mono)
                uids.append(events[0][0])
            else:
                monos += [mono] * n
                uids += [e[0] for e in events]
        for k, monos, uids in segs:
            if len(uids) < _VEC_MIN:
                self._join_scalar_locked(
                    [(m, u, k, None) for m, u in zip(monos, uids)]
                )
            else:
                self._vec_segment_locked(k, np.asarray(monos), uids)

    def _lookup_locked(self, uids, create: bool) -> np.ndarray:
        """uid → slot gather; missing uids allocate (create=True: the
        pop-mid-flight case) or stay -1 for the caller to mask off."""
        idx = self._slo_idx
        raw = [idx.get(u, -1) for u in uids]
        if create and -1 in raw:
            created = []
            for j, i in enumerate(raw):
                if i < 0:
                    raw[j] = self._alloc_slot_locked(uids[j])
                    created.append(raw[j])
            # one batched reset for all freshly-claimed (stale) rows
            self._slo_st[np.asarray(created, np.int64)] = np.nan
        return np.asarray(raw, np.int64)

    def _vec_segment_locked(self, kind: str, monos: np.ndarray, uids) -> None:
        # NOTE: self._slo_st is read only AFTER any allocation —
        # _alloc_slot_locked REPLACES the matrix when it grows
        if kind == "enqueue":
            idx = self._slo_idx
            if any(u in idx for u in uids):
                # rare: a uid re-enqueued while still open — reuse slots
                raw = []
                for u in uids:
                    i = idx.get(u)
                    raw.append(self._alloc_slot_locked(u) if i is None else i)
                idxs = np.asarray(raw, np.int64)
            else:
                # bulk-alloc fast path (the feed stream): slice the free
                # list, one dict.update, vectorized reverse-map writes
                m = len(uids)
                free = self._slo_free
                if len(free) < m:
                    self._grow_locked(m - len(free))
                    free = self._slo_free
                take = free[len(free) - m:]
                del free[len(free) - m:]
                idx.update(zip(uids, take))
                idxs = np.asarray(take, np.int64)
                self._slo_uids[idxs] = uids
                self._slo_alloc[idxs] = True
            st = self._slo_st
            st[idxs] = np.nan
            st[idxs, _ENQ] = monos
            st[idxs, _LAST] = monos
            return
        if kind == "pop":
            idxs = self._lookup_locked(uids, create=True)
            st = self._slo_st
            req = st[idxs, _REQ]
            has_req = req == req
            if has_req.any():
                self._obs_vec_locked("backoff", monos[has_req] - req[has_req])
            enq = st[idxs, _ENQ]
            pop = st[idxs, _POP]
            first = ~has_req & (pop != pop) & (enq == enq)
            if first.any():
                self._obs_vec_locked("queue_wait", monos[first] - enq[first])
            st[idxs, _POP] = monos
            st[idxs, _REQ] = np.nan
            st[idxs, _ASSUMED] = np.nan
            st[idxs, _BINDSTART] = np.nan
            st[idxs, _LAST] = monos
            return
        idxs = self._lookup_locked(uids, create=False)
        st = self._slo_st
        known = idxs >= 0
        if not known.all():
            idxs = idxs[known]
            monos = monos[known]
            if idxs.size == 0:
                return
        if kind == "assumed":
            pop = st[idxs, _POP]
            m = pop == pop
            if m.any():
                self._obs_vec_locked("dispatch", monos[m] - pop[m])
            st[idxs, _ASSUMED] = monos
            st[idxs, _LAST] = monos
        elif kind == "bind_start":
            assumed = st[idxs, _ASSUMED]
            m = assumed == assumed
            if m.any():
                self._obs_vec_locked("commit", monos[m] - assumed[m])
            st[idxs, _BINDSTART] = monos
            st[idxs, _LAST] = monos
        elif kind == "bound":
            bs = st[idxs, _BINDSTART]
            start = np.where(bs == bs, bs, st[idxs, _ASSUMED])
            m = start == start
            if m.any():
                self._obs_vec_locked("bind", monos[m] - start[m])
            enq = st[idxs, _ENQ]
            m = enq == enq
            if m.any():
                self._obs_vec_locked("e2e", monos[m] - enq[m])
            # bulk free: per-uid dict deletes (unavoidable), vectorized
            # liveness/reverse-map writes, one extend onto the free list
            idx_map = self._slo_idx
            if known.all():
                for u in uids:
                    del idx_map[u]
            else:
                for u in self._slo_uids[idxs]:
                    del idx_map[u]
            self._slo_uids[idxs] = None
            self._slo_alloc[idxs] = False
            self._slo_free.extend(idxs.tolist())
        else:  # requeue
            st[idxs, _REQ] = monos
            st[idxs, _ASSUMED] = np.nan
            st[idxs, _BINDSTART] = np.nan
            st[idxs, _LAST] = monos

    def _obs_vec_locked(self, series: str, durs: np.ndarray) -> None:
        durs = np.maximum(durs, 0.0)
        bc = np.bincount(
            np.searchsorted(self._bounds_arr, durs, side="left"),
            minlength=self._nb,
        )
        c = self._slo_cum[series]
        c[0] += bc
        c[1] += float(durs.sum())
        c[2] += durs.size
        self._win_cur[series] += bc
        for st in self._by_series[series]:
            st.n_cur += durs.size
            if durs.size:
                st.bad_cur += int((durs > st.obj.threshold_s).sum())

    # -- windows / registry sync ---------------------------------------------

    def _rotate_locked(self) -> None:
        for s in SERIES:
            self._win_prev[s] = self._win_cur[s]
            self._win_cur[s] = np.zeros(self._nb, np.int64)
        for st in self._slo_objs:
            st.rotate()

    def _sync_registry_locked(self) -> None:
        """Merge the cumulative deltas since the last sync into the
        registry histogram — the scrape-time flush that keeps the hot
        join off the metric locks."""
        for s in SERIES:
            counts, total, n = self._slo_cum[s]
            synced = self._slo_synced[s]
            dn = n - synced[2]
            if not dn:
                continue
            self._stage_hist.merge_counts(
                (counts - synced[0]).tolist(), total - synced[1], dn, stage=s
            )
            self._slo_synced[s] = [counts.copy(), total, n]

    # -- evaluation + breach --------------------------------------------------

    def _evaluate_locked(self, now: float) -> Optional[dict]:
        """Refresh every objective's windowed estimate; return a breach
        record for the first newly-dumpable breach (cooldown-gated)."""
        cfg = self.config
        breach = None
        for st in self._slo_objs:
            o = st.obj
            merged = self._win_cur[o.series] + self._win_prev[o.series]
            est, n = bucket_quantile(self._bounds, merged, o.quantile)
            bad = st.bad_cur + st.bad_prev
            total = st.n_cur + st.n_prev
            budget = 1.0 - o.quantile
            st.current_s = est
            st.samples = n
            st.burn_rate = (
                (bad / total) / budget if total and budget > 0 else 0.0
            )
            st.breached = n >= cfg.min_samples and est > o.threshold_s
            if (
                st.breached
                and breach is None
                and now - self._slo_last_dump >= cfg.breach_cooldown_s
            ):
                self._slo_last_dump = now
                self._slo_dump_seq += 1
                breach = {
                    "objective": o.name,
                    "series": o.series,
                    "quantile": o.quantile,
                    "threshold_s": o.threshold_s,
                    "measured_s": _json_num(est),
                    "window_samples": n,
                    "burn_rate": _json_num(st.burn_rate),
                    "wall_time": self._wall(),
                    "mono": now,
                    "seq": self._slo_dump_seq,
                }
        self._sync_registry_locked()
        return breach

    def _handle_breach(self, record: dict) -> None:
        """Freeze → export → dump → re-arm the black-box ring, then file
        the breach record.  Runs OUTSIDE the evaluator lock: the tracer
        export and the artifact write are slow, and the tracer has its own
        lock."""
        if self.prom is not None:
            self.prom.slo_breaches.inc(objective=record["objective"])
        tr = self.tracer
        frozen = tr.blackbox_freeze() if tr is not None else None
        trace = None
        if frozen is None and tr is not None and self.config.blackbox:
            # breach with the ring unarmed (a manual capture was started
            # and abandoned without its export re-arming it): this
            # breach's trace is lost, but re-arm NOW — idle tracer only,
            # never clobber a manual capture in flight — so the next
            # incident is covered again
            if not tr.enabled:
                tr.blackbox_start(self.config.blackbox_capacity)
        if frozen is not None:
            trace = frozen["trace"]
            record["breach_offset_us"] = frozen["freeze_offset_us"]
            record["trace_events"] = sum(
                1 for e in trace["traceEvents"] if e.get("ph") != "M"
            )
            path = None
            if self.config.dump_dir:
                # an unwritable/full dump_dir must not kill the breach
                # path (or the worker thread): fall back to the in-memory
                # retention the no-dump_dir config gets
                try:
                    os.makedirs(self.config.dump_dir, exist_ok=True)
                    path = os.path.join(
                        self.config.dump_dir,
                        f"blackbox-{record['seq']:04d}-"
                        f"{record['objective']}.json",
                    )
                    with open(path, "w") as f:
                        json.dump(trace, f)
                except OSError:
                    path = None
                    with self._buf_mu:
                        self._slo_errors += 1
            record["trace"] = path
            # resume recording for the next incident
            tr.blackbox_start(self.config.blackbox_capacity)
        with self._mu:
            self._slo_breaches_total += 1
            self._slo_breaches.append(record)
            del self._slo_breaches[:-8]  # keep the recent history bounded
            if trace is not None:
                # retain in memory ONLY when no artifact landed on disk
                # (/debug/slo?action=trace serves it); with a dumped file
                # the copy would pin the whole ring export per process —
                # and a successful dump CLEARS any older failed-dump
                # retention, so action=trace never serves a stale
                # incident's ring alongside a newer breach record
                self._slo_last_trace = (
                    trace if record.get("trace") is None else None
                )

    def external_breach(self, record: dict) -> bool:
        """File a breach raised by ANOTHER observability tier (the
        dispatch ledger's kernel-regression sentinel): same cooldown
        gate, sequence numbering, and freeze→dump→re-arm path as an
        objective breach, so one machinery serves both.  The record must
        carry ``objective`` (the dump filename stem; the sentinel uses
        ``kernel_regression`` plus a ``kernel`` field naming the root).
        Returns False when the cooldown swallowed it."""
        now = self._mono()
        with self._mu:
            if now - self._slo_last_dump < self.config.breach_cooldown_s:
                return False
            self._slo_last_dump = now
            self._slo_dump_seq += 1
            record = dict(
                record,
                seq=self._slo_dump_seq,
                mono=now,
                wall_time=self._wall(),
            )
        self._handle_breach(record)
        return True

    # -- introspection (/debug/slo) ------------------------------------------

    def evaluate(self) -> Optional[dict]:
        """Flush buffered breadcrumbs and force one evaluation pass
        (bypasses the cadence throttle); returns the breach record it
        dumped, if any."""
        self.flush()
        with self._mu:
            breach = self._evaluate_locked(self._mono())
        if breach is not None:
            self._handle_breach(breach)
        return breach

    def last_breach_trace(self) -> Optional[dict]:
        with self._mu:
            return self._slo_last_trace

    def gauge_rows(self) -> List[Tuple[str, float]]:
        """(objective, burn_rate) pairs — the scrape-refresh feed for
        scheduler_tpu_slo_burn_rate (Scheduler.refresh_gauges).  Also
        syncs the stage histogram so /metrics is current."""
        with self._mu:
            self._sync_registry_locked()
            return [(st.obj.name, st.burn_rate) for st in self._slo_objs]

    def snapshot(self) -> dict:
        """The live SLI snapshot /debug/slo serves: per-objective state,
        per-stage decomposition, and the last breach record."""
        self.flush()
        with self._mu:
            self._sync_registry_locked()
            objectives = [
                {
                    "name": st.obj.name,
                    "series": st.obj.series,
                    "quantile": st.obj.quantile,
                    "threshold_s": st.obj.threshold_s,
                    "current_s": _json_num(st.current_s),
                    "burn_rate": _json_num(st.burn_rate),
                    "window_samples": st.samples,
                    "breached": st.breached,
                }
                for st in self._slo_objs
            ]
            breaches_total = self._slo_breaches_total
            last_breach = (
                dict(self._slo_breaches[-1]) if self._slo_breaches else None
            )
            open_attempts = len(self._slo_idx)
            stages = {}
            for s in SERIES:
                counts, total, n = self._slo_cum[s]
                p50, _ = bucket_quantile(self._bounds, counts, 0.5)
                p99, _ = bucket_quantile(self._bounds, counts, 0.99)
                stages[s] = {
                    "count": n,
                    "sum_s": _json_num(total),
                    "p50_s": _json_num(p50) if n else None,
                    "p99_s": _json_num(p99) if n else None,
                }
        out = {
            "enabled": self.enabled,
            "window_s": self.config.window_s,
            "min_samples": self.config.min_samples,
            "objectives": objectives,
            "stages": stages,
            "open_attempts": open_attempts,
            "breaches_total": breaches_total,
            "last_breach": last_breach,
            "ingest_errors": self._slo_errors,
        }
        tr = self.tracer
        if tr is not None:
            out["blackbox"] = tr.stats()
        return out
